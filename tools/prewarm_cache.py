#!/usr/bin/env python
"""AOT compile-cache prewarm: pay the jit compiles BEFORE gang launch.

BENCH_r05 measured compile 62.9 s and wall-to-first-step 125.1 s — over
half the startup wall is XLA compiling programs whose shapes were known
before the gang ever scheduled (ROADMAP item 4 startup latency). This
tool AOT-lowers (``jit(...).lower(...).compile()``) the signatures a
run will execute — the train step, the serving engine's decode-block
program (fp and, with ``--quant``, the int8 twin), every bucket-prefill
program, and the slot insert — with JAX's persistent compilation cache
pointed at a durable directory, so the compiled executables land on
disk without running a single step. ``flow/gang_exec`` then seeds each
member's cache from that directory ahead of member start
(``TPUFLOW_PREWARM_CACHE=<dir>``, rsync-style: only missing entries
copy), so the first real step is a cache LOAD.

Cache keys are HLO + compile options: the prewarmed entries hit only
when the shapes, mesh/sharding, and jax/XLA versions match the run —
prewarm on the same host image with the run's real ``--preset``/
``--batch``/``--seq-len``. A mismatch is harmless (the run compiles
normally); prewarm is an optimization, never a launch gate.

Usage::

    python tools/prewarm_cache.py --preset gpt2 --batch 8 --seq-len 512 \
        --cache-dir /shared/prewarm [--no-train] [--no-serve] \
        [--quant] [--spec K] [--slots 8] [--buckets 16,32,64] \
        [--page-size 16] [--pages N] [--max-new 128]

Then launch the gang with ``TPUFLOW_PREWARM_CACHE=/shared/prewarm``.

CPU note: the persistent cache is OFF on CPU by default (the XLA:CPU
AOT loader can abort reloading entries across machine-feature changes —
see ``maybe_enable_compile_cache``); ``--allow-cpu`` force-enables it
for tests and dry runs of this tool.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Runnable from anywhere (the gang launcher's image bake step, a shared
# volume init container): put the repo root on sys.path like the other
# standalone tools.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse(argv):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--preset", default="test",
                   help="GPT2Config.from_preset name (test|gpt2|medium)")
    p.add_argument("--batch", type=int, default=2,
                   help="train-step global batch rows")
    p.add_argument("--seq-len", type=int, default=64,
                   help="train-step sequence length")
    p.add_argument("--cache-dir", default=None,
                   help="cache directory (default: TPUFLOW_COMPILE_CACHE "
                        "resolution / $TPUFLOW_HOME/compile_cache)")
    p.add_argument("--run-dir", default=None,
                   help="run dir for TPUFLOW_COMPILE_CACHE=run keying")
    p.add_argument("--no-train", action="store_true",
                   help="skip the train-step signature")
    p.add_argument("--accum-steps", type=int, default=1,
                   help="also AOT-lower the comm-overlapped FSDP "
                        "accumulation train step at this depth (ISSUE "
                        "10: the per-microbatch reduce-scatter program "
                        "is a DIFFERENT jit key than the plain step — "
                        "without this twin a gang arming "
                        "TPUFLOW_COMM_OVERLAP pays its compile cold)")
    p.add_argument("--no-serve", action="store_true",
                   help="skip the serving decode/prefill/insert signatures")
    p.add_argument("--quant", action="store_true",
                   help="also prewarm the int8 (fused-native) serving twin")
    p.add_argument("--spec", type=int, default=None, metavar="K",
                   help="arm per-request speculative decode at draft "
                        "length K and prewarm the verify-block "
                        "signature(s) (ISSUE 11: a spec-armed gang "
                        "would otherwise pay the verify compile cold)")
    p.add_argument("--page-size", type=int, default=None,
                   help="paged-KV page size (default TPUFLOW_SERVE_"
                        "PAGE_SIZE/16)")
    p.add_argument("--pages", type=int, default=None,
                   help="paged-KV pool size (default slots * n_ctx / "
                        "page_size + 1)")
    p.add_argument("--no-paged", action="store_true",
                   help="prewarm the legacy contiguous slot-row "
                        "signatures instead of the paged ones")
    p.add_argument("--slots", type=int, default=None,
                   help="serving slots (default TPUFLOW_SERVE_SLOTS/8)")
    p.add_argument("--buckets", default=None,
                   help="comma prefill bucket widths (default ladder)")
    p.add_argument("--decode-block", type=int, default=None,
                   help="serving decode-block tokens")
    p.add_argument("--max-new", type=int, default=128,
                   help="capacity headroom the bucket ladder must keep")
    p.add_argument("--allow-cpu", action="store_true",
                   help="force-enable the persistent cache on CPU (tests)")
    return p.parse_args(argv)


def prewarm(args) -> dict:
    # Env staging must precede backend-touching imports/config.
    if args.allow_cpu:
        os.environ["TPUFLOW_COMPILE_CACHE_CPU"] = "1"
    if args.cache_dir:
        os.environ["TPUFLOW_COMPILE_CACHE"] = args.cache_dir

    import jax
    import jax.numpy as jnp

    from tpuflow.dist import maybe_enable_compile_cache

    cache_dir = maybe_enable_compile_cache(args.run_dir)
    if cache_dir is None:
        raise SystemExit(
            "[prewarm] persistent compile cache is disabled here "
            "(TPUFLOW_COMPILE_CACHE=0, or a CPU platform without "
            "--allow-cpu) — nothing to prewarm into"
        )
    # Prewarm wants EVERY program persisted, including ones under the
    # default min-compile-time threshold (the whole point is that the
    # run skips even the small compiles). Old jax without the knobs:
    # the defaults still persist the expensive programs.
    for knob, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, val)
        except (AttributeError, ValueError):
            pass

    from tpuflow.models.gpt2 import GPT2, GPT2Config
    from tpuflow.obs import device as device_mod

    # Device observatory (ISSUE 15): the prewarm pass holds every
    # compiled executable anyway — record the same per-program
    # compile/cost/memory ledger a live run writes, so an operator sees
    # program footprints (and the static HBM budget verdict) BEFORE any
    # gang launches.
    ledger = device_mod.ProgramLedger(source="prewarm")

    t0 = time.monotonic()
    cfg = GPT2Config.from_preset(args.preset, seq_len=args.seq_len)
    model = GPT2(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init(
        rng, jnp.zeros((1, min(8, cfg.n_ctx)), jnp.int32)
    )["params"]
    programs = 0

    if not args.no_train:
        from tpuflow.train.optim import make_optimizer
        from tpuflow.train.step import create_train_state, make_train_step

        state = create_train_state(
            model, rng, jnp.zeros((1, args.seq_len), jnp.int32),
            make_optimizer(3e-4),
        )
        batch = {
            "x": jnp.zeros((args.batch, args.seq_len), jnp.int32),
            "y": jnp.zeros((args.batch, args.seq_len), jnp.int32),
        }
        step = jax.jit(make_train_step(), donate_argnums=(0,))
        # lower().compile() goes through the same backend compile path
        # the hot loop's first step would — the executable lands in the
        # persistent cache without executing anything.
        t_step = time.monotonic()
        ledger.note_compiled(
            "train.step",
            step.lower(state, batch, rng).compile(),
            compile_s=time.monotonic() - t_step,
        )
        programs += 1
        if args.accum_steps > 1:
            # The comm-overlapped accumulation signature (ISSUE 10):
            # FSDP-sharded state + per-microbatch grad reduce-scatter —
            # the program train_gpt runs when accum_steps > 1 and
            # TPUFLOW_COMM_OVERLAP is armed. Mesh/shardings mirror the
            # FSDP leg's defaults on this host's device count; as with
            # every prewarm signature, a mismatch with the real run is
            # harmless (it just compiles normally).
            from tpuflow import dist
            from tpuflow.parallel import create_sharded_state
            from tpuflow.train.step import TrainState
            from tpuflow.train.optim import make_optimizer

            if args.batch % args.accum_steps:
                raise SystemExit(
                    f"[prewarm] --batch {args.batch} does not split "
                    f"into --accum-steps {args.accum_steps} equal "
                    "microbatches"
                )
            mesh = dist.make_mesh({"fsdp": len(jax.devices())})
            tx = make_optimizer(3e-4)

            def init_fn(rng):
                p = model.init(
                    rng, jnp.zeros((1, min(8, cfg.n_ctx)), jnp.int32)
                )["params"]
                return TrainState.create(
                    apply_fn=model.apply, params=p, tx=tx
                )

            with mesh:
                sstate, shardings = create_sharded_state(
                    init_fn, mesh, jax.random.PRNGKey(0), fsdp=True
                )
                ostep = make_train_step(
                    accum_steps=args.accum_steps,
                    grad_shardings=shardings.params,
                    comm_overlap=True,
                )
                bspec = jax.sharding.NamedSharding(
                    mesh,
                    jax.sharding.PartitionSpec(("data", "fsdp"), None),
                )
                obatch = {
                    k: jax.ShapeDtypeStruct(
                        (args.batch, args.seq_len), jnp.int32,
                        sharding=bspec,
                    )
                    for k in ("x", "y")
                }
                t_step = time.monotonic()
                ledger.note_compiled(
                    "train.step.overlap",
                    ostep.lower(sstate, obatch, rng).compile(),
                    compile_s=time.monotonic() - t_step,
                )
                programs += 1
            del sstate

    if not args.no_serve:
        from tpuflow.infer.serve import ServeEngine

        buckets = (
            [int(b) for b in args.buckets.split(",")]
            if args.buckets else None
        )
        engine = ServeEngine(
            model, params,
            max_slots=args.slots,
            buckets=buckets,
            decode_block=args.decode_block,
            quant="fused_native" if args.quant else None,
            paged=False if args.no_paged else None,
            page_size=args.page_size,
            n_pages=args.pages,
            speculative=args.spec,
        )
        # The engine owns its AOT signature list (decode block, verify
        # block, page/slot insert, bucket prefills, int8 twins) so this
        # tool can never drift from the programs the scheduler replays
        # — ISSUE 11 moved the per-signature lowering into
        # ServeEngine.aot_lower when the paged/spec programs landed.
        programs += engine.aot_lower(
            max_new_tokens=args.max_new, ledger=ledger
        )

    # Program ledger + static HBM budget verdict beside the cache (the
    # operator's pre-launch footprint view; budget ratios absent off-TPU
    # where memory_stats is None).
    ledger.budget_check()
    ledger_path = ledger.write(os.path.join(cache_dir, "programs.json"))

    try:
        entries = len([
            f for f in os.listdir(cache_dir)
            if os.path.isfile(os.path.join(cache_dir, f))
        ])
    except OSError:
        entries = 0
    rec = {
        "cache_dir": cache_dir,
        "programs_compiled": programs,
        "cache_entries": entries,
        "wall_s": round(time.monotonic() - t0, 2),
        "backend": jax.default_backend(),
        "preset": args.preset,
    }
    if ledger_path:
        rec["programs_ledger_path"] = ledger_path
        if ledger.budget:
            rec["resident_bytes"] = ledger.budget.get("resident_bytes")
    return rec


def main(argv=None) -> int:
    rec = prewarm(_parse(argv if argv is not None else sys.argv[1:]))
    print(json.dumps(rec))
    print(
        f"[prewarm] {rec['programs_compiled']} programs -> "
        f"{rec['cache_entries']} cache entries in {rec['cache_dir']} "
        f"({rec['wall_s']}s); launch gangs with "
        f"TPUFLOW_PREWARM_CACHE={rec['cache_dir']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
