#!/usr/bin/env python
"""Run the two medium acceptance configs FOR REAL (VERDICT r2 #8) and
write a committed run log.

- Config 5 shape: GPT-2-medium (355M params) through flows/gpt_flow.py —
  fresh run with a sharded checkpoint, then a --from-run full-state
  resume. Proves the medium preset compiles, checkpoints, and resumes at
  its real parameter count (CPU, tiny step counts: this is a
  compile/checkpoint/resume proof, not a throughput claim).
- Config 2 shape: ResNet-50 (25.6M params) + ImageNet-shaped data
  (224x224x3, 1000 classes) through flows/train_flow.py, gang of
  TPUFLOW_N_PARALLEL processes, then a --from-run warm start.

Writes MEDIUM_RUNS.md at the repo root with wall-clocks, parameter
counts, and checkpoint bytes, then leaves committing to the caller.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _default_home() -> str:
    """tmpfs when it can actually hold the runs (~7 GiB of GPT-2-medium
    sharded state at peak, fresh + resume dirs coexisting), else /tmp —
    containers commonly mount a 64 MiB /dev/shm."""
    try:
        import shutil as _sh

        if os.path.isdir("/dev/shm") and (
            _sh.disk_usage("/dev/shm").free > 24 * 2**30
        ):
            return "/dev/shm/tpuflow_medium_runs"
    except OSError:
        pass
    return "/tmp/tpuflow_medium_runs"


HOME = os.environ.get("MEDIUM_RUNS_HOME", _default_home())


def run(cmd: list[str], env: dict, timeout: float = 3600):
    t0 = time.monotonic()
    p = subprocess.run(
        cmd, env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout,
    )
    dt = time.monotonic() - t0
    sys.stderr.write(p.stdout[-2000:] + p.stderr[-2000:])
    if p.returncode != 0:
        raise RuntimeError(f"{' '.join(cmd)} failed rc={p.returncode}")
    return dt, p.stdout + p.stderr


def du_bytes(path: str) -> int:
    total = 0
    for root, _, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def newest_ckpt_dir(flow: str) -> str:
    base = os.path.join(HOME, "flows", flow)
    runs = sorted(
        (d for d in os.listdir(base) if d.isdigit()), key=int
    )
    return os.path.join(base, runs[-1], "tpu_storage")


def main() -> int:
    import shutil

    shutil.rmtree(HOME, ignore_errors=True)
    env = {
        **os.environ,
        "TPUFLOW_FORCE_CPU": "1",
        "TPUFLOW_HOME": HOME,
        "TPUFLOW_DATA_DIR": "/tmp/tpuflow_medium_data",
    }
    lines = [
        "# Medium-config run log (committed evidence for VERDICT r2 #8)",
        "",
        f"Host: 1-core dev VM, CPU platform (8 virtual devices), "
        f"{time.strftime('%Y-%m-%d')}. Tiny step counts on purpose: these "
        "runs prove compile + sharded checkpoint + resume at REAL "
        "parameter counts, not throughput.",
        "",
    ]

    # ---- GPT-2-medium (355M), FSDP mesh data=2 x fsdp=4 ----------------
    gpt_cmd = [
        sys.executable, "flows/gpt_flow.py", "run",
        "--preset", "medium", "--epochs", "1", "--steps-per-epoch", "1",
        "--batch-size", "8", "--seq-len", "64",
        "--data-axis", "2", "--fsdp-axis", "4",
    ]
    try:
        dt, out = run(gpt_cmd, env, timeout=5400)
        m = re.search(r"run (TpuGptTrain/\d+) succeeded", out)
        if not m:
            raise RuntimeError("gpt medium run did not succeed")
        gpt_run = m.group(1)
        ppl = re.search(r"val_loss=([0-9.]+)", out)
        ck = newest_ckpt_dir("TpuGptTrain")
        ck_bytes = du_bytes(ck)
        lines += [
            "## GPT-2-medium (acceptance config 5 shape, CPU)",
            "",
            f"- fresh run `{' '.join(gpt_cmd[1:])}` -> {gpt_run}:",
            f"  wall {dt:.0f}s, val_loss {ppl.group(1) if ppl else 'n/a'}",
            f"- checkpoint: {ck_bytes / 2**30:.2f} GiB on disk "
            "(355M params f32 + adamw moments, fully sharded over the "
            "2x4 data/fsdp mesh)",
        ]
        dt2, out2 = run(
            [sys.executable, "flows/gpt_flow.py", "run",
             "--preset", "medium", "--epochs", "1", "--steps-per-epoch", "1",
             "--batch-size", "8", "--seq-len", "64",
             "--data-axis", "2", "--fsdp-axis", "4",
             "--from-run", gpt_run, "--decay-steps", "4"],
            env, timeout=5400,
        )
        if "full sharded state restored" not in out2:
            raise RuntimeError("gpt medium resume did not restore full state")
        m2 = re.search(r"run (TpuGptTrain/\d+) succeeded", out2)
        if not m2:
            raise RuntimeError("gpt medium resume run did not succeed")
        # Phase breakdown (VERDICT r3 weak #3): the resume must cost about a
        # fresh run plus the measured restore, not 2x — the r3 gap came from
        # materializing the init just to overwrite it (fixed:
        # create_sharded_state(materialize=False)) plus the background
        # restore-prewarm stealing the 1 core (fixed: prewarm parking).
        phases = re.findall(r"\[gpt\] (state \w+|full sharded state restored):"
                            r" ([0-9.]+)s", out2)
        phase_txt = ", ".join(f"{name} {secs}s" for name, secs in phases)
        restore_s = next(
            (float(s) for name, s in phases
             if name == "full sharded state restored"), 0.0
        )
        # REGRESSION GATE, not just a log line: a resume costing beyond the
        # fresh wall + measured restore + the box's documented ±20% wobble is
        # the r3 bug pattern (init materialized then overwritten / prewarm
        # stealing the core) — fail the evidence run instead of writing the
        # regression up as noise.
        if dt2 > dt * 1.2 + restore_s:
            raise RuntimeError(
                f"resume wall {dt2:.0f}s exceeds fresh {dt:.0f}s * 1.2 + "
                f"restore {restore_s:.1f}s — restore-path regression"
            )
        lines += [
            f"- `--from-run {gpt_run}` resume -> {m2.group(1)}: wall {dt2:.0f}s, "
            "full sharded state (step + params + opt_state) restored"
            + (f" ({phase_txt})" if phase_txt else ""),
            f"- resume overhead vs fresh: {dt2 - dt:+.0f}s against a measured "
            f"restore of {restore_s:.1f}s — gated at fresh*1.2+restore (this "
            "box wobbles ±20% run to run); r3 measured +103s (2x) before the "
            "abstract-template resume + prewarm-parking fixes",
            "",
        ]
    finally:
        # The GPT run dirs hold ~3.4 GiB of sharded state each on
        # tmpfs — reclaim even when the regression gate (or a
        # failed run) raises, so /dev/shm isn't left exhausted for
        # the investigating rerun.
        shutil.rmtree(os.path.join(HOME, "flows", "TpuGptTrain"),
                      ignore_errors=True)

    # ---- ResNet-50 / ImageNet-shaped (config 2), 2-process gang --------
    env_rn = {
        **env,
        "TPUFLOW_N_PARALLEL": "2",
        "TPUFLOW_GANG_LOCAL_DEVICES": "4",
        "TPUFLOW_SYNTH_TRAIN_N": "16",
        "TPUFLOW_SYNTH_TEST_N": "8",
    }
    rn_cmd = [
        sys.executable, "flows/train_flow.py", "run",
        "--model", "resnet50", "--dataset", "imagenet_synth",
        "--epochs", "1", "--batch-size", "8",
    ]
    dt3, out3 = run(rn_cmd, env_rn, timeout=5400)
    m3 = re.search(r"run (TpuTrain/\d+) succeeded", out3)
    if not m3:
        raise RuntimeError("resnet50 run did not succeed")
    rn_run = m3.group(1)
    ck_rn = newest_ckpt_dir("TpuTrain")
    lines += [
        "## ResNet-50 / ImageNet-shaped (acceptance config 2 shape, CPU)",
        "",
        f"- fresh run `{' '.join(rn_cmd[1:])}` (2-process gang x 4 devices, "
        f"batch 224x224x3, 1000 classes) -> {rn_run}: wall {dt3:.0f}s",
        f"- checkpoint: {du_bytes(ck_rn) / 2**20:.0f} MiB on disk "
        "(25.6M params + SGD momentum)",
    ]
    dt4, out4 = run(
        rn_cmd + ["--from-run", rn_run], env_rn, timeout=5400
    )
    m4 = re.search(r"run (TpuTrain/\d+) succeeded", out4)
    if not m4:
        raise RuntimeError("resnet50 warm-start run did not succeed")
    # The warm-start print happens inside a gang subprocess (not in the
    # CLI's stdout); check the recorded artifact instead.
    probe = subprocess.run(
        [sys.executable, "-c",
         "from tpuflow.flow import Run; "
         f"print(bool(Run({m4.group(1)!r}).data.warm_started))"],
        env=env_rn, cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    if probe.stdout.strip() != "True":
        raise RuntimeError(
            f"resnet50 resume did not warm-start: {probe.stdout!r} "
            f"{probe.stderr[-500:]!r}"
        )
    lines += [
        f"- `--from-run {rn_run}` warm start -> {m4.group(1)}: "
        f"wall {dt4:.0f}s, best weights restored into the gang",
        "",
    ]
    shutil.rmtree(HOME, ignore_errors=True)  # reclaim tmpfs

    with open(os.path.join(REPO, "MEDIUM_RUNS.md"), "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
