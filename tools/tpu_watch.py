#!/usr/bin/env python
"""Opportunistic on-TPU evidence capturer.

The dev-box TPU is reached through a tunnel that flaps: it can be healthy
for minutes mid-round and dead at round-end snapshot time, which
previously erased all hardware validation. This watcher probes the
default JAX platform aggressively and, on the first healthy TPU probe,
fires the evidence legs in VALUE ORDER, committing ``TPU_EVIDENCE.json``
after each one so a tunnel flap mid-suite cannot strand what was already
measured:

  1. end-to-end flow contract on the chip (tools/e2e_tpu.py: fresh
     train → --from-run resume → eval card) — VERDICT r4's primary
     deliverable, and the only leg with no prior-round record at all.
  2. train child (``bench.py --train-child``): MFU train step → flash
     kernel correctness+sweep → decode/speculative/int8. The child
     merges the evidence ledger incrementally after each sub-leg.
  3. MFU batch/seq/remat sweep (``bench.py --mfu-sweep``).
  4. device-path checkpoint tier (small payload; documents the tunnel,
     now with the staging/IO split).

Run it in the background for a whole working session:

    python tools/tpu_watch.py >> tools/tpu_watch.log 2>&1 &

Env knobs: TPU_WATCH_INTERVAL_S (probe cadence, default 45),
TPU_WATCH_MAX_S (give up after, default 11h),
TPU_WATCH_PROBE_TIMEOUT_S (per-probe hang bound, default 75).

Follow mode (``--follow [url]``): instead of probing for evidence
windows, poll a LIVE run's metrics endpoint (tpuflow.obs.export,
opted in via TPUFLOW_OBS_HTTP_PORT on the run) and print one status
line per poll — step, step rate, tokens/s, rolling MFU, goodput-so-far,
last loss. The url defaults to 127.0.0.1:$TPUFLOW_OBS_HTTP_PORT;
TPU_WATCH_FOLLOW_INTERVAL_S (default 5) sets the cadence.

Fleet mode (``--fleet [target]``): the multi-replica twin (ISSUE 14) —
poll EVERY serving replica's /status through the fleet observatory
(``tpuflow.obs.fleet``) and print a fleet headline line (summed
QPS/queue/tokens-per-s, occupancy-weighted decode utilization,
fleet-exact TTFT/ITL p99 from merged histogram buckets, SLO count)
plus one line per replica with its health score. ``target`` is a
registration dir or comma URL list; omitted, the TPUFLOW_FLEET_*
knobs resolve it. A replica answering garbage (a /status read
mid-write) or nothing at all is marked STALE — the watcher never
crashes on a dying replica; that is the event it exists to report.

Both live modes run the declarative alert engine (ISSUE 16,
``tpuflow.obs.alerts``) over every poll and print ``ALERT ...
FIRED/RESOLVED`` lines on the lifecycle edges — SLO burn rate
(two-window AND-gate), HBM headroom, goodput drop, health collapse,
stale replicas — deduplicated in between, thresholds from the
``TPUFLOW_ALERT_*`` knobs.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Runnable from anywhere, like the other standalone tools: the knob
# registry lives in the package.
sys.path.insert(0, REPO)
from tpuflow.utils import knobs  # noqa: E402
EVIDENCE = os.path.join(REPO, "TPU_EVIDENCE.json")


def _clean_env(extra: dict[str, str] | None = None) -> dict[str, str]:
    """Child env with every platform pin / stale probe verdict removed."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "TPUFLOW_PLATFORM_PROBED",
                     "TPUFLOW_PLATFORM_BACKEND", "TPUFLOW_FORCE_CPU")
    }
    if extra:
        env.update(extra)
    return env


def _drop_probe_cache() -> None:
    home = knobs.raw(
        "TPUFLOW_HOME", os.path.join(os.path.expanduser("~"), ".tpuflow")
    )
    try:
        os.remove(os.path.join(home, "platform_probe.json"))
    except OSError:
        pass


def probe(timeout_s: float) -> str | None:
    """Backend name of the default platform, or None if init fails/hangs."""
    try:
        p = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            timeout=timeout_s, capture_output=True, text=True,
            env=_clean_env(),
        )
    except subprocess.TimeoutExpired:
        return None
    if p.returncode != 0:
        return None
    out = p.stdout.strip().splitlines()
    return out[-1] if out else None


def run_leg(argv: list[str], extra_env: dict[str, str],
            timeout_s: float, label: str) -> bool:
    _drop_probe_cache()
    # Stream the child's output to a per-leg file: a timed-out leg must
    # leave diagnosable breadcrumbs (which phase it died in), not vanish
    # with its captured pipes (that erased the r4 first-window forensics).
    log_path = os.path.join(
        REPO, "tools", f"tpu_watch_leg_{label.replace(' ', '_')}.log"
    )
    with open(log_path, "a") as logf:
        logf.write(f"\n=== {time.strftime('%Y-%m-%dT%H:%M:%SZ')} "
                   f"{label} ===\n")
        logf.flush()
        run_start = logf.tell()  # tail THIS run, not prior appends
        try:
            p = subprocess.run(
                [sys.executable] + argv,
                env=_clean_env(extra_env), timeout=timeout_s,
                stdout=logf, stderr=subprocess.STDOUT,
            )
        except subprocess.TimeoutExpired:
            print(f"[tpu_watch] {label} timed out after {timeout_s:.0f}s "
                  f"(phase log: {log_path})", flush=True)
            return False
    tail = ""
    try:
        with open(log_path) as f:
            f.seek(run_start)
            tail = "\n".join(f.read().splitlines()[-20:])
    except OSError:
        pass
    print(f"[tpu_watch] {label} rc={p.returncode}\n{tail}", flush=True)
    return p.returncode == 0


def evidence_legs() -> dict:
    try:
        with open(EVIDENCE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def leg_fresh(rec: dict, since: float) -> bool:
    """True when this leg is a TPU record captured after ``since`` (unix
    time). A prior session's committed ledger must not satisfy THIS
    session's capture gates — the watcher exists to produce fresh
    evidence, not to re-discover old files."""
    import calendar

    if rec.get("platform") != "tpu":
        return False
    try:
        t = calendar.timegm(time.strptime(rec["recorded_at"],
                                          "%Y-%m-%dT%H:%M:%SZ"))
    except (KeyError, ValueError):
        return False
    # recorded_at and `since` come from the SAME host clock — no skew to
    # absorb. A slack here would let a capture from a session killed
    # moments ago satisfy this session's gates, which is exactly the
    # stale-ledger outcome the gate exists to prevent. int(): the stamp
    # truncates to whole seconds.
    return t >= int(since)


def git_quiescent() -> bool:
    """True when no rebase/merge/cherry-pick is mid-flight (ADVICE r3:
    an unattended commit must not fire into one)."""
    gitdir = os.path.join(REPO, ".git")
    return not any(
        os.path.exists(os.path.join(gitdir, p))
        for p in ("rebase-merge", "rebase-apply", "MERGE_HEAD",
                  "CHERRY_PICK_HEAD")
    )


def commit_evidence(note: str) -> None:
    """Pathspec'd commit of ONLY the evidence file — never picks up files
    another process staged mid-work; skipped entirely mid-rebase/merge
    (the ledger is durable on disk either way; the round-end snapshot
    commits whatever is left)."""
    if not os.path.exists(EVIDENCE):
        return
    if not git_quiescent():
        print("[tpu_watch] repo mid-rebase/merge — deferring evidence "
              "commit (file persisted on disk)", flush=True)
        return
    subprocess.run(["git", "-C", REPO, "add", "TPU_EVIDENCE.json"])
    subprocess.run([
        "git", "-C", REPO, "commit", "-m",
        f"Record on-TPU bench evidence ({note})",
        "-m", "No-Verification-Needed: benchmark data capture only",
        "--", "TPU_EVIDENCE.json",
    ])


def follow(url: str, interval: float, max_s: float) -> int:
    """Poll ``<url>/status`` (the live export endpoint's JSON view) and
    print one babysitter line per poll. Unreachable polls are reported
    and retried — the endpoint appears when the gang's member 0 starts
    training and vanishes across requeues, both routine mid-watch."""
    import urllib.request

    from tpuflow.obs import alerts as alerts_mod

    def fmt(st: dict, key: str, spec: str = "{:.3g}") -> str:
        v = st.get(key)
        return spec.format(v) if isinstance(v, (int, float)) else "-"

    # Alert engine (ISSUE 16): the same declarative rules the /alerts
    # endpoint serves, evaluated over each poll — a babysitter session
    # prints ALERT lines on the fired/resolved edges, deduplicated
    # in between.
    eng = alerts_mod.AlertEngine()
    deadline = time.time() + max_s
    while time.time() < deadline:
        stamp = time.strftime("%H:%M:%S")
        try:
            with urllib.request.urlopen(
                url.rstrip("/") + "/status", timeout=5
            ) as r:
                st = json.loads(r.read().decode())
        except (OSError, ValueError) as e:
            print(
                f"[tpu_watch {stamp}] follow: {url} unreachable ({e}); "
                f"retry in {interval:.0f}s",
                flush=True,
            )
        else:
            hbm = ""
            if "hbm_used_frac" in st or "hbm_used_bytes" in st:
                # Device observatory (ISSUE 15): HBM residency of the
                # busiest local device — keys only exported when the
                # backend reports memory_stats, so the segment simply
                # disappears off-TPU.
                hbm = (
                    f" hbm={fmt(st, 'hbm_used_frac', '{:.2f}')}"
                    f"/{fmt(st, 'hbm_peak_frac', '{:.2f}')}pk"
                    f" ({fmt(st, 'hbm_used_bytes', '{:.2e}')}B)"
                )
            serving = ""
            if "serve_slot_occupancy" in st:
                # A serving process (tpuflow.infer.serve feeds these):
                # the operator's live queue/TTFT/throughput view, plus
                # the engine-time ledger fractions and SLO count
                # (ISSUE 13) — one line answers "is this replica
                # earning its HBM".
                serving = (
                    f" | serve q={st.get('serve_queue_depth', '-')} "
                    f"occ={fmt(st, 'serve_slot_occupancy', '{:.2f}')} "
                    f"tok/s={fmt(st, 'serve_tokens_per_s', '{:.0f}')} "
                    f"ttft50={fmt(st, 'serve_ttft_p50_s', '{:.3f}')}s "
                    f"p99={fmt(st, 'serve_ttft_p99_s', '{:.3f}')}s "
                    f"itl99={fmt(st, 'serve_itl_p99_s', '{:.4f}')}s "
                    f"idle={fmt(st, 'serve_idle_fraction', '{:.2f}')} "
                    f"dec={fmt(st, 'serve_decode_fraction', '{:.2f}')} "
                    f"pre={fmt(st, 'serve_prefill_fraction', '{:.2f}')} "
                    f"slo={st.get('serve_slo_violations', '-')} "
                    f"done={st.get('serve_requests', '-')}"
                )
                if "serve_pages_host" in st or "serve_pages_disk" in st:
                    # Tiered prefix cache (ISSUE 19): lower-tier page
                    # counts, only when a tier is armed on the replica.
                    serving += (
                        f" host={st.get('serve_pages_host', '-')} "
                        f"disk={st.get('serve_pages_disk', '-')}"
                    )
            print(
                f"[tpu_watch {stamp}] step={st.get('step', '-')} "
                f"rate={fmt(st, 'step_rate')}/s "
                f"tok/s={fmt(st, 'tokens_per_s', '{:.0f}')} "
                f"mfu={fmt(st, 'mfu', '{:.4f}')} "
                f"goodput={fmt(st, 'goodput_fraction', '{:.3f}')} "
                f"loss={fmt(st, 'loss', '{:.4f}')} "
                f"up={fmt(st, 'uptime_s', '{:.0f}')}s" + hbm + serving,
                flush=True,
            )
            for t in eng.observe(status=st):
                print(
                    f"[tpu_watch {stamp}] "
                    + alerts_mod.format_transition(t),
                    flush=True,
                )
        time.sleep(interval)
    print("[tpu_watch] follow deadline reached", flush=True)
    return 0


def fleet(target: str | None, interval: float, max_s: float) -> int:
    """Poll the serving fleet and print one headline + one line per
    replica per interval (tpuflow.obs.fleet does discovery, per-replica
    timeout/backoff, staleness marking, and the histogram merge)."""
    from tpuflow.obs import alerts as alerts_mod
    from tpuflow.obs import fleet as fleet_mod

    obsy = fleet_mod.FleetObservatory(target)
    # Fleet-scope alerting (ISSUE 16): burn-rate over the fleet's summed
    # violation counters, HBM headroom of the tightest replica, health
    # collapse, stale replicas.
    eng = alerts_mod.AlertEngine()
    deadline = time.time() + max_s
    while time.time() < deadline:
        stamp = time.strftime("%H:%M:%S")
        snap = obsy.poll()
        if not snap["replicas"]:
            print(
                f"[tpu_watch {stamp}] fleet: no replicas discovered "
                "(pass a registration dir / URL list or set "
                "TPUFLOW_FLEET_REPLICAS); retry in "
                f"{interval:.0f}s",
                flush=True,
            )
        else:
            print(
                f"[tpu_watch {stamp}] "
                + fleet_mod.format_fleet_line(snap["fleet"]),
                flush=True,
            )
            for row in snap["replicas"]:
                print(fleet_mod.format_replica_line(row), flush=True)
            # End-to-end tracing (ISSUE 18): when the merged fleet TTFT
            # histogram carries exemplars, name the concrete trace
            # behind the p99 bucket — `python -m tpuflow.obs trace`
            # turns it into the per-hop breakdown.
            ex = fleet_mod.hist_exemplar(
                snap["fleet"].get("ttft_hist"), 0.99
            )
            if ex is not None:
                print(
                    f"[tpu_watch {stamp}] ttft p99 exemplar: trace "
                    f"{ex} (python -m tpuflow.obs trace <request_id> "
                    "resolves it)",
                    flush=True,
                )
            for t in eng.observe(fleet=snap["fleet"]):
                print(
                    f"[tpu_watch {stamp}] "
                    + alerts_mod.format_transition(t),
                    flush=True,
                )
        time.sleep(interval)
    print("[tpu_watch] fleet deadline reached", flush=True)
    return 0


def main() -> int:
    interval = float(os.environ.get("TPU_WATCH_INTERVAL_S", "45"))
    probe_timeout = float(os.environ.get("TPU_WATCH_PROBE_TIMEOUT_S", "75"))
    started = time.time()
    # Freshness floor for the capture gates. Overriding it to an earlier
    # time lets a RESTARTED watcher (same working session, new process —
    # e.g. after new legs were added to this file) count legs captured
    # since that floor instead of re-spending a healthy window re-proving
    # them.
    since = float(os.environ.get("TPU_WATCH_SINCE", started))
    deadline = started + float(
        os.environ.get("TPU_WATCH_MAX_S", str(11 * 3600))
    )
    bench_py = os.path.join(REPO, "bench.py")
    while time.time() < deadline:
        stamp = time.strftime("%H:%M:%S")
        backend = probe(probe_timeout)
        if backend != "tpu":
            print(f"[tpu_watch {stamp}] probe: {backend!r} — chip not "
                  f"reachable; retry in {interval:.0f}s", flush=True)
            time.sleep(interval)
            continue
        print(f"[tpu_watch {stamp}] TPU healthy — capturing evidence legs",
              flush=True)
        # r5 value order: e2e flow first — the north-star contract end to
        # end ON the chip (fresh train → --from-run resume → eval card;
        # tools/e2e_tpu.py merges the e2e_flow record itself, hardware
        # proof comes from the train task's device-profile header).
        # VERDICT r4 ranked it THE round's deliverable and the repo
        # already holds an r4 train/MFU record, so a medium-length window
        # lands e2e before re-proving train. Crucially, a FAILING leg
        # falls through to the next one — a deterministic e2e failure
        # (code bug, not tunnel) must not starve the cheaper legs for the
        # whole session; only the final exit is gated on all legs being
        # fresh.
        legs = (
            ("e2e_flow", [os.path.join(REPO, "tools", "e2e_tpu.py")],
             {}, 4200, "e2e flow", "end-to-end flow on chip"),
            # train child: MFU step → flash correctness+sweep → decode
            # (speculative numerics + int8 modes with the r5 fixes); the
            # child merges the ledger after EACH sub-leg.
            ("train", [bench_py, "--train-child"],
             {"TPUFLOW_TRAIN_MODE": "tpu"}, 1200, "train child",
             "train/MFU, flash kernels, decode"),
            # MFU batch/seq/remat sweep + warm compile-cache validation.
            ("train_sweep", [bench_py, "--mfu-sweep"],
             {"TPUFLOW_TRAIN_MODE": "tpu"}, 1500, "mfu sweep",
             "mfu sweep"),
            # Device-path checkpoint tier (small payload: documents the
            # tunnel, now with the staging/IO split). Disk tier + overlap
            # stay OFF on watcher runs — the disk tier's cold restore
            # drops the whole machine's page cache (ADVICE r3).
            ("ckpt_device", [bench_py], {
                "TPUFLOW_BENCH_DEVICE": "1",
                "TPUFLOW_BENCH_TRAIN": "0",
                "TPUFLOW_BENCH_GB": "0.125",
                "TPUFLOW_BENCH_DEVICES": "1",
                "TPUFLOW_BENCH_DISK": "0",
                "TPUFLOW_BENCH_OVERLAP": "0",
            }, 1800, "device ckpt tier", "device ckpt tier"),
        )
        missing = []
        for leg, argv, env, leg_timeout, label, note in legs:
            if leg_fresh(evidence_legs().get(leg, {}), since):
                continue
            run_leg(argv, env, timeout_s=leg_timeout, label=label)
            commit_evidence(note)
            if not leg_fresh(evidence_legs().get(leg, {}), since):
                missing.append(leg)
                # Re-probe between legs: if the tunnel died mid-leg,
                # spending the next leg's timeout on a dead chip wastes
                # the session; if it's alive, the remaining legs still
                # get their shot despite this one failing.
                if probe(probe_timeout) != "tpu":
                    print(f"[tpu_watch] tunnel lost after {label!r}; "
                          "re-entering probe loop", flush=True)
                    break
        if missing:
            print(f"[tpu_watch] legs not captured this window: {missing}; "
                  "will keep probing", flush=True)
            time.sleep(interval)
            continue
        print("[tpu_watch] evidence captured; exiting", flush=True)
        return 0
    print("[tpu_watch] deadline reached without a healthy TPU window",
          flush=True)
    return 1


if __name__ == "__main__":
    if "--fleet" in sys.argv:
        i = sys.argv.index("--fleet")
        fleet_target = None
        if i + 1 < len(sys.argv) and not sys.argv[i + 1].startswith("-"):
            fleet_target = sys.argv[i + 1]
        sys.exit(
            fleet(
                fleet_target,
                float(os.environ.get("TPU_WATCH_FOLLOW_INTERVAL_S", "5")),
                float(os.environ.get("TPU_WATCH_MAX_S", str(11 * 3600))),
            )
        )
    if "--follow" in sys.argv:
        i = sys.argv.index("--follow")
        if i + 1 < len(sys.argv) and not sys.argv[i + 1].startswith("-"):
            follow_url = sys.argv[i + 1]
        else:
            follow_url = (
                "http://127.0.0.1:"
                f"{knobs.raw('TPUFLOW_OBS_HTTP_PORT', '8080')}"
            )
        sys.exit(
            follow(
                follow_url,
                float(os.environ.get("TPU_WATCH_FOLLOW_INTERVAL_S", "5")),
                float(os.environ.get("TPU_WATCH_MAX_S", str(11 * 3600))),
            )
        )
    sys.exit(main())
