#!/usr/bin/env python
"""Opportunistic on-TPU evidence capturer.

The dev-box TPU is reached through a tunnel that flaps: it can be healthy
for hours mid-round and dead at round-end snapshot time, which previously
erased all hardware validation (the round-end bench is the only recorded
run). This watcher closes that gap: it probes the default JAX platform on
an interval and, on the first healthy TPU probe, fires the full bench
suite (train steps/s + MFU, flash fwd/bwd vs XLA, KV-cache decode — via
``bench.py``'s train child — plus the device-path checkpoint leg), which
persists every TPU-platform record to ``TPU_EVIDENCE.json``; the watcher
then commits the evidence and exits.

Run it in the background for a whole working session:

    python tools/tpu_watch.py >> tools/tpu_watch.log 2>&1 &

Env knobs: TPU_WATCH_INTERVAL_S (probe cadence, default 600),
TPU_WATCH_MAX_S (give up after, default 11h),
TPU_WATCH_PROBE_TIMEOUT_S (per-probe hang bound, default 90).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EVIDENCE = os.path.join(REPO, "TPU_EVIDENCE.json")


def _clean_env(extra: dict[str, str] | None = None) -> dict[str, str]:
    """Child env with every platform pin / stale probe verdict removed."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "TPUFLOW_PLATFORM_PROBED",
                     "TPUFLOW_PLATFORM_BACKEND", "TPUFLOW_FORCE_CPU")
    }
    if extra:
        env.update(extra)
    return env


def _drop_probe_cache() -> None:
    home = os.environ.get(
        "TPUFLOW_HOME", os.path.join(os.path.expanduser("~"), ".tpuflow")
    )
    try:
        os.remove(os.path.join(home, "platform_probe.json"))
    except OSError:
        pass


def probe(timeout_s: float) -> str | None:
    """Backend name of the default platform, or None if init fails/hangs."""
    try:
        p = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            timeout=timeout_s, capture_output=True, text=True,
            env=_clean_env(),
        )
    except subprocess.TimeoutExpired:
        return None
    if p.returncode != 0:
        return None
    out = p.stdout.strip().splitlines()
    return out[-1] if out else None


def run_bench(extra_env: dict[str, str], timeout_s: float = 3600) -> bool:
    _drop_probe_cache()
    try:
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=_clean_env(extra_env), timeout=timeout_s,
            capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        print("[tpu_watch] bench timed out", flush=True)
        return False
    tail = "\n".join(p.stderr.splitlines()[-25:])
    print(f"[tpu_watch] bench rc={p.returncode}\n{tail}", flush=True)
    return p.returncode == 0


def evidence_has_tpu_train() -> bool:
    try:
        with open(EVIDENCE) as f:
            return json.load(f).get("train", {}).get("platform") == "tpu"
    except (OSError, ValueError):
        return False


def main() -> int:
    interval = float(os.environ.get("TPU_WATCH_INTERVAL_S", "600"))
    probe_timeout = float(os.environ.get("TPU_WATCH_PROBE_TIMEOUT_S", "90"))
    deadline = time.time() + float(
        os.environ.get("TPU_WATCH_MAX_S", str(11 * 3600))
    )
    while time.time() < deadline:
        stamp = time.strftime("%H:%M:%S")
        backend = probe(probe_timeout)
        if backend != "tpu":
            print(f"[tpu_watch {stamp}] probe: {backend!r} — chip not "
                  f"reachable; retry in {interval:.0f}s", flush=True)
            time.sleep(interval)
            continue
        print(f"[tpu_watch {stamp}] TPU healthy — firing bench suite",
              flush=True)
        # Full suite: host-tier ckpt + TPU train/flash/decode legs. A longer
        # train-child timeout than the round-end default: this run is the
        # evidence capture, so give slow tunnel compiles room.
        run_bench({"TPUFLOW_BENCH_TRAIN_TIMEOUT": "900"})
        if not evidence_has_tpu_train():
            print("[tpu_watch] bench ran but produced no TPU train record; "
                  "will keep probing", flush=True)
            time.sleep(interval)
            continue
        # Device-path checkpoint tier (small payload: the tunnel moves
        # ~0.01 GB/s, this leg documents that path rather than racing it).
        run_bench({
            "TPUFLOW_BENCH_DEVICE": "1",
            "TPUFLOW_BENCH_TRAIN": "0",
            "TPUFLOW_BENCH_GB": "0.125",
            "TPUFLOW_BENCH_DEVICES": "1",
            # Device-path capture only: skip the disk tier (whose cold
            # restore drops the machine's page cache) and the 3.4 GiB
            # overlap leg — both already measured by the main suite run.
            "TPUFLOW_BENCH_DISK": "0",
            "TPUFLOW_BENCH_OVERLAP": "0",
        }, timeout_s=1800)
        # add makes the (possibly untracked) file known to git; the
        # pathspec'd commit then includes ONLY it — never files another
        # process staged mid-work.
        subprocess.run(["git", "-C", REPO, "add", "TPU_EVIDENCE.json"])
        subprocess.run([
            "git", "-C", REPO, "commit", "-m",
            "Record on-TPU bench evidence (train+MFU, flash kernels, decode, "
            "device ckpt tier)",
            "-m", "No-Verification-Needed: benchmark data capture only",
            "--", "TPU_EVIDENCE.json",
        ])
        print("[tpu_watch] evidence committed; exiting", flush=True)
        return 0
    print("[tpu_watch] deadline reached without a healthy TPU window",
          flush=True)
    return 1


if __name__ == "__main__":
    sys.exit(main())
