#!/usr/bin/env python
"""tpulint — AST invariant checker for the hand-maintained contracts.

Eleven PRs of perf and robustness work rest on invariants that, until
now, lived only in comments and runtime tests: the serving engine's
never-recompile contract, the PR-4 donation discipline, trace-time
constant hygiene inside jit bodies, and a ~100-knob ``TPUFLOW_*`` env
surface whose README tables were hand-kept. This tool checks them
statically, on every tree, in seconds:

- **pass 1, knobs** (``tpuflow/lint/knob_pass.py``): every TPUFLOW_*
  read goes through the registry (``tpuflow/utils/knobs.py``), every
  literal names a declared knob, and the README knob tables match the
  generated region byte-for-byte.
- **pass 2, jit** (``tpuflow/lint/jit_pass.py``): no env/knob reads,
  ``time.*``, or host RNG inside traced bodies; no host syncs on traced
  values; donation restricted to step/engine state and never reused
  after the call.
- **pass 3, recompile** (``tpuflow/lint/recompile_pass.py``): the
  ServeEngine jit program inventory, ``compile_stats()``, ``warmup()``,
  ``aot_lower()``, and ``tools/prewarm_cache.py`` coverage agree.
- **pass 4, obs** (``tpuflow/lint/obs_pass.py``): the telemetry-name
  catalog lint, with unemitted catalog entries promoted to errors
  (``tools/obs_lint.py`` remains as a working shim).

Silence a finding with an inline pragma **with a justification**::

    # tpulint: disable=<rule> -- <why this is safe>

Run standalone (exit 1 on violation) or via the pytest twin
(tests/test_tpulint.py). See README "Static analysis runbook".
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpuflow.lint import core  # noqa: E402
from tpuflow.lint import (  # noqa: E402
    jit_pass,
    knob_pass,
    obs_pass,
    recompile_pass,
)

PASSES = {
    "knobs": knob_pass.run,
    "jit": jit_pass.run,
    "recompile": recompile_pass.run,
    "obs": obs_pass.run,
}


def lint(root: str = REPO, passes=None):
    """All findings for ``root`` (shared parsed-source cache across
    passes). ``passes`` is an iterable of pass names, default all."""
    tree = core.Tree(root)
    findings = []
    for name in passes or PASSES:
        findings.extend(PASSES[name](tree))
    findings.extend(tree.parse_errors)
    return findings


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument(
        "--pass", dest="passes", action="append", choices=sorted(PASSES),
        help="run only this pass (repeatable; default: all four)",
    )
    p.add_argument("--root", default=REPO)
    args = p.parse_args(argv)
    findings = lint(args.root, args.passes)
    for f in findings:
        print(f"[tpulint] ERROR: {f}")
    if findings:
        print(f"[tpulint] {len(findings)} finding(s)")
        return 1
    ran = ",".join(args.passes or sorted(PASSES))
    print(f"[tpulint] ok (passes: {ran})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
