"""tpuflow — TPU-native distributed training, checkpointing, and eval pipelines.

A brand-new JAX/XLA framework providing the capabilities of the reference
pipeline `outerbounds/ray-torch-distributed-checkpoint` (Metaflow + Ray Train +
torch DDP/NCCL + Ray Data), re-designed TPU-first:

- ``tpuflow.dist``  — mesh + multi-host gang init (XLA collectives over ICI/DCN,
  replacing NCCL/Gloo + torch.distributed rendezvous).
- ``tpuflow.data``  — dataset registry with per-host sharding and seeded
  per-epoch reshuffle (replacing DataLoader + DistributedSampler).
- ``tpuflow.models`` — Flax model zoo (parity MLP, ResNet, GPT-2) + losses.
- ``tpuflow.train`` — Trainer / ScalingConfig / RunConfig / report() / Result
  (replacing Ray Train's TorchTrainer worker group).
- ``tpuflow.ckpt``  — async sharded checkpointing with best/latest policies and
  retention (Orbax; replacing torch.save + Ray Checkpoint).
- ``tpuflow.infer`` — batch inference engine (replacing Ray Data map_batches).
- ``tpuflow.flow``  — a small flow orchestrator: steps, parameters, artifacts,
  --from-run resume, retries, triggers, cards (replacing Metaflow).
- ``tpuflow.ops``   — Pallas TPU kernels (flash attention, ...).
- ``tpuflow.parallel`` — sharding rules: DP / FSDP / tensor / ring-attention
  sequence parallelism over a named ``jax.sharding.Mesh``.
- ``tpuflow.obs``   — unified telemetry: spans / counters / gauges /
  histograms as JSONL under the run dir, gang-merged into one timeline,
  rendered as the run's timeline card (replacing Ray Train's report()
  stream + Metaflow cards as the observability surface).

See ``SURVEY.md`` at the repo root for the capability contract and the mapping
from every reference component to its tpuflow equivalent.
"""

__version__ = "0.1.0"
