"""ctypes binding for the native IO library, with build-on-demand.

``lib()`` returns the loaded library or None (never raises): if the shared
object is missing it is built with ``make`` once per process under a file
lock; if no toolchain is available, callers fall back to NumPy paths — the
framework stays pure-Python-functional, just slower.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

import numpy as np
from tpuflow.utils import knobs

logger = logging.getLogger("tpuflow.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libtpuflow_io.so")
_lib: ctypes.CDLL | None = None
_tried = False


def _stale() -> bool:
    """True when the shared object is missing or older than its source."""
    if not os.path.exists(_SO):
        return True
    try:
        src_mtime = os.path.getmtime(os.path.join(_DIR, "io.cpp"))
    except OSError:
        return False  # no source shipped: the prebuilt .so can't be stale
    try:
        return os.path.getmtime(_SO) < src_mtime
    except OSError:
        return True


def _build() -> bool:
    from tpuflow.utils import FileLock

    try:
        with FileLock(os.path.join(_DIR, ".build.lock")):
            if not _stale():
                return True
            proc = subprocess.run(
                ["make", "-C", _DIR],
                capture_output=True,
                text=True,
                timeout=120,
            )
        if proc.returncode != 0:
            logger.warning("native build failed:\n%s", proc.stderr[-1000:])
            return False
        return True
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning("native build unavailable: %r", e)
        return False


def lib() -> ctypes.CDLL | None:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if _stale() and not _build():
        return None
    try:
        L = ctypes.CDLL(_SO)
        _bind(L)
    except (OSError, AttributeError) as e:
        # AttributeError: a stale .so (copied with fresh mtimes) missing a
        # newer symbol — fall back to the NumPy paths per the module contract.
        logger.warning("cannot load %s: %r", _SO, e)
        return None
    _lib = L
    return _lib


def _bind(L: ctypes.CDLL) -> None:
    L.ckptio_write.restype = ctypes.c_int
    L.ckptio_write.argtypes = [
        ctypes.c_char_p,
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_int,
    ]
    L.ckptio_write_inplace.restype = ctypes.c_int
    L.ckptio_write_inplace.argtypes = [
        ctypes.c_char_p,
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_int,
    ]
    L.ckptio_read.restype = ctypes.c_int
    L.ckptio_read.argtypes = [
        ctypes.c_char_p,
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_int,
    ]
    L.ckptio_file_size.restype = ctypes.c_int64
    L.ckptio_file_size.argtypes = [ctypes.c_char_p]
    L.dataio_gather_normalize_u8.restype = ctypes.c_int
    L.dataio_gather_normalize_u8.argtypes = [
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_float,
        ctypes.c_float,
        ctypes.c_void_p,
        ctypes.c_int,
    ]
    L.dataio_gather_f32.restype = ctypes.c_int
    L.dataio_gather_f32.argtypes = [
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_void_p,
        ctypes.c_int,
    ]


def default_threads() -> int:
    return int(
        knobs.raw("TPUFLOW_IO_THREADS", min(os.cpu_count() or 1, 16))
    )


_XLA_ALIGN = 4096


def aligned_empty(nbytes: int, align: int = _XLA_ALIGN) -> np.ndarray:
    """Uninitialized u8 buffer whose data pointer is ``align``-aligned.

    XLA's CPU client zero-copy *aliases* host buffers that are at least
    64-byte aligned instead of copying them into fresh device memory —
    restore hands freshly-read shard buffers straight to ``jax.device_put``,
    so alignment here removes an entire memcpy (and an entire fresh-page
    allocation) from the restore path. glibc's malloc returns big blocks at
    a 16-byte offset, hence the explicit over-allocate-and-slice.
    """
    base = np.empty(nbytes + align, np.uint8)
    off = (-base.ctypes.data) % align
    return base[off : off + nbytes]


# ------------------------------------------------------------ typed wrappers
def write_bytes(
    path: str,
    arr: np.ndarray,
    *,
    threads: int | None = None,
    inplace: bool = False,
) -> None:
    """Striped threaded write of a contiguous array's bytes to ``path``.

    ``inplace=True`` overwrites an existing file without truncating first so
    its already-allocated pages are reused (the checkpoint recycle-pool fast
    path on memory-backed filesystems); the file is sized to ``arr.nbytes``
    afterwards either way.
    """
    L = lib()
    arr = np.ascontiguousarray(arr)
    if L is None:
        mode = "r+b" if inplace and os.path.exists(path) else "wb"
        with open(path, mode, buffering=0) as f:
            f.write(memoryview(arr).cast("B"))
            f.truncate(arr.nbytes)
            # Match the native writer's durability contract (write_impl
            # fsyncs before close) — without this, the fallback measures
            # and commits at page-cache speed while claiming durability.
            os.fsync(f.fileno())
        return
    fn = L.ckptio_write_inplace if inplace else L.ckptio_write
    rc = fn(
        path.encode(),
        arr.ctypes.data_as(ctypes.c_void_p),
        arr.nbytes,
        threads if threads is not None else default_threads(),
    )
    if rc != 0:
        raise OSError(rc, os.strerror(rc), path)


def read_bytes(
    path: str,
    nbytes: int,
    *,
    threads: int | None = None,
    out: np.ndarray | None = None,
) -> np.ndarray:
    """Striped threaded read of ``nbytes`` from ``path`` into a u8 array.

    The buffer is page-aligned so downstream ``jax.device_put`` on CPU
    aliases it zero-copy (see ``aligned_empty``). ``out`` supplies the
    destination buffer instead (must be a contiguous u8 array of exactly
    ``nbytes``) — the restore arena passes pre-backed buffers here so the
    read is a single page-cache memcpy with no first-touch faulting."""
    if out is not None:
        # Hard validation (not assert: under `python -O` a size-mismatched
        # buffer would reach the native striped reader, which writes nbytes
        # regardless — heap corruption instead of an exception).
        if out.dtype != np.uint8 or out.nbytes != nbytes or not (
            out.flags["C_CONTIGUOUS"]
        ):
            raise ValueError(
                f"out must be a contiguous uint8 array of exactly {nbytes} "
                f"bytes; got dtype={out.dtype}, nbytes={out.nbytes}, "
                f"contiguous={out.flags['C_CONTIGUOUS']}"
            )
    else:
        out = aligned_empty(nbytes)
    L = lib()
    if L is None:
        with open(path, "rb", buffering=0) as f:
            f.readinto(memoryview(out))
        return out
    rc = L.ckptio_read(
        path.encode(),
        out.ctypes.data_as(ctypes.c_void_p),
        nbytes,
        threads if threads is not None else default_threads(),
    )
    if rc != 0:
        raise OSError(rc, os.strerror(rc), path)
    return out


def gather_normalize_u8(
    src: np.ndarray,
    idx: np.ndarray,
    *,
    mean: float = 0.5,
    std: float = 0.5,
    threads: int | None = None,
) -> np.ndarray:
    """Fused batch gather + normalize for uint8 image datasets:
    out[i] = (src[idx[i]]/255 - mean)/std, shape (len(idx), *src.shape[1:])."""
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(idx, np.int64)
    row_elems = int(np.prod(src.shape[1:]))
    L = lib()
    if L is None or src.dtype != np.uint8:
        return ((src[idx].astype(np.float32) / 255.0) - mean) / std
    out = np.empty((len(idx), *src.shape[1:]), np.float32)
    rc = L.dataio_gather_normalize_u8(
        src.ctypes.data_as(ctypes.c_void_p),
        row_elems,
        idx.ctypes.data_as(ctypes.c_void_p),
        len(idx),
        mean,
        1.0 / std,
        out.ctypes.data_as(ctypes.c_void_p),
        threads if threads is not None else default_threads(),
    )
    if rc != 0:
        raise OSError(rc, os.strerror(rc))
    return out


def gather_f32(
    src: np.ndarray, idx: np.ndarray, *, threads: int | None = None
) -> np.ndarray:
    """Threaded indexed row copy: out[i] = src[idx[i]] for float32 rows."""
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(idx, np.int64)
    L = lib()
    if L is None or src.dtype != np.float32:
        return src[idx]
    row_elems = int(np.prod(src.shape[1:]))
    out = np.empty((len(idx), *src.shape[1:]), np.float32)
    rc = L.dataio_gather_f32(
        src.ctypes.data_as(ctypes.c_void_p),
        row_elems,
        idx.ctypes.data_as(ctypes.c_void_p),
        len(idx),
        out.ctypes.data_as(ctypes.c_void_p),
        threads if threads is not None else default_threads(),
    )
    if rc != 0:
        raise OSError(rc, os.strerror(rc))
    return out
