// tpuflow native IO plane: threaded checkpoint file IO + dataset batch ops.
//
// TPU-native counterpart of the native components in the reference's
// dependency stack (SURVEY.md §2b/2d: Ray core's C++ object store and
// torch's C++ serialization under torch.save at my_ray_module.py:179-201).
// The JAX/XLA compute path stays in jaxlib's C++ runtime; this library covers
// the framework's own host-side hot paths:
//
//   - ckptio_write / ckptio_read: striped multi-threaded pwrite/pread of one
//     contiguous buffer <-> file. Threads each own a disjoint byte range, so
//     storage tiers with per-stream limits (page cache, NVMe queues, network
//     FS) are driven in parallel. Used by the 'raw' checkpoint format.
//   - dataio_gather_normalize_*: batch assembly fused with normalization
//     ((x/255 - mean)/std for u8, identity gather for f32), multithreaded
//     across batch rows. Used by the data loader (replaces the per-batch
//     Python/NumPy gather of DataLoader workers).
//
// Build: `make` in this directory (g++ -O3 -shared -fPIC -pthread).
// Python binding: ctypes (tpuflow/_native/__init__.py) — no pybind11 needed.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// Run fn(i) on n threads; returns first nonzero error code.
template <typename F> int parallel_for(int n, F fn) {
  if (n <= 1) return fn(0);
  std::atomic<int> err{0};
  std::vector<std::thread> threads;
  threads.reserve(n);
  for (int i = 0; i < n; ++i) {
    threads.emplace_back([&, i] {
      int e = fn(i);
      int expected = 0;
      if (e != 0) err.compare_exchange_strong(expected, e);
    });
  }
  for (auto &t : threads) t.join();
  return err.load();
}

int full_pwrite(int fd, const char *buf, size_t count, off_t offset) {
  while (count > 0) {
    ssize_t w = pwrite(fd, buf, count, offset);
    if (w < 0) {
      if (errno == EINTR) continue;
      return errno;
    }
    buf += w;
    offset += w;
    count -= static_cast<size_t>(w);
  }
  return 0;
}

int full_pread(int fd, char *buf, size_t count, off_t offset) {
  while (count > 0) {
    ssize_t r = pread(fd, buf, count, offset);
    if (r < 0) {
      if (errno == EINTR) continue;
      return errno;
    }
    if (r == 0) return EIO;  // truncated file
    buf += r;
    offset += r;
    count -= static_cast<size_t>(r);
  }
  return 0;
}

}  // namespace

extern "C" {

namespace {

// Shared implementation: `truncate_first` picks between the fresh-file path
// (O_TRUNC up front — releases the old pages) and the in-place path (keep
// existing pages so filesystems backed by memory — tmpfs page cache — skip
// the fresh-page zeroing cost; final ftruncate fixes the size either way).
int write_impl(const char *path, const void *data, uint64_t nbytes,
               int nthreads, bool truncate_first) {
  int flags = O_CREAT | O_WRONLY | (truncate_first ? O_TRUNC : 0);
  int fd = open(path, flags, 0644);
  if (fd < 0) return errno;
  if (truncate_first && ftruncate(fd, static_cast<off_t>(nbytes)) != 0) {
    int e = errno;
    close(fd);
    return e;
  }
  if (nthreads < 1) nthreads = 1;
  uint64_t stripe = (nbytes + nthreads - 1) / nthreads;
  const char *base = static_cast<const char *>(data);
  int err = parallel_for(nthreads, [&](int i) -> int {
    uint64_t off = stripe * static_cast<uint64_t>(i);
    if (off >= nbytes) return 0;
    uint64_t len = std::min(stripe, nbytes - off);
    return full_pwrite(fd, base + off, len, static_cast<off_t>(off));
  });
  if (!truncate_first && err == 0 &&
      ftruncate(fd, static_cast<off_t>(nbytes)) != 0)
    err = errno;
  if (fsync(fd) != 0 && err == 0) err = errno;
  if (close(fd) != 0 && err == 0) err = errno;
  return err;
}

}  // namespace

// Write `nbytes` from `data` to `path` with `nthreads` striped writers.
// Returns 0 on success, else errno.
int ckptio_write(const char *path, const void *data, uint64_t nbytes,
                 int nthreads) {
  return write_impl(path, data, nbytes, nthreads, /*truncate_first=*/true);
}

// Same, but overwrite an existing (recycled) file in place instead of
// truncating: on tmpfs/page-cache-backed storage this reuses the file's
// already-faulted pages, which is several times faster than allocating and
// zeroing fresh ones. Used by the checkpoint recycle pool.
int ckptio_write_inplace(const char *path, const void *data, uint64_t nbytes,
                         int nthreads) {
  return write_impl(path, data, nbytes, nthreads, /*truncate_first=*/false);
}

// Read `nbytes` into `data` from `path` with `nthreads` striped readers.
int ckptio_read(const char *path, void *data, uint64_t nbytes, int nthreads) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return errno;
  if (nthreads < 1) nthreads = 1;
  uint64_t stripe = (nbytes + nthreads - 1) / nthreads;
  char *base = static_cast<char *>(data);
  int err = parallel_for(nthreads, [&](int i) -> int {
    uint64_t off = stripe * static_cast<uint64_t>(i);
    if (off >= nbytes) return 0;
    uint64_t len = std::min(stripe, nbytes - off);
    return full_pread(fd, base + off, len, static_cast<off_t>(off));
  });
  if (close(fd) != 0 && err == 0) err = errno;
  return err;
}

// File size helper (-1 on error).
int64_t ckptio_file_size(const char *path) {
  struct stat st;
  if (stat(path, &st) != 0) return -1;
  return static_cast<int64_t>(st.st_size);
}

// Gather rows of a uint8 source into a float32 batch, fused with
// (x/255 - mean)/std normalization. src: (n_rows, row_elems) u8;
// out: (n_idx, row_elems) f32.
int dataio_gather_normalize_u8(const uint8_t *src, uint64_t row_elems,
                               const int64_t *idx, uint64_t n_idx,
                               float mean, float inv_std, float *out,
                               int nthreads) {
  if (nthreads < 1) nthreads = 1;
  uint64_t stripe = (n_idx + nthreads - 1) / nthreads;
  const float scale = inv_std / 255.0f;
  const float bias = -mean * inv_std;
  return parallel_for(nthreads, [&](int t) -> int {
    uint64_t lo = stripe * static_cast<uint64_t>(t);
    uint64_t hi = std::min(lo + stripe, n_idx);
    for (uint64_t r = lo; r < hi; ++r) {
      const uint8_t *s = src + static_cast<uint64_t>(idx[r]) * row_elems;
      float *d = out + r * row_elems;
      for (uint64_t e = 0; e < row_elems; ++e)
        d[e] = static_cast<float>(s[e]) * scale + bias;
    }
    return 0;
  });
}

// Gather rows of a float32 source into a float32 batch (plain indexed copy).
int dataio_gather_f32(const float *src, uint64_t row_elems, const int64_t *idx,
                      uint64_t n_idx, float *out, int nthreads) {
  if (nthreads < 1) nthreads = 1;
  uint64_t stripe = (n_idx + nthreads - 1) / nthreads;
  return parallel_for(nthreads, [&](int t) -> int {
    uint64_t lo = stripe * static_cast<uint64_t>(t);
    uint64_t hi = std::min(lo + stripe, n_idx);
    for (uint64_t r = lo; r < hi; ++r) {
      std::memcpy(out + r * row_elems,
                  src + static_cast<uint64_t>(idx[r]) * row_elems,
                  row_elems * sizeof(float));
    }
    return 0;
  });
}

}  // extern "C"
