"""Checkpoint subsystem: async sharded save/restore with policies.

Replaces the reference's torch.save + Ray Checkpoint + CheckpointConfig stack
(my_ray_module.py:178-205,236-238,253-264) with Orbax-backed sharded
checkpointing — see tpuflow.ckpt.manager for the full capability map.
"""

from tpuflow.ckpt.handle import Checkpoint
from tpuflow.ckpt.manager import (
    CheckpointManager,
    prewarm_restore_handle,
    restore_from_handle,
)
from tpuflow.ckpt.raw import CheckpointIOError, CorruptShardError

__all__ = [
    "Checkpoint",
    "CheckpointIOError",
    "CheckpointManager",
    "CorruptShardError",
    "prewarm_restore_handle",
    "restore_from_handle",
]
