"""Checkpoint handle: a path + metadata, never pickled tensors.

Parity with Ray's ``Checkpoint`` object as the reference uses it
(my_ray_module.py:202 ``Checkpoint.from_directory``, my_ray_module.py:254
``as_directory``; flow artifact handoff at train_flow.py:71-73,
eval_flow.py:42-49): the handle that crosses runs/flows is a *reference* to
checkpoint storage, not the bytes — the flow runner persists it as JSON, so a
checkpoint written by one topology can be restored (resharded) by another
(SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
from typing import Any, Iterator


@dataclasses.dataclass
class Checkpoint:
    """Reference to a checkpoint directory written by CheckpointManager.

    ``alt_paths`` (ISSUE 5): alternate directories holding the SAME
    committed step on other storage tiers — e.g. the node-local fast-tier
    copy beside the persistent one. ``as_directory`` serves the first
    tier that still exists, so a handle stays restorable when one tier is
    gone (persistent dir lagging an upload, or a local copy evicted)."""

    path: str
    metadata: dict[str, Any] = dataclasses.field(default_factory=dict)
    alt_paths: list[str] = dataclasses.field(default_factory=list)

    @classmethod
    def from_directory(cls, path: str, metadata: dict | None = None) -> "Checkpoint":
        """Wrap an existing checkpoint directory (↔ Checkpoint.from_directory,
        my_ray_module.py:202)."""
        path = os.path.abspath(path)
        if not os.path.isdir(path):
            raise FileNotFoundError(f"checkpoint directory not found: {path}")
        meta_path = os.path.join(path, "metadata.json")
        if metadata is None and os.path.exists(meta_path):
            with open(meta_path) as f:
                metadata = json.load(f)
        return cls(path=path, metadata=metadata or {})

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        """Yield a local directory with the checkpoint contents
        (↔ checkpoint.as_directory(), my_ray_module.py:254). Storage here is a
        filesystem path already, so no materialization copy is needed; the
        first existing tier among ``path`` + ``alt_paths`` serves."""
        for candidate in [self.path, *self.alt_paths]:
            if os.path.isdir(candidate):
                yield candidate
                return
        raise FileNotFoundError(
            f"checkpoint directory gone: {self.path}"
            + (f" (and {len(self.alt_paths)} alternate tiers)" if self.alt_paths else "")
        )

    def to_json(self) -> dict:
        out = {"path": self.path, "metadata": self.metadata}
        if self.alt_paths:
            out["alt_paths"] = list(self.alt_paths)
        return out

    @classmethod
    def from_json(cls, obj: dict) -> "Checkpoint":
        return cls(
            path=obj["path"],
            metadata=obj.get("metadata", {}),
            alt_paths=list(obj.get("alt_paths", [])),
        )
