"""Async sharded checkpoint manager with best/latest policies and retention.

The TPU-native replacement for the reference checkpoint subsystem
(my_ray_module.py:178-205,236-238,253-264):

- per-epoch ``torch.save`` of ``{epoch, model_state_dict,
  optimizer_state_dict, val_losses, val_accuracy}``  →  async sharded Orbax
  save of the TrainState pytree (each host writes its shards; tensorstore
  OCDBT under the hood) plus a JSON metadata sidecar carrying the metrics
  history;
- duplicate ``latest_model.pt`` / ``best_model.pt`` files
  (my_ray_module.py:27-28,190-201)  →  *policies*: ``latest_step()`` /
  ``best_step()`` computed from recorded metrics — no duplicate bytes;
- ``CheckpointConfig(num_to_keep=2)`` retention (my_ray_module.py:222,236)
  →  retain the newest ``max_to_keep`` steps **plus** the best step (the
  reference keeps best reachable by writing it into every checkpoint dir);
- restore (my_ray_module.py:253-264: load best, strip the DDP ``module.``
  prefix, weights only)  →  ``restore(weights_only=True, best=True)``; the
  prefix-strip has no equivalent because params are a pytree, not
  name-mangled — the normalization the reference needs is a wrapper artifact;
- topology change: restore takes an abstract state (shapes + shardings) so a
  checkpoint written on one mesh restores, resharded, on another — the
  property the ≥2 GB/s/chip north-star metric presumes (SURVEY.md §5).

Save is asynchronous: training continues while hosts flush shards; ``save``
only blocks to drain a still-running *previous* save (double-buffering, the
same overlap Orbax's own manager provides).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from typing import Any

import jax
import orbax.checkpoint as ocp

from tpuflow import obs
from tpuflow.ckpt.handle import Checkpoint
from tpuflow.utils import knobs

_STATE_DIR = "state"
_META_FILE = "metadata.json"
_STEP_PREFIX = "step_"
# Saves stage into <final>.tmp and become visible via ONE atomic rename at
# commit; anything still wearing the suffix at manager construction is a
# killed writer's leftovers and is garbage-collected (ckpt.gc).
_STAGE_SUFFIX = ".tmp"


def _local_tier_root(persistent_dir: str) -> str | None:
    """Node-local fast-tier directory for this manager, or None when the
    tier is off. ``TPUFLOW_CKPT_LOCAL_DIR`` names the node-local root
    (tmpfs / local NVMe); each run keys a subdirectory off a hash of its
    persistent directory, so concurrent runs never collide while a
    requeued attempt of the SAME run on the same node finds its local
    copies again — that is the whole point of the tier (restore in
    seconds after a preemption instead of re-reading the run dir)."""
    root = knobs.raw("TPUFLOW_CKPT_LOCAL_DIR")
    if not root:
        return None
    key = hashlib.sha1(os.path.abspath(persistent_dir).encode()).hexdigest()[:16]
    return os.path.join(os.path.abspath(root), key)


def _local_keep(default: int = 2) -> int:
    """Local-tier retention: newest ``TPUFLOW_CKPT_LOCAL_KEEP`` committed
    steps survive, oldest evicted first — requeue loops must not fill node
    disk. Clamped to >= 1 (a tier that keeps nothing is the tier being
    off); malformed falls back to ``default``."""
    env = knobs.raw("TPUFLOW_CKPT_LOCAL_KEEP")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return default


def _addressable_nbytes(tree) -> int:
    """Bytes this process will actually write for ``tree``: replica-0
    addressable shards of device arrays (the save path's shard ownership,
    raw._leaf_shards) plus host leaves on process 0. The numerator of the
    recorded save GB/s — the same accounting the ≥2 GB/s/chip BASELINE
    claim uses, so the telemetry number is comparable to the bench's."""
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "addressable_shards"):
            total += sum(
                s.data.nbytes
                for s in leaf.addressable_shards
                if s.replica_id == 0
            )
        elif jax.process_index() == 0:
            if hasattr(leaf, "nbytes"):
                total += int(leaf.nbytes)
            else:
                total += np.asarray(leaf).nbytes
    return total


def _abstractify(tree):
    """Pytree of arrays/scalars/ShapeDtypeStructs → pytree of
    ShapeDtypeStructs (shardings preserved where present), tolerant of
    non-array leaves like a Python-int step counter."""
    import numpy as np

    def one(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)
            )
        arr = np.asarray(x)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    return jax.tree_util.tree_map(one, tree)


class CheckpointManager:
    """Manage per-step checkpoints under one directory.

    Layout::

        directory/
          step_3/
            state/          # Orbax OCDBT pytree (sharded arrays)
            metadata.json   # step, metrics, metrics_history, mesh info
          step_4/ ...
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int | None = 2,
        best_metric: str = "val_loss",
        best_mode: str = "min",
        async_save: bool = True,
        format: str = "auto",
        save_dtype: str | None = None,
    ):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.best_metric = best_metric
        self.best_mode = best_mode
        self._async = async_save
        # Reduced-precision checkpointing: cast floating leaves wider than
        # ``save_dtype`` down before writing (e.g. 'bfloat16' halves f32
        # checkpoint bytes — and doubles effective save/restore GB/s).
        # Restore-with-template casts back to the template dtype, so
        # training resumes in full precision from rounded values. Lossy by
        # design; leave None for bit-exact checkpoints. Integer leaves
        # (step counters, token ids) are never touched.
        if save_dtype is not None and save_dtype not in ("bfloat16", "float16"):
            raise ValueError(
                f"save_dtype must be None, 'bfloat16' or 'float16', "
                f"got {save_dtype!r}"
            )
        self.save_dtype = save_dtype
        # 'raw' = native striped-IO per-leaf files (fast path; needs fully
        # addressable leaves, i.e. single-host); 'orbax' = tensorstore OCDBT
        # (multi-host sharded writes). 'auto' picks raw when possible.
        format = knobs.raw("TPUFLOW_CKPT_FORMAT", format)
        if format == "auto":
            # The native raw format handles both single- and multi-host
            # states (each host writes its own shards); Orbax/ocdbt stays
            # available via TPUFLOW_CKPT_FORMAT=orbax.
            format = "raw"
        if format not in ("raw", "orbax"):
            raise ValueError(f"unknown checkpoint format {format!r}")
        self.format = format
        from tpuflow.ckpt.raw import AsyncRawSaver, RecyclePool

        self._raw_saver = AsyncRawSaver()
        # Retired step files are recycled (pages reused) instead of unlinked;
        # see RecyclePool. Orbax manages its own files, so raw-only.
        self._pool = (
            RecyclePool(os.path.join(self.directory, ".recycle"))
            if self.format == "raw"
            else None
        )
        self._ckptr = ocp.StandardCheckpointer()
        self._metrics_history: list[dict[str, Any]] = []
        self._pending_commit = None  # multi-host raw: commit deferred to drain
        # (step, cleanup) of the save currently in flight: consumed by
        # wait_until_finished when that save dies with a CheckpointIOError
        # — the failed step's staging is reclaimed, ckpt.save_failed is
        # recorded, and training continues (ISSUE 5 tentpole).
        self._pending_fail: tuple[int, Any] | None = None
        # Node-local fast tier (ISSUE 5): saves stage here first and upload
        # to the persistent run dir on the saver thread; restores prefer a
        # crc-valid local copy. None = tier off, persistent-only behavior.
        self.local_dir = _local_tier_root(self.directory)
        self.local_keep = _local_keep()
        # Multi-host: construction is collective (like every other manager
        # operation) — the barriers ensure no host is already writing while
        # process 0 sweeps, and no host starts writing before the sweep ends.
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("tpuflow_ckpt_mgr_preinit")
        self._sweep_orphans()
        if jax.process_count() > 1:
            multihost_utils.sync_global_devices("tpuflow_ckpt_mgr_swept")
        # Rebuild history from existing steps (in-run resume after retry).
        # The newest step's metadata embeds the FULL accumulated history —
        # including steps retention has since deleted — so a retried run's
        # metrics history stays continuous from the first save, not from
        # the oldest still-retained checkpoint.
        steps = self.all_steps()
        seen_steps: set[int] = set()
        if steps:
            newest = self._read_meta(steps[-1]) or {}
            for m in newest.get("metrics_history", []):
                if "step" in m:
                    self._metrics_history.append(dict(m))
                    seen_steps.add(m["step"])
        for step in steps:
            if step in seen_steps:
                continue
            meta = self._read_meta(step)
            if meta and "metrics" in meta:
                self._metrics_history.append({"step": step, **meta["metrics"]})
        self._metrics_history.sort(key=lambda m: m.get("step", 0))

    def prewarm(self, state) -> None:
        """Back recycle-pool pages for the steady-state footprint in the
        background.

        Call once the train state exists (before the first save): the
        page-backing cost of a process's first checkpoints — which on
        ballooning hypervisors dominates cold-save time ~15x — is paid by a
        background thread that overlaps real work (epoch-1 compute),
        instead of by the first ``save()``s. Pool files are created at the
        exact per-shard sizes this process's saves will request (so no
        truncation waste gets reclaimed by the host), sized to the
        retention footprint: ``max_to_keep`` live steps plus one in flight.
        No-op for the Orbax format, for already-warm pools, and with the
        local fast tier on (staging then writes fresh local pages — the
        pool lives on the persistent filesystem, see save()).
        """
        if self._pool is None or self.local_dir is not None:
            return
        sizes = []
        for leaf in jax.tree_util.tree_leaves(state):
            if hasattr(leaf, "addressable_shards"):
                # replica_id==0 mirrors the save path's shard ownership
                # (raw._leaf_shards): replicated leaves count once.
                sizes += [
                    s.data.nbytes
                    for s in leaf.addressable_shards
                    if s.replica_id == 0
                ]
            elif hasattr(leaf, "nbytes") and jax.process_index() == 0:
                # Host/numpy leaves are written by process 0 only
                # (raw._leaf_shards) — other processes must not warm pages
                # no save of theirs will use.
                sizes.append(int(leaf.nbytes))
        # Footprint = max_to_keep newest steps + the pinned best step (which
        # retention keeps even when it falls out of the newest window) + one
        # save in flight.
        steps = (self.max_to_keep or 1) + (2 if self.best_metric else 1)
        self._pool.prewarm(sizes * steps)

    def prewarm_wait(self) -> None:
        if self._pool is not None:
            self._pool.prewarm_wait()

    def prewarm_restore(
        self, step: int | None = None, *, best: bool = False,
        background: bool = True,
    ) -> None:
        """Pre-back the destination buffers a ``restore`` of ``step`` will
        fill (restore-side twin of ``prewarm``; see raw.RestoreArena).

        Call as soon as the checkpoint to restore is known — before the
        work that naturally precedes the restore (dataset decode, mesh
        build, model compile) — and the first-touch page-backing cost of
        the restored state overlaps it on a background thread instead of
        serializing into the restore. No-op for Orbax-format steps.

        Contract: one restore per prewarm. The arena is process-global and
        restores serialize on a process-wide lock; a prewarm issued while
        another restore is in flight may lose (some of) its backing work
        to that restore's cleanup — the optimization silently degrades,
        correctness is unaffected.
        """
        try:
            chosen = self._resolve_step(step, best)
        except (ValueError, FileNotFoundError):
            return
        _prewarm_state_dir(
            os.path.join(
                self._committed_dir(chosen) or self._step_dir(chosen),
                _STATE_DIR,
            ),
            background=background,
        )

    def prewarm_restore_wait(self) -> None:
        from tpuflow.ckpt import raw as raw_fmt

        raw_fmt._ARENA.prewarm_wait()

    def _sweep_orphans(self) -> None:
        """Garbage-collect every leftover of a killed writer (ckpt.gc).

        Three classes, all invisible to ``all_steps()`` but leaking storage
        forever without the sweep: staged ``step_K.tmp`` dirs (killed
        between payload and commit — by construction these can NEVER be
        mistaken for restorable steps, the commit is one atomic rename),
        committed-looking dirs without a ``metadata.json`` (pre-staging
        crashes, upload leftovers), and the local fast tier's stale staging
        plus anything beyond its retention from previous attempts. At
        manager construction no save is in flight, so everything found here
        is an orphan — recycle (raw) or delete it."""
        if jax.process_index() != 0:
            return
        removed: list[str] = []
        try:
            entries = os.listdir(self.directory)
        except FileNotFoundError:
            entries = []
        for name in entries:
            if not name.startswith(_STEP_PREFIX):
                continue
            path = os.path.join(self.directory, name)
            if not os.path.isdir(path):
                continue
            if name.endswith(_STAGE_SUFFIX) or not os.path.exists(
                os.path.join(path, _META_FILE)
            ):
                if self._pool is not None:
                    self._pool.adopt_dir(path)
                else:
                    shutil.rmtree(path, ignore_errors=True)
                removed.append(name)
        if self.local_dir and os.path.isdir(self.local_dir):
            # Local tier: stale staging from killed attempts, uncommitted
            # dirs, and over-retention leftovers — requeue loops must not
            # fill node disk (ISSUE 5 satellite).
            for name in sorted(os.listdir(self.local_dir)):
                if not name.startswith(_STEP_PREFIX):
                    continue
                path = os.path.join(self.local_dir, name)
                if not os.path.isdir(path):
                    continue
                if name.endswith(_STAGE_SUFFIX) or not os.path.exists(
                    os.path.join(path, _META_FILE)
                ):
                    shutil.rmtree(path, ignore_errors=True)
                    removed.append(f"local:{name}")
            for step in self._committed_in(self.local_dir)[: -self.local_keep]:
                shutil.rmtree(
                    os.path.join(self.local_dir, f"{_STEP_PREFIX}{step}"),
                    ignore_errors=True,
                )
                removed.append(f"local:{_STEP_PREFIX}{step}")
        if removed:
            obs.event(
                "ckpt.gc", reclaimed=len(removed), dirs=sorted(removed)[:16]
            )

    # ------------------------------------------------------------------ paths
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step}")

    def _local_step_dir(self, step: int) -> str | None:
        if self.local_dir is None:
            return None
        return os.path.join(self.local_dir, f"{_STEP_PREFIX}{step}")

    def _restore_tiers(self, step: int) -> list[tuple[str, str]]:
        """(tier_name, step_dir) candidates for restoring ``step``, fastest
        first: a committed local copy, then the persistent copy. The
        restore ladder walks these in order, falling through on corruption
        (``ckpt.corrupt`` per hop) before dropping to an earlier step."""
        out = []
        local = self._local_step_dir(step)
        if local is not None and os.path.exists(os.path.join(local, _META_FILE)):
            out.append(("local", local))
        if os.path.exists(os.path.join(self._step_dir(step), _META_FILE)):
            out.append(("persistent", self._step_dir(step)))
        return out

    def _committed_dir(self, step: int) -> str | None:
        """Preferred committed dir for ``step`` (local tier first), or
        None when the step is committed nowhere."""
        tiers = self._restore_tiers(step)
        return tiers[0][1] if tiers else None

    @staticmethod
    def _committed_in(root: str | None) -> list[int]:
        """Committed step numbers under one tier root (sorted)."""
        steps = []
        if root is None:
            return steps
        try:
            entries = os.listdir(root)
        except FileNotFoundError:
            return steps
        for name in entries:
            if name.startswith(_STEP_PREFIX) and not name.endswith(_STAGE_SUFFIX):
                try:
                    step = int(name[len(_STEP_PREFIX) :])
                except ValueError:
                    continue
                if os.path.exists(os.path.join(root, name, _META_FILE)):
                    steps.append(step)
        return sorted(steps)

    def _read_meta(self, step: int) -> dict | None:
        for _tier, sd in self._restore_tiers(step):
            try:
                with open(os.path.join(sd, _META_FILE)) as f:
                    return json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
        return None

    def _all_steps(self) -> list[int]:
        """Completed steps on disk — the union over tiers (an emergency
        save may exist only locally until its upload; a requeued attempt
        must still resume from it). No wait — safe on the saver thread."""
        steps = set(self._committed_in(self.directory))
        steps.update(self._committed_in(self.local_dir))
        return sorted(steps)

    def _best_step(self) -> int | None:
        best: tuple[float, int] | None = None
        sign = 1.0 if self.best_mode == "min" else -1.0
        for step in self._all_steps():
            meta = self._read_meta(step)
            if not meta:
                continue
            value = meta.get("metrics", {}).get(self.best_metric)
            if value is None:
                continue
            key = (sign * float(value), step)
            if best is None or key < best:
                best = key
        return best[1] if best else None

    def all_steps(self) -> list[int]:
        self.wait_until_finished()  # a step is visible once its save commits
        return self._all_steps()

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def best_step(self) -> int | None:
        """Step with the best recorded ``best_metric`` (↔ best_model.pt
        selection by val-loss improvement, my_ray_module.py:190-201)."""
        self.wait_until_finished()
        return self._best_step()

    def rewind_history(self, step: int) -> None:
        """Drop metrics-history entries NEWER than ``step``.

        The divergence auto-rollback (tpuflow.obs.health) restores
        ``step`` and replays the discarded trajectory; the replayed
        epochs re-save their steps, so without the rewind the embedded
        ``metrics_history`` would carry duplicate (and divergent-run)
        entries forever. Disk is untouched — any newer step dirs are the
        next save/retention cycle's problem."""
        self._metrics_history = [
            m for m in self._metrics_history if m.get("step", 0) <= step
        ]

    # ------------------------------------------------------------------ save
    def _drop_step_dir(self, step_dir: str) -> None:
        """Make one persistent-tier step dir invisible (metadata first),
        then recycle its payload pages (pool) or delete it."""
        if not os.path.isdir(step_dir):
            return
        try:
            os.unlink(os.path.join(step_dir, _META_FILE))
        except OSError:
            pass
        if self._pool is not None:
            self._pool.adopt_dir(step_dir)
        else:
            shutil.rmtree(step_dir, ignore_errors=True)

    def save(
        self,
        step: int,
        state,
        metrics: dict | None = None,
        *,
        data_state: dict | None = None,
        _upload: bool = True,
    ) -> Checkpoint:
        """Asynchronously save ``state`` (a pytree) for ``step`` with metrics.

        ↔ the reference's per-epoch torch.save + report(metrics, checkpoint)
        (my_ray_module.py:178-205). Blocks only if the previous async save is
        still in flight.

        Durability model (ISSUE 5): the whole save stages into
        ``step_K.tmp`` and becomes visible via ONE atomic rename at commit
        — no observer can ever see a committed-looking dir with a partial
        payload, and a killed writer's staging is reclaimed by the next
        manager's GC. With the local fast tier on (TPUFLOW_CKPT_LOCAL_DIR)
        the save stages and commits *locally*, then uploads to the
        persistent run dir off the training path (``ckpt.upload`` span).
        Every shard/manifest/marker write runs through the retrying I/O
        wrapper (raw.retry_io); a save whose retries exhaust fails THAT
        step's save cleanly at the next drain (``ckpt.save_failed``) —
        training continues from the previous committed step's durability.

        ``data_state``: opaque loader-cursor dict (epoch, batch index,
        shuffle seed) persisted in the step's metadata so resume replays
        the epoch's remaining batches exactly (deterministic mid-epoch
        resume).
        """
        self.wait_until_finished()
        final_dir = self._step_dir(step)
        local_final = self._local_step_dir(step)
        # With the local fast tier on, the save stages and COMMITS locally;
        # the persistent copy appears via the async upload below.
        commit_root = local_final if local_final is not None else final_dir
        stage_dir = commit_root + _STAGE_SUFFIX
        state_dir = os.path.join(stage_dir, _STATE_DIR)

        def _clean_stale() -> None:
            # A retried step must first become invisible in EVERY tier
            # (stale metadata gone) before its replacement is staged.
            self._drop_step_dir(final_dir)
            self._drop_step_dir(final_dir + _STAGE_SUFFIX)
            if local_final is not None:
                shutil.rmtree(local_final, ignore_errors=True)
                shutil.rmtree(stage_dir, ignore_errors=True)

        if jax.process_count() > 1:
            # Shared-directory mutation is process 0's job, fenced so no
            # other host is writing yet (first barrier) and none starts
            # before the cleanup is done (second barrier).
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("tpuflow_ckpt_save_prep")
            if jax.process_index() == 0:
                _clean_stale()
            multihost_utils.sync_global_devices("tpuflow_ckpt_save_prepped")
        else:
            _clean_stale()
        os.makedirs(stage_dir, exist_ok=True)
        metrics = {k: float(v) for k, v in (metrics or {}).items()}
        hist_entry = {"step": step, **metrics}
        self._metrics_history.append(hist_entry)
        meta = {
            "step": step,
            "metrics": metrics,
            "metrics_history": list(self._metrics_history),
            "process_count": jax.process_count(),
            "device_count": jax.device_count(),
        }
        if data_state is not None:
            meta["data_state"] = dict(data_state)
        if self.save_dtype is not None:
            state = _downcast(state, self.save_dtype)
            meta["save_dtype"] = self.save_dtype

        def _fail_cleanup() -> None:
            # This save died on a classified storage error: the step never
            # existed — drop its history entry and reclaim its staging.
            try:
                self._metrics_history.remove(hist_entry)
            except ValueError:
                pass
            if jax.process_index() == 0:
                self._drop_step_dir(final_dir + _STAGE_SUFFIX)
                if local_final is not None:
                    shutil.rmtree(stage_dir, ignore_errors=True)

        self._pending_fail = (step, _fail_cleanup)

        # Telemetry: one ckpt.save span from save() entry to commit
        # (payload durable + step visible), carrying bytes and derived
        # GB/s. Recorded on the saver thread at commit time — nothing
        # lands on the training critical path; the BASELINE ≥2 GB/s/chip
        # claim becomes a per-save recorded metric.
        _obs_rec = obs.recorder()
        _obs_t0 = time.monotonic()
        _obs_ts = time.time()
        _obs_bytes = _addressable_nbytes(state) if _obs_rec is not None else 0

        def _commit(merge: bool = False) -> None:
            # The step becomes visible only via the atomic stage→final
            # rename below, strictly after its payload is fully on disk —
            # ↔ Orbax's commit-marker semantics, hardened: a crash at ANY
            # point before the rename leaves only an invisible ``.tmp``
            # dir the next GC reclaims. Only then is retention applied, so
            # a crash never leaves fewer than ``max_to_keep`` complete
            # checkpoints.
            from tpuflow.ckpt import raw as raw_fmt

            if jax.process_index() == 0:
                if merge:
                    raw_fmt.merge_manifests(state_dir)
                if knobs.raw("TPUFLOW_FAULT"):
                    from tpuflow.testing import faults

                    if faults.partial_commit():
                        return  # simulated kill between payload and marker
                # Marker written INSIDE the staging dir (atomically), then
                # one rename publishes payload + metadata together. The
                # stage→replace write is the same helper the KV-page
                # store commits through (ISSUE 19) — one idiom, no drift.
                from tpuflow.infer import kv_store as kv_fmt

                marker = os.path.join(stage_dir, _META_FILE)
                raw_fmt.retry_io(
                    lambda: kv_fmt.atomic_write_json(marker, meta),
                    op="write_meta",
                    path=marker,
                )
                raw_fmt.retry_io(
                    lambda: os.replace(stage_dir, commit_root),
                    op="commit",
                    path=commit_root,
                )
            if _obs_rec is not None:
                dur = time.monotonic() - _obs_t0
                _obs_rec.record(
                    "span", "ckpt.save", ts=_obs_ts, dur_s=dur, step=step,
                    bytes=_obs_bytes,
                    gbps=_obs_bytes / dur / 1e9 if dur > 0 else 0.0,
                )
            if local_final is not None and jax.process_index() == 0:
                if _upload:
                    self._upload_step(step, local_final, final_dir)
                self._local_retain()
            self._retain()

        # RecyclePool files live on the persistent filesystem; with the
        # local tier staging on (typically) a different one, every take's
        # cross-device rename would fail and strand the popped pool file —
        # local-tier staging writes fresh pages instead.
        save_pool = self._pool if local_final is None else None
        if self.format == "raw":
            if jax.process_count() > 1:
                # Multi-host: every host writes its own shards; the commit
                # needs an all-hosts barrier (a collective), which must run
                # on the MAIN thread — it happens in wait_until_finished(),
                # which the next save()/restore()/query drains through.
                self._raw_saver.save(state_dir, state, pool=save_pool)
                self._pending_commit = lambda: _commit(merge=True)
            else:
                self._raw_saver.save(
                    state_dir, state, pool=save_pool, on_commit=_commit
                )
        else:
            # StandardCheckpointer.save is async: the commit marker must not
            # appear before the payload is durable, or a crash mid-write
            # leaves a visible-but-incomplete step that in-run resume would
            # pick and fail on. Defer the commit to the drain point (whose
            # first act is draining the async checkpointer) so async saves
            # still overlap with training, and multi-host commits get the
            # same success-exchange + visibility barriers as the raw path.
            self._ckptr.save(state_dir, state)
            self._pending_commit = lambda: _commit(merge=False)
        if not self._async:
            self.wait_until_finished()
        if _upload or local_final is None:
            handle_path, alts = final_dir, [local_final] if local_final else []
        else:
            handle_path, alts = local_final, [final_dir]
        return Checkpoint(path=handle_path, metadata=meta, alt_paths=alts)

    def _upload_step(self, step: int, src: str, dst: str) -> None:
        """Copy a committed local-tier step to the persistent run dir — on
        the saver thread (single-host) or at the deferred-commit drain
        (multi-host), never on the training critical path. The copy lands
        in ``dst.tmp`` and becomes visible via one atomic rename, so the
        persistent tier keeps the staged-commit guarantee. An upload that
        fails after retries leaves the step durable LOCALLY: recorded on
        the ``ckpt.upload`` span (ok=False), never fatal."""
        import errno as _errno

        from tpuflow.ckpt import raw as raw_fmt

        t0, ts0 = time.monotonic(), time.time()
        tmp = dst + _STAGE_SUFFIX

        def _copy() -> None:
            if knobs.raw("TPUFLOW_FAULT"):
                from tpuflow.testing import faults

                faults.maybe_upload_stall()
            shutil.rmtree(tmp, ignore_errors=True)
            try:
                shutil.copytree(src, tmp)
            except shutil.Error as e:  # multi-file copytree wrapper
                raise OSError(_errno.EIO, f"upload copy failed: {e}") from e
            os.replace(tmp, dst)

        err: str | None = None
        try:
            raw_fmt.retry_io(_copy, op="upload", path=dst)
        except raw_fmt.CheckpointIOError as e:
            err = str(e)[:300]
        rec = obs.recorder()
        if rec is not None:
            nbytes = 0
            try:
                sd = os.path.join(src, _STATE_DIR)
                if raw_fmt.is_raw(sd):
                    nbytes = sum(raw_fmt.manifest_shard_sizes(sd))
            except (OSError, ValueError, KeyError):
                pass
            dur = time.monotonic() - t0
            attrs: dict[str, Any] = {"step": step, "bytes": nbytes, "ok": err is None}
            if nbytes and dur > 0 and err is None:
                attrs["gbps"] = nbytes / dur / 1e9
            if err is not None:
                attrs["error"] = err
            rec.record("span", "ckpt.upload", ts=ts0, dur_s=dur, **attrs)

    def emergency_save(
        self,
        step: int,
        state,
        metrics: dict | None = None,
        *,
        data_state: dict | None = None,
    ) -> Checkpoint:
        """Last-chance checkpoint for a closing termination-grace window.

        Stages and commits SYNCHRONOUSLY on the fastest tier (local when
        configured) and skips the persistent upload — a requeued attempt
        on the same node resumes from this exact step instead of the last
        periodic save; the persistent copy appears when that attempt's
        next periodic save uploads normally. Records ``ckpt.emergency_save``
        with the estimated grace remaining. Called by the train-loop drain
        points when ``preempt.emergency_save_advised()``."""
        from tpuflow.utils.preempt import grace_remaining_s

        ckpt = self.save(
            step, state, metrics or {}, data_state=data_state, _upload=False
        )
        self.wait_until_finished()
        grace = grace_remaining_s()
        obs.event(
            "ckpt.emergency_save",
            step=step,
            tier="local" if self.local_dir else "persistent",
            ok=self._committed_dir(step) is not None,
            grace_s=round(grace, 3) if grace is not None else -1.0,
        )
        return ckpt

    def _retain(self) -> None:
        """Keep the newest ``max_to_keep`` steps plus the best step.

        Runs on the saver thread right after a save commits (saves are
        serialized by the wait in ``save()``, so every step seen here is
        complete). The keep-set is computed over the tier UNION (a
        local-only emergency step counts as newest), while deletion walks
        only persistent-committed dirs — the local tier has its own
        count-based retention (``_local_retain``)."""
        if self.max_to_keep is None or jax.process_index() != 0:
            return
        steps = self._all_steps()
        keep = set(steps[-self.max_to_keep :]) if self.max_to_keep else set()
        best = self._best_step()
        if best is not None:
            keep.add(best)
        for s in self._committed_in(self.directory):
            if s in keep:
                continue
            if self._pool is not None:
                self._pool.adopt_dir(self._step_dir(s))
            else:
                shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def _local_retain(self) -> None:
        """Local fast tier: newest ``TPUFLOW_CKPT_LOCAL_KEEP`` committed
        steps survive, oldest evicted first (plain deletes — the recycle
        pool lives on the persistent filesystem, a cross-device rename
        would copy). Bounds node-disk usage across requeue loops."""
        if self.local_dir is None or jax.process_index() != 0:
            return
        for s in self._committed_in(self.local_dir)[: -self.local_keep]:
            shutil.rmtree(
                os.path.join(self.local_dir, f"{_STEP_PREFIX}{s}"),
                ignore_errors=True,
            )

    def _save_failed(self, pending_fail, err: BaseException | None) -> None:
        """One save died on a *classified* storage error (retry budget
        exhausted or permanent errno): reclaim its staging, drop its
        history entry, record ``ckpt.save_failed`` — and return control to
        the loop. Losing one periodic checkpoint is recoverable (the
        previous committed step still restores); killing the member over
        it would cost the whole gang a requeue."""
        step = None
        if pending_fail is not None:
            step, cleanup = pending_fail
            try:
                cleanup()
            except OSError:
                pass
        obs.event(
            "ckpt.save_failed",
            step=step if step is not None else -1,
            error=str(err)[:300] if err is not None else "peer host",
        )
        print(
            f"[tpuflow] checkpoint save for step {step} failed after "
            f"retries; training continues on the previous committed step: "
            f"{err}"
        )

    def wait_until_finished(self) -> None:
        from tpuflow.ckpt import raw as raw_fmt

        pending = self._pending_commit
        self._pending_commit = None
        pending_fail = self._pending_fail
        self._pending_fail = None
        err: BaseException | None = None
        try:
            self._ckptr.wait_until_finished()
            self._raw_saver.wait()
        except BaseException as e:
            # Never publish a step whose writes failed.
            err = e
        # A CheckpointIOError is the retry wrapper's verdict: the storage
        # layer failed for good on THIS save. That fails the step's save
        # cleanly (ckpt.save_failed) instead of killing the member.
        soft = isinstance(err, raw_fmt.CheckpointIOError)
        if pending is not None:
            if jax.process_count() > 1:
                # Deferred multi-host commit. Before the commit barrier,
                # exchange a per-host verdict (1 = writes ok, 2 = this
                # host's save died on a classified storage error — abort
                # the commit uniformly but keep training everywhere, 0 =
                # hard failure — raise everywhere) so ONE host's failed
                # write aborts promptly instead of peers hanging in the
                # barrier until the collective timeout. (A fully dead peer
                # still costs the collective timeout; nothing shorter
                # exists.) SPMD contract: every process drains saves at
                # the same program points (report/restore/queries).
                import numpy as _np

                from jax.experimental import multihost_utils

                codes = _np.asarray(
                    multihost_utils.process_allgather(
                        _np.asarray(
                            1 if err is None else (2 if soft else 0),
                            _np.int32,
                        )
                    )
                )
                if (codes == 0).any():
                    if err is not None and not soft:
                        raise err
                    raise RuntimeError(
                        "checkpoint shard write failed on a peer host; "
                        "commit aborted on all hosts"
                    )
                if (codes == 2).any():
                    # Same branch on every host (same codes): no commit,
                    # no extra barrier needed — the allgather synchronized.
                    self._save_failed(pending_fail, err)
                    return
                # All hosts' local writes succeeded; barrier so the merged
                # manifest covers every host's shards.
                multihost_utils.sync_global_devices("tpuflow_ckpt_commit")
                pending()
                # Second barrier: no host may read the step (restore right
                # after a drain) until process 0 has written the merged
                # manifest and the metadata marker.
                multihost_utils.sync_global_devices("tpuflow_ckpt_committed")
                return
            elif err is None:
                pending()
        if err is not None:
            if soft:
                self._save_failed(pending_fail, err)
                return
            raise err

    def abandon_pending(self) -> None:
        """Drop a deferred multi-host commit that can no longer complete.

        An elastic-gang member loss (ISSUE 7) strands the in-flight save:
        the dead peer's shards will never arrive and the commit's
        success-allgather/barriers would raise (or hang) on every
        survivor. This joins THIS host's local shard writes only (no
        collectives), drops the step's metrics-history entry, and leaves
        the staged ``.tmp`` dir in place for the next manager's startup
        GC — deleting it here would race surviving peers whose saver
        threads are still writing into it. The resume point is the last
        FULLY committed step; ``ckpt.save_failed`` records the stranded
        one. Safe to call when nothing is pending (no-op), and leaves the
        manager clean for ``close()``."""
        pending = self._pending_commit
        self._pending_commit = None
        pending_fail = self._pending_fail
        self._pending_fail = None
        err: BaseException | None = None
        try:
            self._ckptr.wait_until_finished()
            self._raw_saver.wait()
        except BaseException as e:
            err = e
        if pending is None and err is None:
            return
        step = None
        if pending_fail is not None:
            step = pending_fail[0]
            for m in list(self._metrics_history):
                if m.get("step") == step:
                    self._metrics_history.remove(m)
                    break
        obs.event(
            "ckpt.save_failed",
            step=step if step is not None else -1,
            error=(
                str(err)[:300]
                if err is not None
                else "abandoned: mesh re-form (staging left for startup GC)"
            ),
        )

    def close(self) -> None:
        self.wait_until_finished()
        if self._pool is not None:
            # A still-running prewarm writing into <dir>/.recycle would race
            # callers that delete the run directory right after close().
            self._pool.cancel_prewarm()
        self._ckptr.close()
        # Terminal arena reclamation: a prewarm_restore whose restore never
        # ran (step errored, caller aborted) must not pin pre-backed pages
        # for the process lifetime — restore_raw's own cleanup only drops
        # LANDED buffers. abandon (not clear): the arena is
        # process-global, so a full clear() would first JOIN an unrelated
        # manager's in-flight background prewarm — closing one manager
        # must never block on another's multi-GB page-touch (ADVICE r3).
        # The generation bump makes an in-flight prewarm discard instead
        # of landing, so nothing stays pinned past this close; at worst
        # another live manager's prewarm is discarded (a lost
        # optimization, never correctness).
        from tpuflow.ckpt import raw as raw_fmt

        raw_fmt._ARENA.abandon()

    # --------------------------------------------------------------- restore
    def _resolve_step(self, step: int | None, best: bool) -> int:
        self.wait_until_finished()  # an in-flight save commits on its thread
        if step is None:
            steps = self._all_steps()
            chosen = self._best_step() if best else (steps[-1] if steps else None)
        else:
            chosen = step
        if chosen is None or (
            self._committed_dir(chosen) is None
            and not os.path.isdir(self._step_dir(chosen))
        ):
            raise FileNotFoundError(
                f"no checkpoint {'(best)' if best else ''} found in {self.directory}"
            )
        return chosen

    def restore(
        self,
        step: int | None = None,
        *,
        abstract_state=None,
        best: bool = False,
        zero_copy: bool = False,
    ):
        """Restore the full pytree for ``step`` (default: latest; ``best=True``
        picks by metric — the reference restores *best*, my_ray_module.py:255).

        ``abstract_state``: a pytree of ``jax.ShapeDtypeStruct`` (with
        shardings) or a template pytree of arrays. With shardings attached,
        Orbax places/reshards shards directly onto the current mesh — this is
        how a v5e-32-written checkpoint restores on v5e-16.

        ``zero_copy``: raw format only — restored arrays alias the mapped
        shard files (no read copy); see raw.restore_raw for the safety
        contract (read-only consumers of finished/owned runs).

        Integrity + tiers (ISSUE 5): raw-format shards are crc32-verified
        as they are read (``TPUFLOW_CKPT_VERIFY=0`` opts out). The restore
        walks a fallback ladder — crc-valid LOCAL copy (seconds after a
        same-node requeue) → persistent copy → previous committed step —
        recording ``ckpt.restore_tier`` for the tier that served and one
        ``ckpt.corrupt`` per rejected hop; with nothing left the last
        CorruptShardError propagates — corrupted weights are never
        silently returned.
        """
        from tpuflow.ckpt import raw as raw_fmt

        chosen = self._resolve_step(step, best)
        last_err: BaseException | None = None
        while True:
            for tier, sd in self._restore_tiers(chosen) or [
                ("persistent", self._step_dir(chosen))
            ]:
                state_dir = os.path.join(sd, _STATE_DIR)
                t0, ts0 = time.monotonic(), time.time()
                try:
                    if raw_fmt.is_raw(state_dir):
                        out = raw_fmt.restore_raw(
                            state_dir,
                            _abstractify(abstract_state)
                            if abstract_state is not None
                            else None,
                            zero_copy=zero_copy,
                        )
                    elif abstract_state is not None:
                        out = self._ckptr.restore(
                            state_dir, _abstractify(abstract_state)
                        )
                    else:
                        out = self._ckptr.restore(state_dir)
                except raw_fmt.CorruptShardError as e:
                    last_err = e
                    obs.event(
                        "ckpt.corrupt", step=chosen, tier=tier,
                        error=str(e)[:300],
                    )
                    print(
                        f"[tpuflow] checkpoint step {chosen} corrupt on the "
                        f"{tier} tier: {e}"
                    )
                    continue
                obs.event("ckpt.restore_tier", step=chosen, tier=tier)
                _record_restore(state_dir, t0, ts0, step=chosen)
                return out
            prev = [s for s in self._all_steps() if s < chosen]
            if not prev:
                if last_err is not None:
                    raise last_err
                raise FileNotFoundError(
                    f"no restorable copy of step {chosen} in {self.directory}"
                )
            print(
                f"[tpuflow] no valid copy of step {chosen}, falling back "
                f"to previous committed step {prev[-1]}"
            )
            chosen = prev[-1]

    def verify_step(self, step: int | None = None, *, best: bool = False) -> bool:
        """Audit one step's shard files against the manifest crc32s.

        Reads every shard byte once and recomputes the checksums (an
        explicit integrity audit — e.g. before promoting a checkpoint or
        after copying it across storage tiers), on the tier a restore
        would read first (local when present). Records a ``ckpt.verify``
        event with the outcome plus one ``ckpt.corrupt`` event per bad
        shard. Orbax-format steps and shards saved before integrity
        stamping verify vacuously. Returns True when every checked shard
        matches."""
        from tpuflow.ckpt import raw as raw_fmt

        chosen = self._resolve_step(step, best)
        tiers = self._restore_tiers(chosen)
        tier, sd = tiers[0] if tiers else ("persistent", self._step_dir(chosen))
        checked, bad = raw_fmt.verify_dir(os.path.join(sd, _STATE_DIR))
        obs.event(
            "ckpt.verify", step=chosen, shards=checked, ok=not bad, tier=tier
        )
        for fname in bad:
            obs.event("ckpt.corrupt", step=chosen, file=fname, tier=tier)
        return not bad

    def restore_metadata(self, step: int | None = None, *, best: bool = False) -> dict:
        chosen = self._resolve_step(step, best)
        meta = self._read_meta(chosen)
        if meta is None:
            raise FileNotFoundError(f"no metadata for step {chosen}")
        return meta

    def checkpoint(self, step: int | None = None, *, best: bool = False) -> Checkpoint:
        """A flow-level handle to a saved step (path + metadata, no
        tensors). The handle's primary path is the persistent copy (it may
        cross runs/nodes); a committed local copy rides along as an
        alternate path so same-node consumers restore from the fast tier
        when the persistent dir is gone or lagging."""
        chosen = self._resolve_step(step, best)
        meta = self._read_meta(chosen) or {}
        pers = self._step_dir(chosen)
        local = self._local_step_dir(chosen)
        alts = []
        if local is not None and os.path.exists(os.path.join(local, _META_FILE)):
            if os.path.exists(os.path.join(pers, _META_FILE)):
                alts = [local]
            else:
                pers, alts = local, []
        return Checkpoint(path=pers, metadata=meta, alt_paths=alts)


def _record_restore(
    state_dir: str,
    t0: float,
    ts0: float,
    *,
    step: int | None = None,
    subtree: tuple[str, ...] | None = None,
) -> None:
    """Record one ckpt.restore span ending now. ``bytes`` comes from the
    raw manifest (full checkpoint footprint, or the selected subtree's);
    Orbax-format restores record duration only. Restored device arrays may
    still be landing asynchronously, so the derived GB/s is a lower bound
    on wall time, not a device-fenced measurement."""
    rec = obs.recorder()
    if rec is None:
        return
    dur = time.monotonic() - t0
    nbytes = 0
    try:
        from tpuflow.ckpt import raw as raw_fmt

        if raw_fmt.is_raw(state_dir):
            nbytes = sum(raw_fmt.manifest_shard_sizes(state_dir, subtree))
    except (OSError, ValueError, KeyError):
        pass
    attrs: dict[str, Any] = {"bytes": nbytes}
    if step is not None:
        attrs["step"] = step
    if nbytes and dur > 0:
        attrs["gbps"] = nbytes / dur / 1e9
    rec.record("span", "ckpt.restore", ts=ts0, dur_s=dur, **attrs)


def _prewarm_state_dir(
    state_dir: str,
    *,
    subtree: tuple[str, ...] | None = None,
    background: bool = True,
) -> None:
    """Shared body of prewarm_restore / prewarm_restore_handle: back the
    restore arena for one raw-format state dir (no-op for non-raw dirs and
    under mmap mode, where restores never fill arena buffers)."""
    from tpuflow.ckpt import raw as raw_fmt

    if raw_fmt._mmap_enabled() or not raw_fmt.is_raw(state_dir):
        return
    raw_fmt._ARENA.prewarm(
        raw_fmt.manifest_shard_sizes(state_dir, subtree=subtree),
        background=background,
    )


def _downcast(state, dtype_name: str):
    """Cast floating leaves WIDER than ``dtype_name`` down to it (the
    reduced-precision save path; see CheckpointManager save_dtype). Integer
    and already-narrow leaves pass through untouched; works for jax arrays
    (device-side cast, sharding preserved) and host numpy alike."""
    import jax.numpy as jnp

    target = jnp.dtype(dtype_name)

    def cast(leaf):
        d = getattr(leaf, "dtype", None)
        if (
            d is not None
            and jnp.issubdtype(d, jnp.floating)
            and jnp.dtype(d).itemsize > target.itemsize
        ):
            return leaf.astype(target)
        return leaf

    return jax.tree_util.tree_map(cast, state)


def prewarm_restore_handle(
    checkpoint: Checkpoint, *, weights_only: bool = False
) -> None:
    """Background-prewarm the restore arena for a flow-level handle.

    Call as soon as a resume/eval checkpoint handle is known — the
    page-backing of the restore's destination buffers (raw.RestoreArena)
    then overlaps the mesh build / model init / compile that precedes the
    actual ``restore_from_handle``. ``weights_only`` must mirror the
    restore's flag so only the params subtree's buffers are backed.
    Best-effort: non-raw, non-local, or mmap-mode handles are a no-op.
    """
    try:
        _prewarm_state_dir(
            os.path.join(checkpoint.path, _STATE_DIR),
            subtree=("params",) if weights_only else None,
        )
    except (OSError, ValueError, KeyError, AttributeError):
        pass


def restore_from_handle(
    checkpoint: Checkpoint,
    *,
    abstract_state=None,
    weights_only: bool = False,
    subtree: tuple | None = None,
    zero_copy: bool = False,
):
    """Restore state from a flow-level ``Checkpoint`` handle (see
    ``_restore_from_handle_inner`` for semantics). Records one
    ``ckpt.restore`` telemetry span around the restore when obs is on."""
    from tpuflow.ckpt import raw as raw_fmt

    t0, ts0 = time.monotonic(), time.time()
    try:
        out = _restore_from_handle_inner(
            checkpoint,
            abstract_state=abstract_state,
            weights_only=weights_only,
            subtree=subtree,
            zero_copy=zero_copy,
        )
    except raw_fmt.CorruptShardError as e:
        # A handle pins ONE checkpoint — there is no previous step to fall
        # back to; record the corruption and let the error propagate.
        obs.event("ckpt.corrupt", error=str(e)[:300])
        raise
    if obs.enabled():
        acct_subtree = subtree or (("params",) if weights_only else None)
        _record_restore(
            os.path.join(checkpoint.path, _STATE_DIR), t0, ts0,
            subtree=tuple(acct_subtree) if acct_subtree else None,
        )
    return out


def _restore_from_handle_inner(
    checkpoint: Checkpoint,
    *,
    abstract_state=None,
    weights_only: bool = False,
    subtree: tuple | None = None,
    zero_copy: bool = False,
):
    """Restore state from a flow-level ``Checkpoint`` handle.

    ``weights_only=True`` is the parity semantic of the reference's
    ``set_weights_from_checkpoint`` (my_ray_module.py:253-264): only model
    params are returned — optimizer state and step are saved but deliberately
    not restored (§3.2 note) — while ``False`` gives the full-state resume the
    reference lacks. With ``weights_only=True``, ``abstract_state`` is the
    abstract **params** tree (shapes/dtypes/shardings); only that subtree is
    read from storage (partial restore), which is also what makes a
    checkpoint written on one topology load onto another here.
    """
    from tpuflow.ckpt import raw as raw_fmt

    with checkpoint.as_directory() as path:
        if not os.path.exists(os.path.join(path, _META_FILE)):
            # A handle returned by save() is valid only after the owning
            # manager's wait_until_finished() has committed the step (async
            # save / deferred multi-host commit). Fail fast with the real
            # reason instead of a confusing missing-manifest error deeper in.
            raise FileNotFoundError(
                f"checkpoint at {path} is not committed (no {_META_FILE}): "
                "the save that produced this handle has not finished — drain "
                "the CheckpointManager (wait_until_finished/close) before "
                "consuming the handle"
            )
        state_dir = os.path.join(path, _STATE_DIR)
        if raw_fmt.is_raw(state_dir):
            if weights_only or subtree is not None:
                params = raw_fmt.restore_raw(
                    state_dir,
                    # weights_only = the params subtree; an explicit subtree
                    # selects any other weight tree in the payload (e.g.
                    # ('ema_params',) for EMA evaluation).
                    subtree=subtree or ("params",),
                    zero_copy=zero_copy,
                )
                if abstract_state is not None:
                    abstract = _abstractify(abstract_state)
                    params = jax.tree_util.tree_map(
                        lambda arr, t: raw_fmt._place(
                            arr.astype(t.dtype)
                            if arr.dtype != t.dtype
                            else arr,
                            t.sharding,
                        )
                        if t.sharding is not None
                        else arr,
                        params,
                        abstract,
                    )
                return params
            return raw_fmt.restore_raw(
                state_dir,
                _abstractify(abstract_state) if abstract_state is not None else None,
                zero_copy=zero_copy,
            )
        if subtree is not None:
            # Only the raw format supports arbitrary-subtree partial
            # restores; silently returning the wrong tree (e.g. raw params
            # labeled as EMA) would be worse than failing.
            raise ValueError(
                "subtree selection requires the raw checkpoint format; "
                f"{state_dir} is Orbax-format"
            )
        if weights_only and abstract_state is not None:
            item = {"params": _abstractify(abstract_state)}
            ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
            try:
                out = ckptr.restore(
                    state_dir,
                    args=ocp.args.PyTreeRestore(
                        item=item,
                        restore_args=ocp.checkpoint_utils.construct_restore_args(
                            item
                        ),
                        partial_restore=True,
                    ),
                )
            finally:
                ckptr.close()
            return out["params"]
        ckptr = ocp.StandardCheckpointer()
        try:
            if abstract_state is not None:
                restored = ckptr.restore(state_dir, _abstractify(abstract_state))
            else:
                restored = ckptr.restore(state_dir)
        finally:
            ckptr.close()
    if weights_only:
        return restored["params"] if "params" in restored else restored
    return restored
