"""Async sharded checkpoint manager with best/latest policies and retention.

The TPU-native replacement for the reference checkpoint subsystem
(my_ray_module.py:178-205,236-238,253-264):

- per-epoch ``torch.save`` of ``{epoch, model_state_dict,
  optimizer_state_dict, val_losses, val_accuracy}``  →  async sharded Orbax
  save of the TrainState pytree (each host writes its shards; tensorstore
  OCDBT under the hood) plus a JSON metadata sidecar carrying the metrics
  history;
- duplicate ``latest_model.pt`` / ``best_model.pt`` files
  (my_ray_module.py:27-28,190-201)  →  *policies*: ``latest_step()`` /
  ``best_step()`` computed from recorded metrics — no duplicate bytes;
- ``CheckpointConfig(num_to_keep=2)`` retention (my_ray_module.py:222,236)
  →  retain the newest ``max_to_keep`` steps **plus** the best step (the
  reference keeps best reachable by writing it into every checkpoint dir);
- restore (my_ray_module.py:253-264: load best, strip the DDP ``module.``
  prefix, weights only)  →  ``restore(weights_only=True, best=True)``; the
  prefix-strip has no equivalent because params are a pytree, not
  name-mangled — the normalization the reference needs is a wrapper artifact;
- topology change: restore takes an abstract state (shapes + shardings) so a
  checkpoint written on one mesh restores, resharded, on another — the
  property the ≥2 GB/s/chip north-star metric presumes (SURVEY.md §5).

Save is asynchronous: training continues while hosts flush shards; ``save``
only blocks to drain a still-running *previous* save (double-buffering, the
same overlap Orbax's own manager provides).
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any

import jax
import orbax.checkpoint as ocp

from tpuflow import obs
from tpuflow.ckpt.handle import Checkpoint

_STATE_DIR = "state"
_META_FILE = "metadata.json"
_STEP_PREFIX = "step_"


def _addressable_nbytes(tree) -> int:
    """Bytes this process will actually write for ``tree``: replica-0
    addressable shards of device arrays (the save path's shard ownership,
    raw._leaf_shards) plus host leaves on process 0. The numerator of the
    recorded save GB/s — the same accounting the ≥2 GB/s/chip BASELINE
    claim uses, so the telemetry number is comparable to the bench's."""
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "addressable_shards"):
            total += sum(
                s.data.nbytes
                for s in leaf.addressable_shards
                if s.replica_id == 0
            )
        elif jax.process_index() == 0:
            if hasattr(leaf, "nbytes"):
                total += int(leaf.nbytes)
            else:
                total += np.asarray(leaf).nbytes
    return total


def _abstractify(tree):
    """Pytree of arrays/scalars/ShapeDtypeStructs → pytree of
    ShapeDtypeStructs (shardings preserved where present), tolerant of
    non-array leaves like a Python-int step counter."""
    import numpy as np

    def one(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)
            )
        arr = np.asarray(x)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    return jax.tree_util.tree_map(one, tree)


class CheckpointManager:
    """Manage per-step checkpoints under one directory.

    Layout::

        directory/
          step_3/
            state/          # Orbax OCDBT pytree (sharded arrays)
            metadata.json   # step, metrics, metrics_history, mesh info
          step_4/ ...
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int | None = 2,
        best_metric: str = "val_loss",
        best_mode: str = "min",
        async_save: bool = True,
        format: str = "auto",
        save_dtype: str | None = None,
    ):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.best_metric = best_metric
        self.best_mode = best_mode
        self._async = async_save
        # Reduced-precision checkpointing: cast floating leaves wider than
        # ``save_dtype`` down before writing (e.g. 'bfloat16' halves f32
        # checkpoint bytes — and doubles effective save/restore GB/s).
        # Restore-with-template casts back to the template dtype, so
        # training resumes in full precision from rounded values. Lossy by
        # design; leave None for bit-exact checkpoints. Integer leaves
        # (step counters, token ids) are never touched.
        if save_dtype is not None and save_dtype not in ("bfloat16", "float16"):
            raise ValueError(
                f"save_dtype must be None, 'bfloat16' or 'float16', "
                f"got {save_dtype!r}"
            )
        self.save_dtype = save_dtype
        # 'raw' = native striped-IO per-leaf files (fast path; needs fully
        # addressable leaves, i.e. single-host); 'orbax' = tensorstore OCDBT
        # (multi-host sharded writes). 'auto' picks raw when possible.
        format = os.environ.get("TPUFLOW_CKPT_FORMAT", format)
        if format == "auto":
            # The native raw format handles both single- and multi-host
            # states (each host writes its own shards); Orbax/ocdbt stays
            # available via TPUFLOW_CKPT_FORMAT=orbax.
            format = "raw"
        if format not in ("raw", "orbax"):
            raise ValueError(f"unknown checkpoint format {format!r}")
        self.format = format
        from tpuflow.ckpt.raw import AsyncRawSaver, RecyclePool

        self._raw_saver = AsyncRawSaver()
        # Retired step files are recycled (pages reused) instead of unlinked;
        # see RecyclePool. Orbax manages its own files, so raw-only.
        self._pool = (
            RecyclePool(os.path.join(self.directory, ".recycle"))
            if self.format == "raw"
            else None
        )
        self._ckptr = ocp.StandardCheckpointer()
        self._metrics_history: list[dict[str, Any]] = []
        self._pending_commit = None  # multi-host raw: commit deferred to drain
        # Multi-host: construction is collective (like every other manager
        # operation) — the barriers ensure no host is already writing while
        # process 0 sweeps, and no host starts writing before the sweep ends.
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("tpuflow_ckpt_mgr_preinit")
        self._sweep_orphans()
        if jax.process_count() > 1:
            multihost_utils.sync_global_devices("tpuflow_ckpt_mgr_swept")
        # Rebuild history from existing steps (in-run resume after retry).
        # The newest step's metadata embeds the FULL accumulated history —
        # including steps retention has since deleted — so a retried run's
        # metrics history stays continuous from the first save, not from
        # the oldest still-retained checkpoint.
        steps = self.all_steps()
        seen_steps: set[int] = set()
        if steps:
            newest = self._read_meta(steps[-1]) or {}
            for m in newest.get("metrics_history", []):
                if "step" in m:
                    self._metrics_history.append(dict(m))
                    seen_steps.add(m["step"])
        for step in steps:
            if step in seen_steps:
                continue
            meta = self._read_meta(step)
            if meta and "metrics" in meta:
                self._metrics_history.append({"step": step, **meta["metrics"]})
        self._metrics_history.sort(key=lambda m: m.get("step", 0))

    def prewarm(self, state) -> None:
        """Back recycle-pool pages for the steady-state footprint in the
        background.

        Call once the train state exists (before the first save): the
        page-backing cost of a process's first checkpoints — which on
        ballooning hypervisors dominates cold-save time ~15x — is paid by a
        background thread that overlaps real work (epoch-1 compute),
        instead of by the first ``save()``s. Pool files are created at the
        exact per-shard sizes this process's saves will request (so no
        truncation waste gets reclaimed by the host), sized to the
        retention footprint: ``max_to_keep`` live steps plus one in flight.
        No-op for the Orbax format and for already-warm pools.
        """
        if self._pool is None:
            return
        sizes = []
        for leaf in jax.tree_util.tree_leaves(state):
            if hasattr(leaf, "addressable_shards"):
                # replica_id==0 mirrors the save path's shard ownership
                # (raw._leaf_shards): replicated leaves count once.
                sizes += [
                    s.data.nbytes
                    for s in leaf.addressable_shards
                    if s.replica_id == 0
                ]
            elif hasattr(leaf, "nbytes") and jax.process_index() == 0:
                # Host/numpy leaves are written by process 0 only
                # (raw._leaf_shards) — other processes must not warm pages
                # no save of theirs will use.
                sizes.append(int(leaf.nbytes))
        # Footprint = max_to_keep newest steps + the pinned best step (which
        # retention keeps even when it falls out of the newest window) + one
        # save in flight.
        steps = (self.max_to_keep or 1) + (2 if self.best_metric else 1)
        self._pool.prewarm(sizes * steps)

    def prewarm_wait(self) -> None:
        if self._pool is not None:
            self._pool.prewarm_wait()

    def prewarm_restore(
        self, step: int | None = None, *, best: bool = False,
        background: bool = True,
    ) -> None:
        """Pre-back the destination buffers a ``restore`` of ``step`` will
        fill (restore-side twin of ``prewarm``; see raw.RestoreArena).

        Call as soon as the checkpoint to restore is known — before the
        work that naturally precedes the restore (dataset decode, mesh
        build, model compile) — and the first-touch page-backing cost of
        the restored state overlaps it on a background thread instead of
        serializing into the restore. No-op for Orbax-format steps.

        Contract: one restore per prewarm. The arena is process-global and
        restores serialize on a process-wide lock; a prewarm issued while
        another restore is in flight may lose (some of) its backing work
        to that restore's cleanup — the optimization silently degrades,
        correctness is unaffected.
        """
        try:
            chosen = self._resolve_step(step, best)
        except (ValueError, FileNotFoundError):
            return
        _prewarm_state_dir(
            os.path.join(self._step_dir(chosen), _STATE_DIR),
            background=background,
        )

    def prewarm_restore_wait(self) -> None:
        from tpuflow.ckpt import raw as raw_fmt

        raw_fmt._ARENA.prewarm_wait()

    def _sweep_orphans(self) -> None:
        """Reclaim step dirs whose save never committed (crash mid-write).

        Uncommitted dirs (no ``metadata.json``) are invisible to
        ``all_steps()`` and would otherwise leak storage forever; at manager
        construction no save is in flight, so every uncommitted dir here is a
        crash orphan — recycle (raw) or delete it."""
        if jax.process_index() != 0:
            return
        try:
            entries = os.listdir(self.directory)
        except FileNotFoundError:
            return
        for name in entries:
            if not name.startswith(_STEP_PREFIX):
                continue
            path = os.path.join(self.directory, name)
            if os.path.isdir(path) and not os.path.exists(
                os.path.join(path, _META_FILE)
            ):
                if self._pool is not None:
                    self._pool.adopt_dir(path)
                else:
                    shutil.rmtree(path, ignore_errors=True)

    # ------------------------------------------------------------------ paths
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step}")

    def _read_meta(self, step: int) -> dict | None:
        try:
            with open(os.path.join(self._step_dir(step), _META_FILE)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def _all_steps(self) -> list[int]:
        """Completed steps on disk (no wait — safe on the saver thread)."""
        steps = []
        try:
            entries = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in entries:
            if name.startswith(_STEP_PREFIX):
                try:
                    step = int(name[len(_STEP_PREFIX) :])
                except ValueError:
                    continue
                # Only completed saves count (state committed + metadata).
                if os.path.exists(os.path.join(self.directory, name, _META_FILE)):
                    steps.append(step)
        return sorted(steps)

    def _best_step(self) -> int | None:
        best: tuple[float, int] | None = None
        sign = 1.0 if self.best_mode == "min" else -1.0
        for step in self._all_steps():
            meta = self._read_meta(step)
            if not meta:
                continue
            value = meta.get("metrics", {}).get(self.best_metric)
            if value is None:
                continue
            key = (sign * float(value), step)
            if best is None or key < best:
                best = key
        return best[1] if best else None

    def all_steps(self) -> list[int]:
        self.wait_until_finished()  # a step is visible once its save commits
        return self._all_steps()

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def best_step(self) -> int | None:
        """Step with the best recorded ``best_metric`` (↔ best_model.pt
        selection by val-loss improvement, my_ray_module.py:190-201)."""
        self.wait_until_finished()
        return self._best_step()

    def rewind_history(self, step: int) -> None:
        """Drop metrics-history entries NEWER than ``step``.

        The divergence auto-rollback (tpuflow.obs.health) restores
        ``step`` and replays the discarded trajectory; the replayed
        epochs re-save their steps, so without the rewind the embedded
        ``metrics_history`` would carry duplicate (and divergent-run)
        entries forever. Disk is untouched — any newer step dirs are the
        next save/retention cycle's problem."""
        self._metrics_history = [
            m for m in self._metrics_history if m.get("step", 0) <= step
        ]

    # ------------------------------------------------------------------ save
    def save(self, step: int, state, metrics: dict | None = None) -> Checkpoint:
        """Asynchronously save ``state`` (a pytree) for ``step`` with metrics.

        ↔ the reference's per-epoch torch.save + report(metrics, checkpoint)
        (my_ray_module.py:178-205). Blocks only if the previous async save is
        still in flight.
        """
        self.wait_until_finished()
        step_dir = self._step_dir(step)
        state_dir = os.path.join(step_dir, _STATE_DIR)

        def _clean_stale() -> None:
            # A retried step must first become invisible (stale metadata
            # gone) before its old state is recycled and rewritten.
            try:
                os.unlink(os.path.join(step_dir, _META_FILE))
            except FileNotFoundError:
                pass
            if os.path.exists(state_dir):
                if self._pool is not None:
                    self._pool.adopt_dir(state_dir)  # recycle a retried step
                else:
                    shutil.rmtree(state_dir)

        if jax.process_count() > 1:
            # Shared-directory mutation is process 0's job, fenced so no
            # other host is writing yet (first barrier) and none starts
            # before the cleanup is done (second barrier).
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("tpuflow_ckpt_save_prep")
            if jax.process_index() == 0:
                _clean_stale()
            multihost_utils.sync_global_devices("tpuflow_ckpt_save_prepped")
        else:
            _clean_stale()
        os.makedirs(step_dir, exist_ok=True)
        metrics = {k: float(v) for k, v in (metrics or {}).items()}
        self._metrics_history.append({"step": step, **metrics})
        meta = {
            "step": step,
            "metrics": metrics,
            "metrics_history": list(self._metrics_history),
            "process_count": jax.process_count(),
            "device_count": jax.device_count(),
        }
        if self.save_dtype is not None:
            state = _downcast(state, self.save_dtype)
            meta["save_dtype"] = self.save_dtype

        # Telemetry: one ckpt.save span from save() entry to commit
        # (payload durable + step visible), carrying bytes and derived
        # GB/s. Recorded on the saver thread at commit time — nothing
        # lands on the training critical path; the BASELINE ≥2 GB/s/chip
        # claim becomes a per-save recorded metric.
        _obs_rec = obs.recorder()
        _obs_t0 = time.monotonic()
        _obs_ts = time.time()
        _obs_bytes = _addressable_nbytes(state) if _obs_rec is not None else 0

        def _commit(merge: bool = False) -> None:
            # The step becomes visible (metadata.json present) only once its
            # payload is fully on disk — ↔ Orbax's commit-marker semantics; a
            # crash mid-write leaves an invisible directory — and only then
            # is retention applied, so a crash never leaves fewer than
            # ``max_to_keep`` complete checkpoints. Retired files land in the
            # recycle pool in time for the *next* save to overwrite them.
            if jax.process_index() == 0:
                if merge:
                    from tpuflow.ckpt import raw as raw_fmt

                    raw_fmt.merge_manifests(state_dir)
                # Atomic marker: a crash mid-dump must not leave a visible
                # step with unreadable metadata.
                tmp = os.path.join(step_dir, _META_FILE + ".tmp")
                with open(tmp, "w") as f:
                    json.dump(meta, f)
                os.replace(tmp, os.path.join(step_dir, _META_FILE))
            self._retain()
            if _obs_rec is not None:
                dur = time.monotonic() - _obs_t0
                _obs_rec.record(
                    "span", "ckpt.save", ts=_obs_ts, dur_s=dur, step=step,
                    bytes=_obs_bytes,
                    gbps=_obs_bytes / dur / 1e9 if dur > 0 else 0.0,
                )

        if self.format == "raw":
            if jax.process_count() > 1:
                # Multi-host: every host writes its own shards; the commit
                # needs an all-hosts barrier (a collective), which must run
                # on the MAIN thread — it happens in wait_until_finished(),
                # which the next save()/restore()/query drains through.
                self._raw_saver.save(state_dir, state, pool=self._pool)
                self._pending_commit = lambda: _commit(merge=True)
            else:
                self._raw_saver.save(
                    state_dir, state, pool=self._pool, on_commit=_commit
                )
        else:
            # StandardCheckpointer.save is async: the commit marker must not
            # appear before the payload is durable, or a crash mid-write
            # leaves a visible-but-incomplete step that in-run resume would
            # pick and fail on. Defer the commit to the drain point (whose
            # first act is draining the async checkpointer) so async saves
            # still overlap with training, and multi-host commits get the
            # same success-exchange + visibility barriers as the raw path.
            self._ckptr.save(state_dir, state)
            self._pending_commit = lambda: _commit(merge=False)
        if not self._async:
            self.wait_until_finished()
        return Checkpoint(path=step_dir, metadata=meta)

    def _retain(self) -> None:
        """Keep the newest ``max_to_keep`` steps plus the best step.

        Runs on the saver thread right after a save commits (saves are
        serialized by the wait in ``save()``, so every step seen here is
        complete)."""
        if self.max_to_keep is None or jax.process_index() != 0:
            return
        steps = self._all_steps()
        keep = set(steps[-self.max_to_keep :]) if self.max_to_keep else set()
        best = self._best_step()
        if best is not None:
            keep.add(best)
        for s in steps:
            if s in keep:
                continue
            if self._pool is not None:
                self._pool.adopt_dir(self._step_dir(s))
            else:
                shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def wait_until_finished(self) -> None:
        pending = self._pending_commit
        self._pending_commit = None
        err: BaseException | None = None
        try:
            self._ckptr.wait_until_finished()
            self._raw_saver.wait()
        except BaseException as e:
            # Never publish a step whose writes failed.
            err = e
        if pending is not None:
            if jax.process_count() > 1:
                # Deferred multi-host commit. Before the commit barrier,
                # exchange a per-host success bit so ONE host's failed write
                # aborts the commit promptly and uniformly on ALL hosts —
                # instead of peers hanging in the barrier until the
                # collective timeout. (A fully dead peer still costs the
                # collective timeout; nothing shorter exists.) SPMD contract:
                # every process drains saves at the same program points
                # (report/restore/queries).
                import numpy as _np

                from jax.experimental import multihost_utils

                ok = multihost_utils.process_allgather(
                    _np.asarray(1 if err is None else 0, _np.int32)
                )
                if int(_np.min(ok)) == 0:
                    if err is not None:
                        raise err
                    raise RuntimeError(
                        "checkpoint shard write failed on a peer host; "
                        "commit aborted on all hosts"
                    )
                # All hosts' local writes succeeded; barrier so the merged
                # manifest covers every host's shards.
                multihost_utils.sync_global_devices("tpuflow_ckpt_commit")
                pending()
                # Second barrier: no host may read the step (restore right
                # after a drain) until process 0 has written the merged
                # manifest and the metadata marker.
                multihost_utils.sync_global_devices("tpuflow_ckpt_committed")
            elif err is None:
                pending()
        if err is not None:
            raise err

    def close(self) -> None:
        self.wait_until_finished()
        if self._pool is not None:
            # A still-running prewarm writing into <dir>/.recycle would race
            # callers that delete the run directory right after close().
            self._pool.cancel_prewarm()
        self._ckptr.close()
        # Terminal arena reclamation: a prewarm_restore whose restore never
        # ran (step errored, caller aborted) must not pin pre-backed pages
        # for the process lifetime — restore_raw's own cleanup only drops
        # LANDED buffers. abandon (not clear): the arena is
        # process-global, so a full clear() would first JOIN an unrelated
        # manager's in-flight background prewarm — closing one manager
        # must never block on another's multi-GB page-touch (ADVICE r3).
        # The generation bump makes an in-flight prewarm discard instead
        # of landing, so nothing stays pinned past this close; at worst
        # another live manager's prewarm is discarded (a lost
        # optimization, never correctness).
        from tpuflow.ckpt import raw as raw_fmt

        raw_fmt._ARENA.abandon()

    # --------------------------------------------------------------- restore
    def _resolve_step(self, step: int | None, best: bool) -> int:
        self.wait_until_finished()  # an in-flight save commits on its thread
        if step is None:
            steps = self._all_steps()
            chosen = self._best_step() if best else (steps[-1] if steps else None)
        else:
            chosen = step
        if chosen is None or not os.path.isdir(self._step_dir(chosen)):
            raise FileNotFoundError(
                f"no checkpoint {'(best)' if best else ''} found in {self.directory}"
            )
        return chosen

    def restore(
        self,
        step: int | None = None,
        *,
        abstract_state=None,
        best: bool = False,
        zero_copy: bool = False,
    ):
        """Restore the full pytree for ``step`` (default: latest; ``best=True``
        picks by metric — the reference restores *best*, my_ray_module.py:255).

        ``abstract_state``: a pytree of ``jax.ShapeDtypeStruct`` (with
        shardings) or a template pytree of arrays. With shardings attached,
        Orbax places/reshards shards directly onto the current mesh — this is
        how a v5e-32-written checkpoint restores on v5e-16.

        ``zero_copy``: raw format only — restored arrays alias the mapped
        shard files (no read copy); see raw.restore_raw for the safety
        contract (read-only consumers of finished/owned runs).

        Integrity: raw-format shards are crc32-verified as they are read
        (``TPUFLOW_CKPT_VERIFY=0`` opts out). A corrupted step records a
        ``ckpt.corrupt`` event and falls back to the newest earlier
        committed step; with no earlier step the CorruptShardError
        propagates — corrupted weights are never silently returned.
        """
        from tpuflow.ckpt import raw as raw_fmt

        chosen = self._resolve_step(step, best)
        while True:
            state_dir = os.path.join(self._step_dir(chosen), _STATE_DIR)
            t0, ts0 = time.monotonic(), time.time()
            try:
                if raw_fmt.is_raw(state_dir):
                    out = raw_fmt.restore_raw(
                        state_dir,
                        _abstractify(abstract_state)
                        if abstract_state is not None
                        else None,
                        zero_copy=zero_copy,
                    )
                elif abstract_state is not None:
                    out = self._ckptr.restore(
                        state_dir, _abstractify(abstract_state)
                    )
                else:
                    out = self._ckptr.restore(state_dir)
            except raw_fmt.CorruptShardError as e:
                obs.event("ckpt.corrupt", step=chosen, error=str(e)[:300])
                prev = [s for s in self._all_steps() if s < chosen]
                if not prev:
                    raise
                print(
                    f"[tpuflow] checkpoint step {chosen} corrupt, falling "
                    f"back to step {prev[-1]}: {e}"
                )
                chosen = prev[-1]
                continue
            _record_restore(state_dir, t0, ts0, step=chosen)
            return out

    def verify_step(self, step: int | None = None, *, best: bool = False) -> bool:
        """Audit one step's shard files against the manifest crc32s.

        Reads every shard byte once and recomputes the checksums (an
        explicit integrity audit — e.g. before promoting a checkpoint or
        after copying it across storage tiers). Records a ``ckpt.verify``
        event with the outcome plus one ``ckpt.corrupt`` event per bad
        shard. Orbax-format steps and shards saved before integrity
        stamping verify vacuously. Returns True when every checked shard
        matches."""
        from tpuflow.ckpt import raw as raw_fmt

        chosen = self._resolve_step(step, best)
        checked, bad = raw_fmt.verify_dir(
            os.path.join(self._step_dir(chosen), _STATE_DIR)
        )
        obs.event(
            "ckpt.verify", step=chosen, shards=checked, ok=not bad
        )
        for fname in bad:
            obs.event("ckpt.corrupt", step=chosen, file=fname)
        return not bad

    def restore_metadata(self, step: int | None = None, *, best: bool = False) -> dict:
        chosen = self._resolve_step(step, best)
        meta = self._read_meta(chosen)
        if meta is None:
            raise FileNotFoundError(f"no metadata for step {chosen}")
        return meta

    def checkpoint(self, step: int | None = None, *, best: bool = False) -> Checkpoint:
        """A flow-level handle to a saved step (path + metadata, no tensors)."""
        chosen = self._resolve_step(step, best)
        return Checkpoint(
            path=self._step_dir(chosen), metadata=self._read_meta(chosen) or {}
        )


def _record_restore(
    state_dir: str,
    t0: float,
    ts0: float,
    *,
    step: int | None = None,
    subtree: tuple[str, ...] | None = None,
) -> None:
    """Record one ckpt.restore span ending now. ``bytes`` comes from the
    raw manifest (full checkpoint footprint, or the selected subtree's);
    Orbax-format restores record duration only. Restored device arrays may
    still be landing asynchronously, so the derived GB/s is a lower bound
    on wall time, not a device-fenced measurement."""
    rec = obs.recorder()
    if rec is None:
        return
    dur = time.monotonic() - t0
    nbytes = 0
    try:
        from tpuflow.ckpt import raw as raw_fmt

        if raw_fmt.is_raw(state_dir):
            nbytes = sum(raw_fmt.manifest_shard_sizes(state_dir, subtree))
    except (OSError, ValueError, KeyError):
        pass
    attrs: dict[str, Any] = {"bytes": nbytes}
    if step is not None:
        attrs["step"] = step
    if nbytes and dur > 0:
        attrs["gbps"] = nbytes / dur / 1e9
    rec.record("span", "ckpt.restore", ts=ts0, dur_s=dur, **attrs)


def _prewarm_state_dir(
    state_dir: str,
    *,
    subtree: tuple[str, ...] | None = None,
    background: bool = True,
) -> None:
    """Shared body of prewarm_restore / prewarm_restore_handle: back the
    restore arena for one raw-format state dir (no-op for non-raw dirs and
    under mmap mode, where restores never fill arena buffers)."""
    from tpuflow.ckpt import raw as raw_fmt

    if raw_fmt._mmap_enabled() or not raw_fmt.is_raw(state_dir):
        return
    raw_fmt._ARENA.prewarm(
        raw_fmt.manifest_shard_sizes(state_dir, subtree=subtree),
        background=background,
    )


def _downcast(state, dtype_name: str):
    """Cast floating leaves WIDER than ``dtype_name`` down to it (the
    reduced-precision save path; see CheckpointManager save_dtype). Integer
    and already-narrow leaves pass through untouched; works for jax arrays
    (device-side cast, sharding preserved) and host numpy alike."""
    import jax.numpy as jnp

    target = jnp.dtype(dtype_name)

    def cast(leaf):
        d = getattr(leaf, "dtype", None)
        if (
            d is not None
            and jnp.issubdtype(d, jnp.floating)
            and jnp.dtype(d).itemsize > target.itemsize
        ):
            return leaf.astype(target)
        return leaf

    return jax.tree_util.tree_map(cast, state)


def prewarm_restore_handle(
    checkpoint: Checkpoint, *, weights_only: bool = False
) -> None:
    """Background-prewarm the restore arena for a flow-level handle.

    Call as soon as a resume/eval checkpoint handle is known — the
    page-backing of the restore's destination buffers (raw.RestoreArena)
    then overlaps the mesh build / model init / compile that precedes the
    actual ``restore_from_handle``. ``weights_only`` must mirror the
    restore's flag so only the params subtree's buffers are backed.
    Best-effort: non-raw, non-local, or mmap-mode handles are a no-op.
    """
    try:
        _prewarm_state_dir(
            os.path.join(checkpoint.path, _STATE_DIR),
            subtree=("params",) if weights_only else None,
        )
    except (OSError, ValueError, KeyError, AttributeError):
        pass


def restore_from_handle(
    checkpoint: Checkpoint,
    *,
    abstract_state=None,
    weights_only: bool = False,
    subtree: tuple | None = None,
    zero_copy: bool = False,
):
    """Restore state from a flow-level ``Checkpoint`` handle (see
    ``_restore_from_handle_inner`` for semantics). Records one
    ``ckpt.restore`` telemetry span around the restore when obs is on."""
    from tpuflow.ckpt import raw as raw_fmt

    t0, ts0 = time.monotonic(), time.time()
    try:
        out = _restore_from_handle_inner(
            checkpoint,
            abstract_state=abstract_state,
            weights_only=weights_only,
            subtree=subtree,
            zero_copy=zero_copy,
        )
    except raw_fmt.CorruptShardError as e:
        # A handle pins ONE checkpoint — there is no previous step to fall
        # back to; record the corruption and let the error propagate.
        obs.event("ckpt.corrupt", error=str(e)[:300])
        raise
    if obs.enabled():
        acct_subtree = subtree or (("params",) if weights_only else None)
        _record_restore(
            os.path.join(checkpoint.path, _STATE_DIR), t0, ts0,
            subtree=tuple(acct_subtree) if acct_subtree else None,
        )
    return out


def _restore_from_handle_inner(
    checkpoint: Checkpoint,
    *,
    abstract_state=None,
    weights_only: bool = False,
    subtree: tuple | None = None,
    zero_copy: bool = False,
):
    """Restore state from a flow-level ``Checkpoint`` handle.

    ``weights_only=True`` is the parity semantic of the reference's
    ``set_weights_from_checkpoint`` (my_ray_module.py:253-264): only model
    params are returned — optimizer state and step are saved but deliberately
    not restored (§3.2 note) — while ``False`` gives the full-state resume the
    reference lacks. With ``weights_only=True``, ``abstract_state`` is the
    abstract **params** tree (shapes/dtypes/shardings); only that subtree is
    read from storage (partial restore), which is also what makes a
    checkpoint written on one topology load onto another here.
    """
    from tpuflow.ckpt import raw as raw_fmt

    with checkpoint.as_directory() as path:
        if not os.path.exists(os.path.join(path, _META_FILE)):
            # A handle returned by save() is valid only after the owning
            # manager's wait_until_finished() has committed the step (async
            # save / deferred multi-host commit). Fail fast with the real
            # reason instead of a confusing missing-manifest error deeper in.
            raise FileNotFoundError(
                f"checkpoint at {path} is not committed (no {_META_FILE}): "
                "the save that produced this handle has not finished — drain "
                "the CheckpointManager (wait_until_finished/close) before "
                "consuming the handle"
            )
        state_dir = os.path.join(path, _STATE_DIR)
        if raw_fmt.is_raw(state_dir):
            if weights_only or subtree is not None:
                params = raw_fmt.restore_raw(
                    state_dir,
                    # weights_only = the params subtree; an explicit subtree
                    # selects any other weight tree in the payload (e.g.
                    # ('ema_params',) for EMA evaluation).
                    subtree=subtree or ("params",),
                    zero_copy=zero_copy,
                )
                if abstract_state is not None:
                    abstract = _abstractify(abstract_state)
                    params = jax.tree_util.tree_map(
                        lambda arr, t: raw_fmt._place(
                            arr.astype(t.dtype)
                            if arr.dtype != t.dtype
                            else arr,
                            t.sharding,
                        )
                        if t.sharding is not None
                        else arr,
                        params,
                        abstract,
                    )
                return params
            return raw_fmt.restore_raw(
                state_dir,
                _abstractify(abstract_state) if abstract_state is not None else None,
                zero_copy=zero_copy,
            )
        if subtree is not None:
            # Only the raw format supports arbitrary-subtree partial
            # restores; silently returning the wrong tree (e.g. raw params
            # labeled as EMA) would be worse than failing.
            raise ValueError(
                "subtree selection requires the raw checkpoint format; "
                f"{state_dir} is Orbax-format"
            )
        if weights_only and abstract_state is not None:
            item = {"params": _abstractify(abstract_state)}
            ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
            try:
                out = ckptr.restore(
                    state_dir,
                    args=ocp.args.PyTreeRestore(
                        item=item,
                        restore_args=ocp.checkpoint_utils.construct_restore_args(
                            item
                        ),
                        partial_restore=True,
                    ),
                )
            finally:
                ckptr.close()
            return out["params"]
        ckptr = ocp.StandardCheckpointer()
        try:
            if abstract_state is not None:
                restored = ckptr.restore(state_dir, _abstractify(abstract_state))
            else:
                restored = ckptr.restore(state_dir)
        finally:
            ckptr.close()
    if weights_only:
        return restored["params"] if "params" in restored else restored
    return restored
