"""Async sharded checkpoint manager with best/latest policies and retention.

The TPU-native replacement for the reference checkpoint subsystem
(my_ray_module.py:178-205,236-238,253-264):

- per-epoch ``torch.save`` of ``{epoch, model_state_dict,
  optimizer_state_dict, val_losses, val_accuracy}``  →  async sharded Orbax
  save of the TrainState pytree (each host writes its shards; tensorstore
  OCDBT under the hood) plus a JSON metadata sidecar carrying the metrics
  history;
- duplicate ``latest_model.pt`` / ``best_model.pt`` files
  (my_ray_module.py:27-28,190-201)  →  *policies*: ``latest_step()`` /
  ``best_step()`` computed from recorded metrics — no duplicate bytes;
- ``CheckpointConfig(num_to_keep=2)`` retention (my_ray_module.py:222,236)
  →  retain the newest ``max_to_keep`` steps **plus** the best step (the
  reference keeps best reachable by writing it into every checkpoint dir);
- restore (my_ray_module.py:253-264: load best, strip the DDP ``module.``
  prefix, weights only)  →  ``restore(weights_only=True, best=True)``; the
  prefix-strip has no equivalent because params are a pytree, not
  name-mangled — the normalization the reference needs is a wrapper artifact;
- topology change: restore takes an abstract state (shapes + shardings) so a
  checkpoint written on one mesh restores, resharded, on another — the
  property the ≥2 GB/s/chip north-star metric presumes (SURVEY.md §5).

Save is asynchronous: training continues while hosts flush shards; ``save``
only blocks to drain a still-running *previous* save (double-buffering, the
same overlap Orbax's own manager provides).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any

import jax
import orbax.checkpoint as ocp

from tpuflow.ckpt.handle import Checkpoint

_STATE_DIR = "state"
_META_FILE = "metadata.json"
_STEP_PREFIX = "step_"


def _abstractify(tree):
    """Pytree of arrays/scalars/ShapeDtypeStructs → pytree of
    ShapeDtypeStructs (shardings preserved where present), tolerant of
    non-array leaves like a Python-int step counter."""
    import numpy as np

    def one(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)
            )
        arr = np.asarray(x)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    return jax.tree_util.tree_map(one, tree)


class CheckpointManager:
    """Manage per-step checkpoints under one directory.

    Layout::

        directory/
          step_3/
            state/          # Orbax OCDBT pytree (sharded arrays)
            metadata.json   # step, metrics, metrics_history, mesh info
          step_4/ ...
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int | None = 2,
        best_metric: str = "val_loss",
        best_mode: str = "min",
        async_save: bool = True,
        format: str = "auto",
    ):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.best_metric = best_metric
        self.best_mode = best_mode
        self._async = async_save
        # 'raw' = native striped-IO per-leaf files (fast path; needs fully
        # addressable leaves, i.e. single-host); 'orbax' = tensorstore OCDBT
        # (multi-host sharded writes). 'auto' picks raw when possible.
        format = os.environ.get("TPUFLOW_CKPT_FORMAT", format)
        if format == "auto":
            format = "raw" if jax.process_count() == 1 else "orbax"
        if format not in ("raw", "orbax"):
            raise ValueError(f"unknown checkpoint format {format!r}")
        self.format = format
        from tpuflow.ckpt.raw import AsyncRawSaver

        self._raw_saver = AsyncRawSaver()
        self._ckptr = ocp.StandardCheckpointer()
        self._metrics_history: list[dict[str, Any]] = []
        # Rebuild history from existing steps (in-run resume after retry).
        for step in self.all_steps():
            meta = self._read_meta(step)
            if meta and "metrics" in meta:
                self._metrics_history.append({"step": step, **meta["metrics"]})

    # ------------------------------------------------------------------ paths
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step}")

    def _read_meta(self, step: int) -> dict | None:
        try:
            with open(os.path.join(self._step_dir(step), _META_FILE)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def all_steps(self) -> list[int]:
        steps = []
        try:
            entries = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in entries:
            if name.startswith(_STEP_PREFIX):
                try:
                    step = int(name[len(_STEP_PREFIX) :])
                except ValueError:
                    continue
                # Only completed saves count (state committed + metadata).
                if os.path.exists(os.path.join(self.directory, name, _META_FILE)):
                    steps.append(step)
        return sorted(steps)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def best_step(self) -> int | None:
        """Step with the best recorded ``best_metric`` (↔ best_model.pt
        selection by val-loss improvement, my_ray_module.py:190-201)."""
        best: tuple[float, int] | None = None
        sign = 1.0 if self.best_mode == "min" else -1.0
        for step in self.all_steps():
            meta = self._read_meta(step)
            if not meta:
                continue
            value = meta.get("metrics", {}).get(self.best_metric)
            if value is None:
                continue
            key = (sign * float(value), step)
            if best is None or key < best:
                best = key
        return best[1] if best else None

    # ------------------------------------------------------------------ save
    def save(self, step: int, state, metrics: dict | None = None) -> Checkpoint:
        """Asynchronously save ``state`` (a pytree) for ``step`` with metrics.

        ↔ the reference's per-epoch torch.save + report(metrics, checkpoint)
        (my_ray_module.py:178-205). Blocks only if the previous async save is
        still in flight.
        """
        self.wait_until_finished()
        step_dir = self._step_dir(step)
        state_dir = os.path.join(step_dir, _STATE_DIR)
        if os.path.exists(state_dir):
            shutil.rmtree(state_dir)  # overwrite a retried step cleanly
        os.makedirs(step_dir, exist_ok=True)
        if self.format == "raw":
            self._raw_saver.save(state_dir, state)
        else:
            self._ckptr.save(state_dir, state)
        if not self._async:
            self.wait_until_finished()
        metrics = {k: float(v) for k, v in (metrics or {}).items()}
        self._metrics_history.append({"step": step, **metrics})
        meta = {
            "step": step,
            "metrics": metrics,
            "metrics_history": self._metrics_history,
            "process_count": jax.process_count(),
            "device_count": jax.device_count(),
        }
        if jax.process_index() == 0:
            with open(os.path.join(step_dir, _META_FILE), "w") as f:
                json.dump(meta, f)
        self._retain()
        return Checkpoint(path=step_dir, metadata=meta)

    def _retain(self) -> None:
        """Keep the newest ``max_to_keep`` steps plus the best step."""
        if self.max_to_keep is None or jax.process_index() != 0:
            return
        steps = self.all_steps()
        keep = set(steps[-self.max_to_keep :]) if self.max_to_keep else set()
        best = self.best_step()
        if best is not None:
            keep.add(best)
        doomed = [s for s in steps if s not in keep]
        if doomed:
            # Never delete a dir whose async save may still be writing: saves
            # are serialized by the wait in save(), and metadata.json is only
            # written after the save call returns, so completed steps are safe
            # except possibly the newest — which is always in `keep`.
            for s in doomed:
                shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def wait_until_finished(self) -> None:
        self._ckptr.wait_until_finished()
        self._raw_saver.wait()

    def close(self) -> None:
        self.wait_until_finished()
        self._ckptr.close()

    # --------------------------------------------------------------- restore
    def _resolve_step(self, step: int | None, best: bool) -> int:
        chosen = (
            self.best_step() if best else self.latest_step()
        ) if step is None else step
        if chosen is None or not os.path.isdir(self._step_dir(chosen)):
            raise FileNotFoundError(
                f"no checkpoint {'(best)' if best else ''} found in {self.directory}"
            )
        return chosen

    def restore(
        self,
        step: int | None = None,
        *,
        abstract_state=None,
        best: bool = False,
    ):
        """Restore the full pytree for ``step`` (default: latest; ``best=True``
        picks by metric — the reference restores *best*, my_ray_module.py:255).

        ``abstract_state``: a pytree of ``jax.ShapeDtypeStruct`` (with
        shardings) or a template pytree of arrays. With shardings attached,
        Orbax places/reshards shards directly onto the current mesh — this is
        how a v5e-32-written checkpoint restores on v5e-16.
        """
        from tpuflow.ckpt import raw as raw_fmt

        chosen = self._resolve_step(step, best)
        state_dir = os.path.join(self._step_dir(chosen), _STATE_DIR)
        if raw_fmt.is_raw(state_dir):
            return raw_fmt.restore_raw(
                state_dir,
                _abstractify(abstract_state) if abstract_state is not None else None,
            )
        if abstract_state is not None:
            return self._ckptr.restore(state_dir, _abstractify(abstract_state))
        return self._ckptr.restore(state_dir)

    def restore_metadata(self, step: int | None = None, *, best: bool = False) -> dict:
        chosen = self._resolve_step(step, best)
        meta = self._read_meta(chosen)
        if meta is None:
            raise FileNotFoundError(f"no metadata for step {chosen}")
        return meta

    def checkpoint(self, step: int | None = None, *, best: bool = False) -> Checkpoint:
        """A flow-level handle to a saved step (path + metadata, no tensors)."""
        chosen = self._resolve_step(step, best)
        return Checkpoint(
            path=self._step_dir(chosen), metadata=self._read_meta(chosen) or {}
        )


def restore_from_handle(
    checkpoint: Checkpoint,
    *,
    abstract_state=None,
    weights_only: bool = False,
):
    """Restore state from a flow-level ``Checkpoint`` handle.

    ``weights_only=True`` is the parity semantic of the reference's
    ``set_weights_from_checkpoint`` (my_ray_module.py:253-264): only model
    params are returned — optimizer state and step are saved but deliberately
    not restored (§3.2 note) — while ``False`` gives the full-state resume the
    reference lacks. With ``weights_only=True``, ``abstract_state`` is the
    abstract **params** tree (shapes/dtypes/shardings); only that subtree is
    read from storage (partial restore), which is also what makes a
    checkpoint written on one topology load onto another here.
    """
    from tpuflow.ckpt import raw as raw_fmt

    with checkpoint.as_directory() as path:
        state_dir = os.path.join(path, _STATE_DIR)
        if raw_fmt.is_raw(state_dir):
            if weights_only:
                params = raw_fmt.restore_raw(state_dir, subtree=("params",))
                if abstract_state is not None:
                    abstract = _abstractify(abstract_state)
                    params = jax.tree_util.tree_map(
                        lambda arr, t: jax.device_put(
                            arr.astype(t.dtype)
                            if arr.dtype != t.dtype
                            else arr,
                            t.sharding,
                        )
                        if t.sharding is not None
                        else arr,
                        params,
                        abstract,
                    )
                return params
            return raw_fmt.restore_raw(
                state_dir,
                _abstractify(abstract_state) if abstract_state is not None else None,
            )
        if weights_only and abstract_state is not None:
            item = {"params": _abstractify(abstract_state)}
            ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
            try:
                out = ckptr.restore(
                    state_dir,
                    args=ocp.args.PyTreeRestore(
                        item=item,
                        restore_args=ocp.checkpoint_utils.construct_restore_args(
                            item
                        ),
                        partial_restore=True,
                    ),
                )
            finally:
                ckptr.close()
            return out["params"]
        ckptr = ocp.StandardCheckpointer()
        try:
            if abstract_state is not None:
                restored = ckptr.restore(state_dir, _abstractify(abstract_state))
            else:
                restored = ckptr.restore(state_dir)
        finally:
            ckptr.close()
    if weights_only:
        return restored["params"] if "params" in restored else restored
    return restored
