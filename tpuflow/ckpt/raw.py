"""'raw' checkpoint format: manifest + per-shard binary files via native IO.

The fast path of the checkpoint subsystem (the 2 GB/s/chip north-star
metric): every pytree leaf is written as its device shards — one file per
distinct shard, written/read by the striped multi-threaded native ckptio
(tpuflow/_native/io.cpp) — plus a JSON manifest carrying paths / shapes /
dtypes / shard index offsets. No chunking, no compression, no gather:

- sharded leaves (FSDP states) never materialize the full array on save;
  each shard's device-local bytes go straight to its own file, so per-chip
  write bandwidth adds up exactly like the production multi-host model;
- replicated leaves (DP params) are written ONCE (replica 0), not per
  device — the dedup torch.save gets for free and Orbax also applies;
- restore is topology-free: shards are reassembled (or passed through when a
  single shard covers the array) and placed with any target sharding;
- partial restore (e.g. the params subtree for weights-only warm starts)
  reads only the matching files.

Scope: leaves must be fully addressable (single-host runs, or replicated on
any topology). The manager automatically uses Orbax for multi-host sharded
state — both formats share the manager's layout and policies.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any

import jax
import numpy as np

from tpuflow import _native

MANIFEST = "manifest.json"
FORMAT_NAME = "tpuflow-raw-v2"


def _path_names(path) -> list[str]:
    names = []
    for entry in path:
        if hasattr(entry, "key"):
            names.append(str(entry.key))
        elif hasattr(entry, "name"):
            names.append(str(entry.name))
        elif hasattr(entry, "idx"):
            names.append(str(entry.idx))
        else:
            names.append(str(entry))
    return names


def _leaf_shards(leaf) -> list[tuple[list[int], np.ndarray]]:
    """(start_indices, host_array) per distinct shard of a leaf."""
    if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
        if not leaf.is_fully_addressable:
            raise ValueError(
                "raw format needs fully-addressable arrays; use format='orbax' "
                "for multi-host sharded state"
            )
        if leaf.sharding.is_fully_replicated:
            return [([0] * leaf.ndim, np.asarray(leaf.addressable_shards[0].data))]
        out = []
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue
            starts = [
                (s.start or 0) for s in shard.index
            ]
            out.append((starts, np.asarray(shard.data)))
        return out
    arr = np.asarray(leaf)
    return [([0] * arr.ndim, arr)]


def _gather_host(tree):
    """Synchronous device→host stage: (path, full_shape, dtype, shards)."""
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        shards = _leaf_shards(leaf)
        shape = list(getattr(leaf, "shape", shards[0][1].shape))
        out.append((_path_names(path), shape, shards[0][1].dtype.str, shards))
    return out


def _write_entries(directory: str, host_leaves) -> None:
    manifest = {"format": FORMAT_NAME, "leaves": []}
    for i, (names, shape, dtype, shards) in enumerate(host_leaves):
        entry = {"path": names, "shape": shape, "dtype": dtype, "shards": []}
        for j, (starts, arr) in enumerate(shards):
            fname = f"leaf_{i:05d}_{j:03d}.bin"
            _native.write_bytes(os.path.join(directory, fname), arr)
            entry["shards"].append(
                {"file": fname, "start": starts, "shape": list(arr.shape)}
            )
        manifest["leaves"].append(entry)
    with open(os.path.join(directory, MANIFEST), "w") as f:
        json.dump(manifest, f)


def save_raw(directory: str, tree: Any) -> None:
    """Write ``tree`` synchronously."""
    os.makedirs(directory, exist_ok=True)
    _write_entries(directory, _gather_host(tree))


class AsyncRawSaver:
    """Double-buffered async save: the device→host shard fetch happens
    synchronously (same contract as Orbax async — callers may donate device
    buffers immediately), file IO runs on a background thread."""

    def __init__(self):
        self._thread: threading.Thread | None = None
        self._error: list[BaseException] = []

    def save(self, directory: str, tree: Any) -> None:
        self.wait()
        os.makedirs(directory, exist_ok=True)
        host_leaves = _gather_host(tree)

        def _write():
            try:
                _write_entries(directory, host_leaves)
            except BaseException as e:  # surfaced on next wait()
                self._error.append(e)

        self._thread = threading.Thread(target=_write, daemon=True)
        self._thread.start()

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error:
            raise self._error.pop()


def is_raw(directory: str) -> bool:
    return os.path.exists(os.path.join(directory, MANIFEST))


def _read_manifest(directory: str) -> dict:
    with open(os.path.join(directory, MANIFEST)) as f:
        m = json.load(f)
    if m.get("format") != FORMAT_NAME:
        raise ValueError(f"{directory}: not a {FORMAT_NAME} checkpoint")
    return m


def _read_shard(directory: str, shard: dict, dtype: np.dtype) -> np.ndarray:
    nbytes = int(np.prod(shard["shape"]) * dtype.itemsize) if shard["shape"] else dtype.itemsize
    buf = _native.read_bytes(os.path.join(directory, shard["file"]), nbytes)
    return buf.view(dtype).reshape(shard["shape"])


def _read_leaf(directory: str, entry: dict) -> np.ndarray:
    dtype = np.dtype(entry["dtype"])
    shards = entry["shards"]
    if len(shards) == 1 and shards[0]["shape"] == entry["shape"]:
        return _read_shard(directory, shards[0], dtype)
    full = np.empty(entry["shape"], dtype)
    for shard in shards:
        idx = tuple(
            slice(start, start + dim)
            for start, dim in zip(shard["start"], shard["shape"])
        )
        full[idx] = _read_shard(directory, shard, dtype)
    return full


def restore_raw(
    directory: str,
    abstract_state: Any | None = None,
    *,
    subtree: tuple[str, ...] | None = None,
):
    """Restore a raw checkpoint.

    - With ``abstract_state`` (template pytree, same structure): leaves are
      matched in flatten order, cast to the template dtype and placed with
      the template's sharding when present.
    - Without a template: rebuilds a nested dict from manifest paths (works
      for dict-shaped trees like ``{"params": ...}``).
    - ``subtree``: restore only leaves whose path starts with this prefix,
      returned as the corresponding nested structure (partial restore).
    """
    manifest = _read_manifest(directory)
    entries = manifest["leaves"]
    if subtree is not None:
        entries = [
            e for e in entries if tuple(e["path"][: len(subtree)]) == subtree
        ]
        if not entries:
            raise KeyError(f"no leaves under {subtree} in {directory}")

    if abstract_state is not None and subtree is None:
        flat, treedef = jax.tree_util.tree_flatten(abstract_state)
        if len(flat) != len(entries):
            raise ValueError(
                f"template has {len(flat)} leaves, checkpoint {len(entries)}"
            )
        out = []
        for tmpl, entry in zip(flat, entries):
            arr = _read_leaf(directory, entry)
            dtype = getattr(tmpl, "dtype", None)
            if dtype is not None and arr.dtype != dtype:
                arr = arr.astype(dtype)
            sharding = getattr(tmpl, "sharding", None)
            out.append(
                jax.device_put(arr, sharding) if sharding is not None else arr
            )
        return jax.tree_util.tree_unflatten(treedef, out)

    # Path-based nested-dict reconstruction.
    root: dict = {}
    for entry in entries:
        names = entry["path"][len(subtree) :] if subtree else entry["path"]
        arr = _read_leaf(directory, entry)
        if not names:
            return arr  # the subtree was a single leaf
        node = root
        for name in names[:-1]:
            node = node.setdefault(name, {})
        node[names[-1]] = arr
    return root
