"""'raw' checkpoint format: manifest + per-shard binary files via native IO.

The fast path of the checkpoint subsystem (the 2 GB/s/chip north-star
metric): every pytree leaf is written as its device shards — one file per
distinct shard, written/read by the striped multi-threaded native ckptio
(tpuflow/_native/io.cpp) — plus a JSON manifest carrying paths / shapes /
dtypes / shard index offsets. No chunking, no compression, no gather:

- sharded leaves (FSDP states) never materialize the full array on save;
  each shard's device-local bytes go straight to its own file, so per-chip
  write bandwidth adds up exactly like the production multi-host model;
- replicated leaves (DP params) are written ONCE (replica 0), not per
  device — the dedup torch.save gets for free and Orbax also applies;
- restore is topology-free: shards are reassembled (or passed through when a
  single shard covers the array) and placed with any target sharding;
- partial restore (e.g. the params subtree for weights-only warm starts)
  reads only the matching files.

Multi-host: each process writes only the shards it owns (``replica_id == 0``
filter — disjoint across hosts, so per-host bandwidth adds up) plus a
manifest fragment; process 0 merges fragments into the unified manifest at
commit, after an all-hosts barrier. Restore reads only the files backing the
local devices of the target sharding. Orbax/ocdbt remains available via
``TPUFLOW_CKPT_FORMAT=orbax`` — both formats share the manager's layout and
policies.
"""

from __future__ import annotations

import errno
import json
import os
import random
import threading
import time
import weakref
import zlib
from typing import Any, Callable

import jax
import numpy as np

from tpuflow import _native
from tpuflow.utils import knobs

MANIFEST = "manifest.json"
FORMAT_NAME = "tpuflow-raw-v2"


class CorruptShardError(RuntimeError):
    """A shard file's bytes do not match the manifest (crc32 mismatch or
    truncation). Raised by restore-side verification so corrupted weights
    are never silently returned; the CheckpointManager catches it to fall
    back to the previous committed step."""


class CheckpointIOError(OSError):
    """A checkpoint storage operation failed for good: either a permanent
    error (EACCES, EROFS, ...) or a transient one that survived the whole
    retry budget (``retry_io``). The CheckpointManager treats a *save*
    dying this way as that step's save failing cleanly — partial staging
    reclaimed, ``ckpt.save_failed`` recorded, training continues — never
    as a member death; restores let it propagate (with tier/step fallback
    first)."""


# Errnos worth retrying: the storage layer hiccuped but the operation may
# well succeed on a fresh attempt (shared-filesystem brownouts, NFS/FUSE
# timeouts, device congestion). ENOSPC/EDQUOT are deliberately transient
# HERE: retention and the orphan GC free space between attempts, so "disk
# full" during a save is frequently a passing state, not a verdict.
_TRANSIENT_ERRNOS = frozenset(
    getattr(errno, name)
    for name in (
        "EIO", "EAGAIN", "EBUSY", "EINTR", "ETIMEDOUT", "ESTALE",
        "ENOSPC", "EDQUOT", "ENETDOWN", "ENETUNREACH", "ENETRESET",
        "ECONNRESET", "ECONNABORTED", "EREMOTEIO", "ENOLINK",
    )
    if hasattr(errno, name)
)

# Structural absence is a *semantic* outcome callers branch on (is this a
# committed step? does the subtree exist?), not a storage failure — those
# errors re-raise unchanged instead of being wrapped in CheckpointIOError.
_STRUCTURAL_ERRNOS = frozenset({errno.ENOENT, errno.ENOTDIR, errno.EISDIR})


def io_retries(default: int = 4) -> int:
    """Transient-failure retry budget per storage operation
    (``TPUFLOW_CKPT_IO_RETRIES``). 0 disables retrying; a malformed value
    falls back to ``default`` (checkpointing must never die on a typo'd
    env var mid-provisioning)."""
    env = knobs.raw("TPUFLOW_CKPT_IO_RETRIES")
    if env:
        try:
            return max(0, int(env))
        except ValueError:
            pass
    return default


def io_backoff_s(default: float = 0.05) -> float:
    """Base backoff before the first retry (``TPUFLOW_CKPT_IO_BACKOFF_S``);
    doubles per attempt with 50-100% jitter so a gang's writers don't
    hammer a recovering filesystem in lockstep."""
    env = knobs.raw("TPUFLOW_CKPT_IO_BACKOFF_S")
    if env:
        try:
            return max(0.0, float(env))
        except ValueError:
            pass
    return default


def io_transient(e: OSError) -> bool:
    """Transient-vs-permanent classification of one storage error. Errors
    without an errno (wrapper layers, some FUSE stacks) count as transient
    — retrying a permanent error wastes a bounded few attempts, while NOT
    retrying a transient one fails a save that would have succeeded."""
    return e.errno is None or e.errno in _TRANSIENT_ERRNOS


def retry_io(
    fn: Callable[[], Any],
    *,
    op: str,
    path: str = "",
    _sleep: Callable[[float], None] = time.sleep,
):
    """Run one storage operation with transient-error retries.

    Every shard read/write, manifest dump, fsync-ing rename and upload
    copy in the checkpoint fast path goes through here: transient
    ``OSError``s (see ``io_transient``) are retried up to ``io_retries()``
    times with jittered exponential backoff from ``io_backoff_s()``,
    recording one ``ckpt.io_retry`` event per attempt; a permanent error
    or an exhausted budget records ``ckpt.io_error`` and raises
    :class:`CheckpointIOError` (structural absence — ENOENT and friends —
    re-raises unchanged; ``CorruptShardError`` passes straight through:
    integrity failures are never retried, re-reading corrupt bytes cannot
    help). ``fn`` must be safe to re-run from scratch — every call site
    rewrites its file from the start.
    """
    from tpuflow import obs

    retries = io_retries()
    backoff = io_backoff_s()
    attempt = 0
    while True:
        attempt += 1
        try:
            if knobs.raw("TPUFLOW_FAULT"):
                from tpuflow.testing import faults

                faults.ckpt_io_fault(op, path)
            return fn()
        except CorruptShardError:
            raise
        except OSError as e:
            if isinstance(e, CheckpointIOError):
                raise  # a nested retry_io already classified + recorded it
            name = os.path.basename(path.rstrip(os.sep)) if path else ""
            if e.errno in _STRUCTURAL_ERRNOS:
                raise
            if not io_transient(e):
                obs.event(
                    "ckpt.io_error", op=op, path=name, errno=e.errno,
                    attempts=attempt, transient=False, error=str(e)[:200],
                )
                raise CheckpointIOError(
                    f"{op} {path or '<unknown>'}: permanent storage error: {e}"
                ) from e
            if attempt > retries:
                obs.event(
                    "ckpt.io_error", op=op, path=name, errno=e.errno,
                    attempts=attempt, transient=True, error=str(e)[:200],
                )
                raise CheckpointIOError(
                    f"{op} {path or '<unknown>'}: transient storage error "
                    f"persisted through {attempt} attempts: {e}"
                ) from e
            delay = backoff * (2 ** (attempt - 1)) * (0.5 + 0.5 * random.random())
            obs.event(
                "ckpt.io_retry", op=op, path=name, attempt=attempt,
                delay_s=round(delay, 4), error=str(e)[:200],
            )
            _sleep(delay)


def _verify_enabled() -> bool:
    """Restore-side integrity verification (per-shard crc32 recorded in
    the manifest at save). On by default; ``TPUFLOW_CKPT_VERIFY=0`` opts
    out (e.g. to reclaim the checksum pass on trusted local storage or to
    keep zero-copy restores from touching every page)."""
    return knobs.raw("TPUFLOW_CKPT_VERIFY", "1") not in ("0", "false")


def _crc32(arr: np.ndarray) -> int:
    a = np.ascontiguousarray(arr)
    try:
        buf = memoryview(a).cast("B")
    except (TypeError, ValueError):
        buf = a.tobytes()  # extended dtypes without a buffer interface
    return zlib.crc32(buf)


def _check_shard_bytes(path: str, shard: dict, buf, nbytes: int) -> None:
    """Compare just-read shard bytes against the manifest record; shards
    saved before integrity stamping (no ``crc32`` key) pass vacuously."""
    want = shard.get("crc32")
    if want is None:
        return
    got = zlib.crc32(buf)
    if got != int(want):
        raise CorruptShardError(
            f"{path}: crc32 mismatch (manifest {int(want)}, file {got}, "
            f"{nbytes} bytes) — shard corrupted on storage"
        )

# (st_dev, st_ino) -> live-mapping refcount for shard files whose mapped
# pages escaped to a caller via zero_copy restore in this process: live
# restored arrays alias those pages, so the recycle pool must never
# overwrite the inodes in place (adopt_dir/take unlink them instead — the
# pages outlive the unlink). Inode identity is immune to cwd changes and
# symlinked path spellings; refcounts are released by a finalizer when the
# mapping is garbage-collected, so a reused inode number is not excluded
# forever. The cross-PROCESS hazard (another process recycling the same
# checkpoint directory while this one holds mappings) is documented on
# restore_raw.
_ALIASED_INODES: dict[tuple[int, int], int] = {}
_ALIASED_LOCK = threading.Lock()


def _register_alias_fd(fd: int) -> tuple[int, int]:
    st = os.fstat(fd)
    key = (st.st_dev, st.st_ino)
    with _ALIASED_LOCK:
        _ALIASED_INODES[key] = _ALIASED_INODES.get(key, 0) + 1
    return key


def _unregister_alias(key: tuple[int, int]) -> None:
    with _ALIASED_LOCK:
        n = _ALIASED_INODES.get(key, 0)
        if n <= 1:
            _ALIASED_INODES.pop(key, None)
        else:
            _ALIASED_INODES[key] = n - 1


def _is_aliased(path: str) -> bool:
    try:
        st = os.stat(path)
    except OSError:
        return False
    with _ALIASED_LOCK:
        return (st.st_dev, st.st_ino) in _ALIASED_INODES


def _mmap_enabled() -> bool:
    """Opt-in zero-copy restore via file mapping (TPUFLOW_CKPT_MMAP=1).

    OFF by default for a correctness reason: ``jax.device_put`` on CPU
    zero-copy *aliases* page-aligned host memory, so an array restored from a
    mapped shard file shares pages with that file — and the recycle pool
    overwrites retired shard files in place, which would silently mutate the
    restored array. Only enable for strictly read-only consumers of finished
    runs (e.g. batch eval); while enabled, this process's managers unlink
    retired files instead of recycling them (see RecyclePool.adopt_dir).
    """
    return knobs.raw("TPUFLOW_CKPT_MMAP", "0") == "1"


def _spare_cores() -> int:
    """Cores available for BACKGROUND page-backing beyond the one the
    host compute thread occupies. Background prewarm only wins when its
    page touches run on cores compute isn't using; on a 1-core box it
    steals the only core and measures actively harmful (BENCH_r03
    prewarm_overlap: hidden_s -16.2 s, first save collapsed 8x). When
    this returns 0, background prewarms PARK their work: it runs only if
    a caller explicitly waits (prewarm_wait — that caller has nothing
    better to do with the core), else it never runs and the first save /
    restore pays exactly what it would have paid with no prewarm at all.
    Override: TPUFLOW_PREWARM_THREADS (0 parks, >=1 forces background).
    """
    env = knobs.raw("TPUFLOW_PREWARM_THREADS")
    if env is not None:
        try:
            return max(int(env), 0)
        except ValueError:
            pass
    return max((os.cpu_count() or 1) - 1, 0)


class RecyclePool:
    """Pool of retired shard files whose pages get reused by later saves.

    Retention hands doomed step directories to :meth:`adopt_dir`, which
    renames their ``.bin`` files into the pool instead of unlinking them;
    :meth:`take` hands a file back to a new save, which overwrites it in
    place (``write_bytes(..., inplace=True)``). On memory-backed storage
    (tmpfs staging tiers, page cache) this skips the fresh-page zeroing
    that otherwise dominates checkpoint write cost — steady-state per-epoch
    saves run at memcpy speed. Thread-safe: retention (main thread) and the
    async saver (background thread) share one pool.
    """

    def __init__(self, directory: str):
        self.directory = directory
        self._lock = threading.Lock()
        self._files: dict[int, list[str]] = {}  # size -> paths
        self._counter = 0
        self._warm_promised: dict[int, int] = {}
        self._warm_threads: list[threading.Thread] = []
        self._warm_cancel = threading.Event()
        self._deferred: list[int] = []  # sizes parked on a starved box
        if os.path.isdir(directory):
            for name in os.listdir(directory):
                path = os.path.join(directory, name)
                try:
                    self._files.setdefault(os.path.getsize(path), []).append(path)
                except OSError:
                    continue
                # Seed the name counter past every surviving pool file so a
                # restarted process never renames over a still-pooled inode.
                try:
                    self._counter = max(
                        self._counter, int(name[1:].split(".")[0])
                    )
                except (ValueError, IndexError):
                    self._counter += 1

    def adopt_dir(self, step_dir: str) -> None:
        """Absorb every ``.bin`` under ``step_dir`` and delete the rest."""
        import shutil

        if _mmap_enabled():
            # Restored arrays may alias these files' pages — never reuse
            # their inodes in place (see _mmap_enabled).
            shutil.rmtree(step_dir, ignore_errors=True)
            return
        # The step must become invisible before its payload is harvested: a
        # crash mid-adopt must not leave a committed-looking step with
        # missing shard files. (When adopting a bare state/ dir the caller
        # has already unlinked the metadata; this is then a no-op.)
        try:
            os.unlink(os.path.join(step_dir, "metadata.json"))
        except OSError:
            pass
        os.makedirs(self.directory, exist_ok=True)
        for root, _, names in os.walk(step_dir):
            for name in names:
                if not name.endswith(".bin"):
                    continue
                src = os.path.join(root, name)
                if _is_aliased(src):
                    # A live zero-copy restore maps this inode's pages:
                    # pooling it would let a later in-place overwrite mutate
                    # the restored arrays. rmtree below unlinks it instead
                    # (mapped pages outlive the unlink).
                    continue
                with self._lock:
                    self._counter += 1
                    dst = os.path.join(self.directory, f"r{self._counter:08d}.bin")
                    try:
                        size = os.path.getsize(src)
                        os.rename(src, dst)
                    except OSError:
                        continue
                    self._files.setdefault(size, []).append(dst)
        shutil.rmtree(step_dir, ignore_errors=True)

    def take(self, nbytes: int) -> str | None:
        """Pop a pooled file (exact-size match preferred) or None.

        Tiny requests (< 64 KiB, below the prewarm threshold) never draw
        from the pool: the in-place overwrite truncates the recycled file,
        so a small leaf would destroy a large warm file's pages for a
        fresh-write saving that is noise. The size-mismatch fallback
        likewise only hands out files at least as large as the request —
        their page prefix is reused and nothing warm is freed.
        """
        if nbytes < 64 * 1024:
            return None
        with self._lock:
            # Exact size first, then the smallest larger file (its page
            # prefix is reused; the truncated tail was surplus anyway).
            candidates = [nbytes] if nbytes in self._files else []
            candidates += sorted(
                s for s in self._files if s > nbytes
            )
            for size in candidates:
                bucket = self._files.get(size, [])
                while bucket:
                    path = bucket.pop()
                    if not bucket:
                        self._files.pop(size, None)
                    if _is_aliased(path):
                        # A live zero-copy mapping aliases this inode (it
                        # won the adopt/registration race): overwriting it
                        # in place would mutate restored arrays — unlink
                        # instead and keep looking.
                        try:
                            os.unlink(path)
                        except OSError:
                            pass
                        continue
                    return path
        return None

    def prewarm(self, sizes: list[int]) -> None:
        """Back pool pages for files of exactly ``sizes`` in the background.

        The first saves of a process's lifetime otherwise pay for growing
        the host's memory footprint (on ballooning hypervisors, first-touch
        of new guest pages runs ~15x slower than a steady-state write, and
        pages freed back to the host are reclaimed — so truncation waste
        re-pays the cost). Prewarming creates pool files of zeroed,
        *touched* pages at the exact shard sizes a save will request, while
        the caller does real work (epoch-1 compute in the trainer), so even
        the first checkpoint saves land on recycled pages at memcpy speed.
        Files enter the pool one by one — a save racing the prewarm simply
        consumes whatever is warm so far. Idempotent top-up: a repeated
        request only creates files not already pooled or being created by
        an in-flight prewarm (``_warm_promised`` tracks in-flight files
        only; fulfilled or failed promises are released, so a pool drained
        by saves can be topped up again). Sizes under 64 KiB are skipped
        (their fresh-write cost is noise).
        """
        sizes = sorted((s for s in sizes if s >= 64 * 1024), reverse=True)
        with self._lock:
            have: dict[int, int] = {
                s: len(v) for s, v in self._files.items()
            }
            for s, n in self._warm_promised.items():
                have[s] = have.get(s, 0) + n
            todo = []
            for s in sizes:
                if have.get(s, 0) > 0:
                    have[s] -= 1
                else:
                    todo.append(s)
                    self._warm_promised[s] = self._warm_promised.get(s, 0) + 1
            if not todo:
                return
            if _spare_cores() < 1:
                # Starved box: park the work instead of stealing the
                # compute core (see _spare_cores). Promises stay: a
                # repeated prewarm must not double-book the sizes.
                self._deferred.extend(todo)
                return
            t = threading.Thread(
                target=self._prewarm_run, args=(todo,), daemon=True
            )
            self._warm_threads.append(t)
        t.start()

    def _release_promise(self, size: int) -> None:
        n = self._warm_promised.get(size, 0)
        if n <= 1:
            self._warm_promised.pop(size, None)
        else:
            self._warm_promised[size] = n - 1

    def _prewarm_run(self, sizes: list[int]) -> None:
        os.makedirs(self.directory, exist_ok=True)
        # One small reused source buffer: its own pages get backed once,
        # while every written file page is a fresh first-touch (the cost
        # this thread exists to absorb off the save path).
        chunk = 32 * 2**20
        buf = b"\0" * chunk

        def abort(from_i: int, partial: str | None) -> None:
            # Drop the partial file and release every unfulfilled promise
            # so a later prewarm may retry (ENOSPC, cancel at close, ...).
            if partial is not None:
                try:
                    os.unlink(partial)
                except OSError:
                    pass
            with self._lock:
                for s in sizes[from_i:]:
                    self._release_promise(s)

        class _Cancelled(Exception):
            pass

        for i, size in enumerate(sizes):
            if self._warm_cancel.is_set():
                return abort(i, None)
            with self._lock:
                self._counter += 1
                path = os.path.join(self.directory, f"r{self._counter:08d}.bin")

            def write_warm_file() -> None:
                # Restart-from-scratch on retry ("wb" truncates): a partial
                # warm file must never enter the pool.
                with open(path, "wb", buffering=0) as f:
                    written = 0
                    while written < size:
                        if self._warm_cancel.is_set():
                            raise _Cancelled
                        f.write(buf[: min(chunk, size - written)])
                        written += min(chunk, size - written)

            try:
                # Through the retrying wrapper (ckpt.io_retry recorded):
                # a transient ENOSPC — retention/GC free space between
                # attempts — must not silently leave the warm file absent
                # and re-expose the first save to cold page-backing.
                retry_io(write_warm_file, op="prewarm", path=path)
            except _Cancelled:
                return abort(i, path)
            except (CheckpointIOError, OSError):
                return abort(i, path)
            with self._lock:
                self._files.setdefault(size, []).append(path)
                self._release_promise(size)

    def prewarm_wait(self, timeout: float | None = None) -> None:
        """Block until prewarmed files exist. ``timeout`` bounds the
        background-thread joins ONLY: on a starved box, parked work (see
        _spare_cores) executes in full on this caller's thread first,
        regardless of timeout."""
        with self._lock:
            threads = list(self._warm_threads)
            deferred, self._deferred = self._deferred, []
        if deferred:
            # The caller is blocking anyway — parked work (starved box,
            # see _spare_cores) runs here on the caller's own core.
            self._prewarm_run(sorted(deferred, reverse=True))
        for t in threads:
            t.join(timeout)

    def cancel_prewarm(self) -> None:
        """Stop in-flight prewarm promptly and join its threads (close());
        parked work is dropped, not executed."""
        self._warm_cancel.set()
        with self._lock:
            deferred, self._deferred = self._deferred, []
            for s in deferred:
                self._release_promise(s)
        self.prewarm_wait()
        self._warm_cancel.clear()

    def clear(self) -> None:
        import shutil

        self.cancel_prewarm()
        with self._lock:
            self._files.clear()
            self._warm_promised.clear()
            shutil.rmtree(self.directory, ignore_errors=True)


class RestoreArena:
    """Pre-backed destination buffers for restore reads.

    The restore-side mirror of the save-side ``RecyclePool``: on ballooning
    hypervisors the dominant cost of a cold restore is not moving the bytes
    but *backing the destination pages* (first-touch of fresh anonymous
    memory runs ~10x slower than memcpy on the dev host). The arena
    allocates and touches page-aligned buffers ahead of time — on a
    background thread that overlaps real startup work (data pipeline build,
    model compile) — and hands each out exactly once; ``jax.device_put`` on
    CPU then aliases the buffer zero-copy, so the restore critical path is a
    single page-cache memcpy into already-backed pages.

    Ownership is transfer-only: a taken buffer never returns to the arena
    (its pages belong to the restored array), so there is no reuse-while-
    aliased hazard. Sizes must match exactly — shard sizes are deterministic
    from the manifest, which is what ``prewarm`` is fed from. One restore
    per prewarm: ``restore_raw`` drops any unconsumed buffers when it
    finishes, so a prewarm whose restore took another shape (template
    mismatch, partial subtree, mmap) costs its backing work but never pins
    memory past the restore.
    """

    def __init__(self):
        self._buffers: dict[int, list[np.ndarray]] = {}
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        # Serializes background-prewarm spawns: without it two concurrent
        # prewarm() calls can race on self._thread and join a thread that
        # was created but not yet started.
        self._spawn_lock = threading.Lock()
        self._deferred: list[int] = []  # sizes parked on a starved box
        # Bumped by abandon(): an in-flight _back from an older generation
        # discards instead of landing — terminal reclamation without the
        # multi-GB join.
        self._gen = 0

    def prewarm(self, sizes: list[int], *, background: bool = True) -> None:
        """Allocate + page-back one buffer per entry of ``sizes``."""
        sizes = [int(s) for s in sizes if s > 0]
        if not sizes:
            return

        gen = self._gen

        def _run():
            self._back(sizes, gen)

        if background:
            if _spare_cores() < 1:
                # Starved box: park the work instead of stealing the
                # compute core (see _spare_cores); it runs only if a
                # caller explicitly blocks in prewarm_wait.
                with self._lock:
                    self._deferred.extend(sizes)
                return
            # One prewarm in flight at a time. The join of the previous
            # thread happens OUTSIDE the lock (it can last a multi-GB
            # page-touch), so prewarm_wait's brief locked read stays
            # bounded; the loop re-checks after joining because another
            # spawner may have won the slot meanwhile.
            while True:
                with self._spawn_lock:
                    prev = self._thread
                    if prev is None or not prev.is_alive():
                        t = threading.Thread(
                            target=_run,
                            name="tpuflow-restore-arena",
                            daemon=True,
                        )
                        t.start()  # started BEFORE publication: joiners
                        self._thread = t  # never see an unstarted thread
                        return
                prev.join()
        else:
            _run()

    def _back(self, sizes: list[int], gen: int | None = None) -> None:
        for s in sizes:
            with self._lock:
                if gen is not None and gen != self._gen:
                    return  # abandon()ed mid-flight: discard, don't land
            buf = _native.aligned_empty(s)
            buf[::4096] = 0  # touch every page: back it now, not at read
            if s % 4096:
                buf[-1] = 0
            with self._lock:
                if gen is not None and gen != self._gen:
                    return
                self._buffers.setdefault(s, []).append(buf)

    def prewarm_wait(self, timeout: float | None = None) -> None:
        """Block until prewarmed buffers have landed. ``timeout`` bounds
        the background-thread join ONLY: on a starved box, parked work
        (see _spare_cores) executes in full on this caller's thread
        first, regardless of timeout."""
        with self._lock:
            deferred, self._deferred = self._deferred, []
            gen = self._gen
        if deferred:
            # The caller is blocking anyway — parked work (starved box,
            # see _spare_cores) runs here on the caller's own core.
            self._back(deferred, gen)
        with self._spawn_lock:
            t = self._thread
        if t is not None:
            t.join(timeout)
            if not t.is_alive():
                with self._spawn_lock:
                    # Compare-and-swap: never clobber a spawn published
                    # after our read — losing the only reference to an
                    # in-flight prewarm would let clear() skip its join
                    # and leak the buffers it lands afterwards.
                    if self._thread is t:
                        self._thread = None

    def take(self, nbytes: int) -> np.ndarray | None:
        """Pop a pre-backed buffer of exactly ``nbytes``, else None."""
        with self._lock:
            stack = self._buffers.get(int(nbytes))
            return stack.pop() if stack else None

    def drop_present(self) -> None:
        """Drop buffers that have LANDED plus any parked (never-started)
        work, without joining an in-flight background prewarm — its
        still-unlanded buffers survive (they belong to the next
        restore). End-of-restore cleanup uses this."""
        with self._lock:
            self._buffers.clear()
            self._deferred.clear()

    def abandon(self) -> None:
        """Terminal reclamation without blocking: drop landed + parked
        buffers AND make any in-flight background prewarm discard its
        remaining work instead of landing it (generation bump — the
        thread keeps running but appends nothing). Used by
        CheckpointManager.close(): joining a possibly multi-GB page-touch
        there would block one manager's close on another's prewarm, while
        plain drop_present would let buffers landing moments later stay
        pinned for the process lifetime."""
        with self._lock:
            self._gen += 1
            self._buffers.clear()
            self._deferred.clear()

    def clear(self) -> None:
        with self._lock:
            self._deferred.clear()  # drop parked work, don't execute it
        self.prewarm_wait()
        with self._lock:
            self._buffers.clear()


_ARENA = RestoreArena()
# Process-wide restore serialization (see restore_raw): the arena hand-off
# and its end-of-restore cleanup are only safe one restore at a time.
_RESTORE_LOCK = threading.RLock()


def _path_names(path) -> list[str]:
    names = []
    for entry in path:
        if hasattr(entry, "key"):
            names.append(str(entry.key))
        elif hasattr(entry, "name"):
            names.append(str(entry.name))
        elif hasattr(entry, "idx"):
            names.append(str(entry.idx))
        else:
            names.append(str(entry))
    return names


def _leaf_shards(leaf) -> list[tuple[list[int], np.ndarray]]:
    """(start_indices, host_array) per locally-owned shard of a leaf.

    Ownership = ``replica_id == 0``: across the whole mesh exactly one copy
    of every distinct shard has replica 0, so N hosts each write only their
    own disjoint shard set (per-host write bandwidth adds up — the
    multi-host production model the ≥2 GB/s/chip target presumes) and
    replicated leaves are written exactly once globally. A host owning no
    replica-0 shard of a leaf returns [] for it.
    """
    if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
        out = []
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue
            starts = [
                (s.start or 0) for s in shard.index
            ]
            out.append((starts, np.asarray(shard.data)))
        return out
    # Non-jax leaves (host scalars, plain numpy) exist identically on every
    # process: the same ownership rule applies — process 0 writes, the rest
    # contribute no shard (otherwise N hosts race on one shared file).
    if jax.process_index() != 0:
        return []
    arr = np.asarray(leaf)
    return [([0] * arr.ndim, arr)]


def _dtype_str(d) -> str:
    """Manifest dtype spelling. Extended types (bfloat16, float8_*) have a
    raw-void ``.str`` ('<V2') that loses the type identity — their ``.name``
    parses back via the ml_dtypes registry; standard dtypes keep the
    endianness-explicit ``.str``."""
    d = np.dtype(d)
    return d.name if d.kind == "V" else d.str


def _gather_host(tree):
    """Device→host stage: (path, full_shape, dtype, shards).

    Every process lists every leaf (the pytree is global), each with only
    its locally-owned shards — possibly none on this process.

    All owned shards start their device→host copies ASYNC up front, then
    materialize in order: on real accelerators the DMA of shard N+1
    overlaps the numpy materialization of shard N instead of each
    ``np.asarray`` paying a serial round trip (a no-op on the CPU
    backend, where the buffers are already host-resident)."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    prefetch = True
    for _, leaf in leaves:
        if not prefetch:
            break
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            for shard in leaf.addressable_shards:
                if shard.replica_id == 0:
                    try:
                        shard.data.copy_to_host_async()
                    except (AttributeError, RuntimeError):
                        # Platform without async D2H: abandon the whole
                        # prefetch (not just this leaf) — the sync path
                        # below handles everything.
                        prefetch = False
                        break
    out = []
    for path, leaf in leaves:
        shards = _leaf_shards(leaf)
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            shape, dtype = list(leaf.shape), _dtype_str(leaf.dtype)
        else:
            # Pure-Python scalar/list leaves: derive shape/dtype the same way
            # _leaf_shards does, so processes that own no shard of the leaf
            # (every rank but 0) still emit a valid manifest entry.
            arr = np.asarray(leaf)
            shape, dtype = list(arr.shape), _dtype_str(arr.dtype)
        out.append((_path_names(path), shape, dtype, shards))
    return out


def _write_one(directory: str, fname: str, arr, pool: RecyclePool | None) -> None:
    dst = os.path.join(directory, fname)

    def attempt() -> None:
        recycled = pool.take(arr.nbytes) if pool is not None else None
        if recycled is not None:
            try:
                os.rename(recycled, dst)
                _native.write_bytes(dst, arr, inplace=True)
                return
            except OSError:
                pass  # fall through to a fresh write
        _native.write_bytes(dst, arr)

    retry_io(attempt, op="write_shard", path=dst)
    if knobs.raw("TPUFLOW_FAULT"):
        from tpuflow.testing import faults

        faults.corrupt_after_write(dst)


def _fs_is_memory_backed(path: str) -> bool:
    """True when ``path`` lives on tmpfs/ramfs (fsync is free there)."""
    try:
        best, fstype = "", ""
        path = os.path.abspath(path)
        with open("/proc/mounts") as f:
            for line in f:
                parts = line.split()
                if len(parts) < 3:
                    continue
                mnt = parts[1]
                # Path-boundary match: /run must not claim /runtime/ckpt.
                if (mnt == "/" or path == mnt or
                        path.startswith(mnt + "/")) and len(mnt) > len(best):
                    best, fstype = mnt, parts[2]
        return fstype in ("tmpfs", "ramfs")
    except OSError:
        return False


def _write_entries(
    directory: str, host_leaves, pool: RecyclePool | None = None
) -> None:
    """Write this process's shards. Single-process: the unified manifest is
    written directly. Multi-process: each process writes a manifest FRAGMENT
    (``manifest.p<rank>.json``) listing only the shards it owns; process 0
    merges fragments at commit time (``merge_manifests``) after the
    cross-process barrier, so the unified manifest — and hence step
    visibility — appears only once every host's shards are on storage.

    On memory-backed storage files are written sequentially (each write is
    already striped across threads, and fsync costs nothing). On real disks
    the per-file fsync waits on the device, so files are pipelined through a
    small thread pool: the memcpy of file N+1 overlaps the flush of file N
    (ctypes releases the GIL for the native write). Override the pool width
    with TPUFLOW_WRITE_CONCURRENCY; 1 forces sequential."""
    manifest = {
        "format": FORMAT_NAME,
        "process_count": jax.process_count(),
        "leaves": [],
    }
    jobs: list[tuple[str, Any]] = []
    for i, (names, shape, dtype, shards) in enumerate(host_leaves):
        entry = {"path": names, "shape": shape, "dtype": dtype, "shards": []}
        for starts, arr in shards:
            # Start coordinates are globally unique per distinct shard, so
            # hosts never collide on names and the merge is a plain union.
            coord = "x".join(map(str, starts)) or "0"
            fname = f"leaf_{i:05d}_{coord}.bin"
            jobs.append((fname, arr))
            entry["shards"].append(
                {
                    "file": fname,
                    "start": starts,
                    "shape": list(arr.shape),
                    # Content-integrity stamp, verified on restore
                    # (_check_shard_bytes). Computed here — on the async
                    # saver's thread — so the checksum pass never lands on
                    # the training critical path.
                    "crc32": _crc32(arr),
                }
            )
        manifest["leaves"].append(entry)
    width = int(knobs.raw("TPUFLOW_WRITE_CONCURRENCY", "0")) or (
        1 if _fs_is_memory_backed(directory) else 4
    )
    if width <= 1 or len(jobs) <= 1:
        for fname, arr in jobs:
            _write_one(directory, fname, arr, pool)
    else:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(width, len(jobs))) as ex:
            futures = [
                ex.submit(_write_one, directory, fname, arr, pool)
                for fname, arr in jobs
            ]
            for fut in futures:
                fut.result()  # propagate the first write error
    if jax.process_count() > 1:
        frag = os.path.join(directory, f"manifest.p{jax.process_index():05d}.json")

        def write_frag() -> None:
            with open(frag + ".tmp", "w") as f:
                json.dump(manifest, f)
            os.replace(frag + ".tmp", frag)

        retry_io(write_frag, op="write_manifest", path=frag)
        return
    unified = os.path.join(directory, MANIFEST)

    def write_unified() -> None:
        with open(unified, "w") as f:
            json.dump(manifest, f)

    retry_io(write_unified, op="write_manifest", path=unified)


def merge_manifests(directory: str, *, visibility_timeout_s: float = 10.0) -> None:
    """Union all manifest fragments into the unified manifest (process 0,
    after the all-hosts barrier). Fragments agree on leaf order/shape/dtype
    (the pytree is global); shard lists are disjoint unions.

    Merging FEWER fragments than the save's ``process_count`` would leave
    uncovered regions of restored arrays filled with uninitialized memory —
    but at the call site every writer has already reported success, so a
    shortfall is a transient visibility lag on eventually-consistent shared
    storage: poll briefly for the full set before failing loudly."""
    import time as _time

    deadline = _time.monotonic() + visibility_timeout_s
    while True:
        names = sorted(
            n for n in os.listdir(directory)
            if n.startswith("manifest.p") and n.endswith(".json")
        )
        expected = None
        if names:
            with open(os.path.join(directory, names[0])) as f:
                first = json.load(f)
            expected = int(first.get("process_count", len(names)))
            if len(names) >= expected:
                break
        if _time.monotonic() >= deadline:
            if not names:
                raise FileNotFoundError(f"no manifest fragments in {directory}")
            raise FileNotFoundError(
                f"{directory} has {len(names)} manifest fragments but the "
                f"save ran on {expected} processes; the step is incomplete "
                "on this storage (lagging sync or failed writer)"
            )
        _time.sleep(0.05)
    merged: dict | None = None
    for name in names:
        with open(os.path.join(directory, name)) as f:
            frag = json.load(f)
        if merged is None:
            merged = frag
            continue
        for entry, add in zip(merged["leaves"], frag["leaves"]):
            entry["shards"].extend(add["shards"])

    def write_merged() -> None:
        with open(os.path.join(directory, MANIFEST + ".tmp"), "w") as f:
            json.dump(merged, f)
        os.replace(
            os.path.join(directory, MANIFEST + ".tmp"),
            os.path.join(directory, MANIFEST),
        )

    retry_io(
        write_merged, op="write_manifest", path=os.path.join(directory, MANIFEST)
    )


def save_raw(directory: str, tree: Any, pool: RecyclePool | None = None) -> None:
    """Write ``tree`` synchronously."""
    os.makedirs(directory, exist_ok=True)
    _write_entries(directory, _gather_host(tree), pool)


class AsyncRawSaver:
    """Double-buffered async save: the device→host shard fetch happens
    synchronously (same contract as Orbax async — callers may donate device
    buffers immediately), file IO runs on a background thread.

    ``on_commit`` (if given) runs on the background thread strictly after all
    shard files are on disk — the manager uses it to write ``metadata.json``,
    so a step only becomes visible once its payload is complete (a crash
    mid-write leaves an invisible directory, reclaimed by the next manager's
    orphan sweep)."""

    def __init__(self):
        self._thread: threading.Thread | None = None
        self._error: list[BaseException] = []

    def save(
        self,
        directory: str,
        tree: Any,
        *,
        pool: RecyclePool | None = None,
        on_commit=None,
    ) -> None:
        self.wait()
        os.makedirs(directory, exist_ok=True)
        host_leaves = _gather_host(tree)

        def _write():
            try:
                _write_entries(directory, host_leaves, pool)
                if on_commit is not None:
                    on_commit()
            except BaseException as e:  # surfaced on next wait()
                self._error.append(e)

        self._thread = threading.Thread(target=_write, daemon=True)
        self._thread.start()

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error:
            raise self._error.pop()


def manifest_shard_sizes(
    directory: str, subtree: tuple[str, ...] | None = None
) -> list[int]:
    """Byte size of every shard file a restore of ``directory`` will read —
    the sizes ``RestoreArena.prewarm`` needs to pre-back the restore's
    destination buffers. One entry per unique shard file per leaf (the
    aligned restore path reads each file into exactly one buffer).
    ``subtree`` limits the sizes to a partial restore's leaves (e.g.
    ``('params',)`` for weights-only warm starts)."""
    manifest = _read_manifest(directory)
    sizes = []
    for entry in manifest["leaves"]:
        if subtree is not None and tuple(entry["path"][: len(subtree)]) != subtree:
            continue
        dtype = np.dtype(entry["dtype"])
        seen = set()
        for shard in entry["shards"]:
            if shard["file"] in seen:
                continue
            seen.add(shard["file"])
            n = int(np.prod(shard["shape"])) * dtype.itemsize
            sizes.append(n if shard["shape"] else dtype.itemsize)
    return sizes


def verify_dir(directory: str) -> tuple[int, list[str]]:
    """Recompute every shard file's crc32 against the manifest.

    Returns ``(shards_checked, bad_files)``. Shards without a recorded
    crc32 (checkpoints saved before integrity stamping) are skipped, and a
    non-raw directory checks nothing — both verify vacuously. Reads every
    byte once: an explicit audit, independent of the restore-time
    ``TPUFLOW_CKPT_VERIFY`` setting.
    """
    if not is_raw(directory):
        return 0, []
    manifest = _read_manifest(directory)
    checked = 0
    bad: list[str] = []
    seen: set[str] = set()
    for entry in manifest["leaves"]:
        dtype = np.dtype(entry["dtype"])
        for shard in entry["shards"]:
            fname = shard["file"]
            if fname in seen or shard.get("crc32") is None:
                continue
            seen.add(fname)
            checked += 1
            nbytes = (
                int(np.prod(shard["shape"])) * dtype.itemsize
                if shard["shape"]
                else dtype.itemsize
            )
            try:
                with open(os.path.join(directory, fname), "rb") as f:
                    data = f.read()
            except OSError:
                bad.append(fname)
                continue
            if len(data) < nbytes or zlib.crc32(data[:nbytes]) != int(
                shard["crc32"]
            ):
                bad.append(fname)
    return checked, bad


def is_raw(directory: str) -> bool:
    return os.path.exists(os.path.join(directory, MANIFEST))


def _read_manifest(directory: str) -> dict:
    path = os.path.join(directory, MANIFEST)

    def read() -> dict:
        with open(path) as f:
            return json.load(f)

    m = retry_io(read, op="read_manifest", path=path)
    if m.get("format") != FORMAT_NAME:
        raise ValueError(f"{directory}: not a {FORMAT_NAME} checkpoint")
    return m


def _read_shard(
    directory: str,
    shard: dict,
    dtype: np.dtype,
    *,
    allow_mmap: bool | None = None,
    threads: int | None = None,
    escapes: bool = True,
) -> np.ndarray:
    """Read (or map) one shard file.

    ``escapes=False`` promises the caller copies the returned array before
    it reaches user code (e.g. assembling a full leaf), so a mapping does
    not need the recycle-pool alias guard.
    """
    nbytes = int(np.prod(shard["shape"]) * dtype.itemsize) if shard["shape"] else dtype.itemsize
    path = os.path.join(directory, shard["file"])
    verify = _verify_enabled() and shard.get("crc32") is not None
    if verify:
        # Truncation pre-check: a torn/short file must fail loudly here,
        # not as an opaque native-reader error (or worse, garbage bytes).
        try:
            size = os.path.getsize(path)
        except OSError as e:
            raise CorruptShardError(f"{path}: unreadable shard ({e})") from e
        if size < nbytes:
            raise CorruptShardError(
                f"{path}: truncated shard ({size} bytes, manifest expects "
                f"{nbytes})"
            )
    if _mmap_enabled() if allow_mmap is None else allow_mmap:
        # Zero-copy: map the file's pages instead of reading into a fresh
        # buffer (copy-on-write so callers get a writable array without
        # touching the checkpoint). Consumers that place onto devices copy
        # exactly once, from the mapped pages — or alias them outright on
        # the CPU backend, hence the escape registration. The inode is
        # registered from OUR open fd before the mapping escapes, and the
        # path is re-checked afterwards: if the recycle pool adopted the
        # file in the registration window, the mapping is discarded and we
        # fall back to a plain copy (a freshly re-read one — the mapped
        # bytes could already be mid-overwrite).
        flat = None
        key = None
        try:
            f = open(path, "rb")
        except OSError:
            f = None
        if f is not None:
            try:
                if escapes:
                    key = _register_alias_fd(f.fileno())
                try:
                    flat = np.memmap(f, dtype=np.uint8, mode="c", shape=(nbytes,))
                except (OSError, ValueError):
                    flat = None  # zero-length/unmappable: fall through
            finally:
                f.close()
        if flat is not None and escapes:
            try:
                st = os.stat(path)
                same = (st.st_dev, st.st_ino) == key
            except OSError:
                same = False
            if not same:
                flat = None
        if flat is None:
            if key is not None:
                _unregister_alias(key)
        else:
            if key is not None:
                weakref.finalize(flat, _unregister_alias, key)
            if verify:
                # Forces the mapped pages in — the price of verifying a
                # zero-copy restore; TPUFLOW_CKPT_VERIFY=0 keeps it lazy.
                _check_shard_bytes(path, shard, flat, nbytes)
            return flat.view(dtype).reshape(shard["shape"])
    # Escaping reads draw their destination from the restore arena when a
    # pre-backed buffer of this exact size is available (transient reads —
    # escapes=False, copied into a full-leaf buffer — must not consume them).
    out = _ARENA.take(nbytes) if escapes else None
    buf = retry_io(
        lambda: _native.read_bytes(path, nbytes, threads=threads, out=out),
        op="read_shard",
        path=path,
    )
    if verify:
        _check_shard_bytes(path, shard, buf, nbytes)
    return buf.view(dtype).reshape(shard["shape"])


def _place(arr: np.ndarray, sharding) -> Any:
    """Host array → sharded jax.Array via per-shard placement.

    ``jax.device_put(arr, sharding)`` routes through a slow generic path for
    sharded layouts; assembling from per-device slices is the fast path (each
    device copies only its own contiguous window of the mapped pages).
    """
    shape = arr.shape
    try:
        index_map = sharding.addressable_devices_indices_map(shape)
        shards = []
        for device, index in index_map.items():
            piece = arr[index]
            if not (
                piece.flags["C_CONTIGUOUS"] and piece.ctypes.data % 64 == 0
            ):
                # Copy into an aligned buffer so device_put stays zero-copy.
                buf = _aligned_like(piece.shape, piece.dtype)
                buf[...] = piece
                piece = buf
            shards.append(jax.device_put(piece, device))
        return jax.make_array_from_single_device_arrays(shape, sharding, shards)
    except (TypeError, AttributeError, ValueError):
        return jax.device_put(arr, sharding)


def _resolve_index(index, shape) -> tuple[tuple[int, ...], tuple[int, ...]]:
    """A device's index (tuple of slices) → (starts, extents)."""
    starts, extents = [], []
    for sl, dim in zip(index, shape):
        start = sl.start or 0
        stop = sl.stop if sl.stop is not None else dim
        starts.append(start)
        extents.append(stop - start)
    return tuple(starts), tuple(extents)


def _plan_entry(entry: dict, tmpl) -> list | None:
    """Aligned-restore plan for one manifest entry: ``[(device, shard), …]``
    when every device's required slice coincides with a saved shard file
    (restoring onto the sharding the state was saved with — the common
    case); None when host assembly + resharding is needed instead."""
    sharding = getattr(tmpl, "sharding", None)
    if sharding is None:
        return None
    shape = tuple(entry["shape"])
    try:
        index_map = sharding.addressable_devices_indices_map(shape)
        lookup = {
            (tuple(s["start"]), tuple(s["shape"])): s for s in entry["shards"]
        }
        placements = []
        for device, index in index_map.items():
            shard = lookup.get(_resolve_index(index, shape))
            if shard is None:
                return None
            placements.append((device, shard))
        return placements
    except (TypeError, AttributeError, ValueError):
        return None


def _cast(arr: np.ndarray, tmpl) -> np.ndarray:
    dtype = getattr(tmpl, "dtype", None)
    if dtype is None or arr.dtype == dtype:
        return arr
    # Casting into an aligned destination keeps the result eligible for the
    # zero-copy device_put path (see _native.aligned_empty).
    out = _aligned_like(arr.shape, np.dtype(dtype))
    out[...] = arr
    return out


def _aligned_like(shape, dtype: np.dtype) -> np.ndarray:
    # Scalars (shape ()) need one element; zero-size shapes need 0 bytes and
    # reshape fine from a 0-length view.
    nbytes = int(np.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
    buf = _ARENA.take(nbytes)
    if buf is None:
        buf = _native.aligned_empty(nbytes)
    return buf.view(dtype).reshape(shape)


def _read_leaf(
    directory: str,
    entry: dict,
    *,
    threads: int | None = None,
    zero_copy: bool = False,
) -> np.ndarray:
    dtype = np.dtype(entry["dtype"])
    shards = entry["shards"]
    if len(shards) == 1 and shards[0]["shape"] == entry["shape"]:
        return _read_shard(
            directory,
            shards[0],
            dtype,
            threads=threads,
            allow_mmap=True if zero_copy else None,
        )
    full = _aligned_like(tuple(entry["shape"]), dtype)
    for shard in shards:
        idx = tuple(
            slice(start, start + dim)
            for start, dim in zip(shard["start"], shard["shape"])
        )
        # The copy into `full` makes the data private, so mapping the shard
        # file here is always safe (no alias escapes → no registration).
        full[idx] = _read_shard(
            directory, shard, dtype, allow_mmap=True, escapes=False
        )
    return full


def restore_raw(
    directory: str,
    abstract_state: Any | None = None,
    *,
    subtree: tuple[str, ...] | None = None,
    zero_copy: bool = False,
):
    """Restore a raw checkpoint.

    - With ``abstract_state`` (template pytree, same structure): leaves are
      matched in flatten order, cast to the template dtype and placed with
      the template's sharding when present.
    - Without a template: rebuilds a nested dict from manifest paths (works
      for dict-shaped trees like ``{"params": ...}``).
    - ``subtree``: restore only leaves whose path starts with this prefix,
      returned as the corresponding nested structure (partial restore).
    - ``zero_copy``: map shard files instead of reading them — restored
      arrays alias the files' page-cache pages (no buffer allocation, no
      copy; XLA's CPU client aliases page-aligned host memory), and data
      is paged in on first use. Sound in-process: every file whose mapping
      escapes is registered by inode, and RecyclePool.adopt_dir unlinks
      registered inodes instead of recycling them in place. NOT safe if a
      *different* process may recycle the same checkpoint directory while
      this one holds the arrays — use only for read-only consumers of runs
      this process owns or that are finished (batch eval, benches).
    """
    # Restores serialize on a process-wide lock: the arena is process-global
    # and its cleanup below would otherwise steal/drop the pre-backed
    # buffers of a concurrent restore (threads, or a prewarm for restore B
    # issued while restore A is in flight). Serialization preserves the
    # one-restore-per-prewarm contract; a prewarm issued mid-restore can
    # still lose (some of) its backing work to the cleanup — a lost
    # optimization, never a correctness problem.
    with _RESTORE_LOCK:
        try:
            return _restore_raw_inner(
                directory, abstract_state, subtree=subtree, zero_copy=zero_copy
            )
        finally:
            # Reclaim prewarmed-but-unconsumed arena buffers: a restore that
            # took a different path than its prewarm anticipated (template
            # mismatch → assemble fallback, partial-subtree read, mmap) must
            # not pin pre-backed pages for the process lifetime. One restore
            # per prewarm is the contract; leftovers die with the restore.
            # drop_present (not clear): an in-flight background prewarm for
            # the NEXT restore is not joined-and-discarded, so its
            # still-unlanded buffers survive for that restore.
            _ARENA.drop_present()


def _restore_raw_inner(
    directory: str,
    abstract_state: Any | None = None,
    *,
    subtree: tuple[str, ...] | None = None,
    zero_copy: bool = False,
):
    manifest = _read_manifest(directory)
    entries = manifest["leaves"]
    if subtree is not None:
        entries = [
            e for e in entries if tuple(e["path"][: len(subtree)]) == subtree
        ]
        if not entries:
            raise KeyError(f"no leaves under {subtree} in {directory}")

    if abstract_state is not None and subtree is None:
        flat, treedef = jax.tree_util.tree_flatten(abstract_state)
        if len(flat) != len(entries):
            raise ValueError(
                f"template has {len(flat)} leaves, checkpoint {len(entries)}"
            )
        # Restore parallelism is at SHARD granularity: every (device, shard
        # file) pair is an independent read+place task (file IO and device
        # copies are C++-side with the GIL released), so faults and copies
        # overlap across all cores — the multi-host analogue is every host
        # reading only its own shards concurrently.
        from concurrent.futures import ThreadPoolExecutor

        aligned = [_plan_entry(entry, tmpl) for tmpl, entry in zip(flat, entries)]

        # One task per unique shard FILE: replicated leaves map several
        # devices onto one file, which is read once and placed per device
        # inside the task (no IO amplification). Sharded leaves get one task
        # per shard. File IO and device copies are C++-side with the GIL
        # released, so tasks overlap across cores.
        grouped = []  # per aligned entry: list[(shard, [devices])]
        n_tasks = 0
        for plan in aligned:
            if plan is None:
                n_tasks += 1
                grouped.append(None)
                continue
            by_file: dict[str, tuple[dict, list]] = {}
            for dev, shard in plan:
                by_file.setdefault(shard["file"], (shard, []))[1].append(dev)
            grouped.append(list(by_file.values()))
            n_tasks += len(by_file)
        # IO-bound concurrency floor: restore tasks spend their time
        # blocked on the device (cold reads) or page faults, not running
        # on a core, so capping workers at cpu_count starves the device's
        # queue depth on low-core hosts — measured on the 1-core dev box:
        # cold disk restore 1.10 GB/s with 1 worker vs a 1.81 GB/s
        # 2-stream device ceiling (bench.py probe_disk_ceiling). The
        # floor of 4 matches the write path's pipeline width. An EXPLICIT
        # TPUFLOW_IO_THREADS is a user cap on inflight IO (e.g. to stay
        # polite on shared storage) — it wins over the floor.
        budget = _native.default_threads()
        if not knobs.is_set("TPUFLOW_IO_THREADS"):
            budget = max(budget, 4)
        workers = min(n_tasks, budget) or 1
        # Each pooled task gets its slice of the FLOORED budget (not the
        # raw core count): a checkpoint with fewer shard files than the
        # floor still drives the device at full width by striping each
        # file over more native-reader threads — total inflight stays
        # ~budget regardless of how the tree groups into files.
        read_threads = max(1, budget // workers)

        def read_group(entry, tmpl, shard, devices):
            arr = _cast(
                _read_shard(
                    directory,
                    shard,
                    np.dtype(entry["dtype"]),
                    threads=read_threads,
                    allow_mmap=True if zero_copy else None,
                ),
                tmpl,
            )
            return [jax.device_put(arr, dev) for dev in devices]

        def assemble_fallback(entry, tmpl):
            arr = _cast(
                _read_leaf(
                    directory, entry, threads=read_threads, zero_copy=zero_copy
                ),
                tmpl,
            )
            sharding = getattr(tmpl, "sharding", None)
            return _place(arr, sharding) if sharding is not None else arr

        with ThreadPoolExecutor(workers) as pool:
            futures = []
            for (tmpl, entry), groups in zip(zip(flat, entries), grouped):
                if groups is None:
                    futures.append(
                        (None, pool.submit(assemble_fallback, entry, tmpl))
                    )
                else:
                    futures.append(
                        (
                            (tmpl, entry),
                            [
                                pool.submit(read_group, entry, tmpl, shard, devs)
                                for shard, devs in groups
                            ],
                        )
                    )
            out = []
            for key, fs in futures:
                if key is None:
                    out.append(fs.result())
                else:
                    tmpl, entry = key
                    shards = [a for f in fs for a in f.result()]
                    out.append(
                        jax.make_array_from_single_device_arrays(
                            tuple(entry["shape"]), tmpl.sharding, shards
                        )
                    )
        return jax.tree_util.tree_unflatten(treedef, out)

    # Path-based nested-dict reconstruction.
    root: dict = {}
    for entry in entries:
        names = entry["path"][len(subtree) :] if subtree else entry["path"]
        arr = _read_leaf(directory, entry, zero_copy=zero_copy)
        if not names:
            return arr  # the subtree was a single leaf
        node = root
        for name in names[:-1]:
            node = node.setdefault(name, {})
        node[names[-1]] = arr
    return root
