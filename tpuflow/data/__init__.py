"""Data layer: dataset registry, per-host sharding, seeded reshuffle."""

from tpuflow.data.datasets import (
    Dataset,
    Split,
    get_labels_map,
    load_dataset,
)
from tpuflow.data.loader import (
    ShardedLoader,
    get_dataloaders,
    prefetch_to_device,
)

__all__ = [
    "Dataset",
    "ShardedLoader",
    "Split",
    "get_dataloaders",
    "prefetch_to_device",
    "get_labels_map",
    "load_dataset",
]
