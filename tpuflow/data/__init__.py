"""Data layer: dataset registry, per-host sharding, seeded reshuffle."""

from tpuflow.data.datasets import (
    Dataset,
    Split,
    get_labels_map,
    load_dataset,
)
from tpuflow.data.loader import ShardedLoader, get_dataloaders

__all__ = [
    "Dataset",
    "ShardedLoader",
    "Split",
    "get_dataloaders",
    "get_labels_map",
    "load_dataset",
]
