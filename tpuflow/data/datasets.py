"""Dataset registry: FashionMNIST / CIFAR-10 / synthetic ImageNet-style.

Parity with the reference data layer (my_ray_module.py:30-76): FashionMNIST
normalized with mean 0.5 / std 0.5, download guarded by a file lock. This
environment has zero network egress, so acquisition works in two tiers:

1. If standard on-disk files exist under ``data_dir`` (IDX ``*-ubyte[.gz]``
   for FashionMNIST/MNIST, pickle batches for CIFAR-10), they are decoded.
2. Otherwise a **deterministic, learnable synthetic stand-in** with identical
   shapes/dtypes/split sizes is generated (seeded class-template images), so
   every pipeline runs end-to-end and accuracy metrics are meaningful. The
   record notes ``synthetic=True`` so runs are honest about provenance.

The decoded arrays are cached as ``.npz`` under a FileLock — one
decoder/generator per host, same race guard as the reference's download lock
(my_ray_module.py:41,54).
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import pickle
import struct
import sys

import numpy as np

from tpuflow.utils import FileLock
from tpuflow.utils import knobs

def _default_dir() -> str:
    """Resolve TPUFLOW_DATA_DIR at CALL time, not import time: a frozen
    module constant captures whatever environment happened to exist when
    the module was first imported, so a process that sets the env var
    later (tests monkeypatching a tmp dir, a flow configuring per-run
    storage) silently reads/writes someone else's dataset cache — the
    readme-contract test evaluated a 10k-row cache left in the login
    user's default dir by an unrelated manual run."""
    return knobs.raw(
        "TPUFLOW_DATA_DIR", os.path.expanduser("~/tpuflow_data")
    )

FASHION_MNIST_CLASSES = [
    "T-shirt/top",
    "Trouser",
    "Pullover",
    "Dress",
    "Coat",
    "Sandal",
    "Shirt",
    "Sneaker",
    "Bag",
    "Ankle boot",
]


# Canonical per-dataset spec: the loaders below AND out-of-band consumers
# (eval flows, predictors sizing a model before touching rows) read from
# this one table — add a dataset here first.
_DATASET_SPECS = {
    "fashion_mnist": {"shape": (28, 28), "num_classes": 10},
    "mnist": {"shape": (28, 28), "num_classes": 10},
    "cifar10": {"shape": (32, 32, 3), "num_classes": 10},
    "imagenet_synth": {"shape": (224, 224, 3), "num_classes": 1000},
}


def dataset_info(name: str) -> dict:
    """Registry metadata without materializing the data: sample shape and
    class count."""
    if name not in _DATASET_SPECS:
        raise KeyError(
            f"no registry metadata for dataset {name!r}; known: "
            f"{sorted(_DATASET_SPECS)}"
        )
    return _DATASET_SPECS[name]


def get_labels_map(dataset: str = "fashion_mnist") -> dict[int, str]:
    """class-id → human name for card rendering (parity:
    my_ray_module.py:79-91 get_labels_map)."""
    if dataset in ("fashion_mnist", "mnist"):
        return dict(enumerate(FASHION_MNIST_CLASSES))
    if dataset == "cifar10":
        return dict(
            enumerate(
                [
                    "airplane",
                    "automobile",
                    "bird",
                    "cat",
                    "deer",
                    "dog",
                    "frog",
                    "horse",
                    "ship",
                    "truck",
                ]
            )
        )
    if dataset == "imagenet_synth":
        # Synthetic classes have no human names; ids render as class_<i>.
        return {i: f"class_{i}" for i in range(1000)}
    raise KeyError(dataset)


@dataclasses.dataclass
class Split:
    """One split: normalized float32 images + int32 labels."""

    images: np.ndarray
    labels: np.ndarray

    def __len__(self) -> int:
        return len(self.labels)


@dataclasses.dataclass
class Dataset:
    name: str
    train: Split
    test: Split
    num_classes: int
    synthetic: bool


def _read_idx(path: str) -> np.ndarray:
    """Decode an IDX file (the FashionMNIST/MNIST wire format)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    zero, dtype_code, ndim = struct.unpack(">HBB", data[:4])
    if zero != 0:
        raise ValueError(f"{path}: bad IDX magic")
    dims = struct.unpack(f">{ndim}I", data[4 : 4 + 4 * ndim])
    dtype = {0x08: np.uint8, 0x0B: np.int16, 0x0C: np.int32, 0x0D: np.float32}[
        dtype_code
    ]
    return np.frombuffer(data[4 + 4 * ndim :], dtype=dtype).reshape(dims)


def _find(data_dir: str, names: list[str]) -> str | None:
    for n in names:
        for cand in (os.path.join(data_dir, n), os.path.join(data_dir, n + ".gz")):
            if os.path.exists(cand):
                return cand
    return None


def _normalize(images_u8: np.ndarray) -> np.ndarray:
    """uint8 [0,255] → float32, ToTensor (/255) then Normalize((0.5,),(0.5,))
    — exactly the reference transform (my_ray_module.py:38)."""
    return ((images_u8.astype(np.float32) / 255.0) - 0.5) / 0.5


def _synth_classification(
    seed: int, n_train: int, n_test: int, shape: tuple, num_classes: int
) -> tuple[Split, Split]:
    """Deterministic learnable stand-in: each class is a fixed smooth template
    + per-sample noise. Linear models reach high accuracy; random guessing
    stays at 1/num_classes, so train/val curves behave like real data."""
    rng = np.random.default_rng(seed)
    templates = rng.normal(scale=1.0, size=(num_classes, *shape)).astype(np.float32)
    # Smooth templates along spatial dims so conv models see structure.
    for axis in range(len(shape))[:2]:
        templates = (
            templates + np.roll(templates, 1, axis=axis + 1)
            + np.roll(templates, -1, axis=axis + 1)
        ) / 3.0

    def make(n: int, split_seed: int) -> Split:
        r = np.random.default_rng(split_seed)
        labels = r.integers(0, num_classes, size=n).astype(np.int32)
        noise = r.normal(scale=1.0, size=(n, *shape)).astype(np.float32)
        images = 0.8 * templates[labels] + noise * 0.6
        return Split(images.astype(np.float32), labels)

    return make(n_train, seed + 1), make(n_test, seed + 2)


def _load_synthetic_lm(
    n_docs: int, seq_len: int, vocab_size: int, seed: int = 0
) -> Dataset:
    """Deterministic learnable LM data for the GPT family: each document
    cycles an arithmetic token pattern (next token fully predictable from
    the previous one), with a doc-dependent stride — loss decreases fast,
    random guessing sits at log(vocab).

    The Split reuses the image/label fields as numpy VIEWS of one token
    buffer: ``images = tokens[:, :-1]`` (model input) and
    ``labels = tokens[:, 1:]`` (next-token targets), so the sharded loader,
    per-epoch reshuffle and device prefetcher work unchanged for language
    models.
    """
    def make(n: int, split_seed: int) -> Split:
        r = np.random.default_rng((seed, split_seed))
        starts = r.integers(0, vocab_size, size=n)
        strides = r.integers(1, 7, size=n)
        pos = np.arange(seq_len + 1)
        tokens = (
            (starts[:, None] + strides[:, None] * pos[None, :]) % vocab_size
        ).astype(np.int32)
        return Split(tokens[:, :-1], tokens[:, 1:])

    return Dataset(
        "lm_synth",
        make(n_docs, 1),
        make(max(n_docs // 8, 1), 2),
        num_classes=vocab_size,
        synthetic=True,
    )


def resolve_text_path(
    data_dir: str | None = None, text_path: str | None = None
) -> str | None:
    """The ONE source of truth for which file 'lm_text' trains on:
    explicit ``text_path`` → ``TPUFLOW_TEXT_FILE`` env → first ``*.txt``
    under the data dir → None (synthetic stand-in). Exposed so flows can
    record the resolved path (plus a content hash) as a run artifact and
    consumers can pin the identical corpus instead of re-resolving in a
    possibly different environment."""
    import glob as _glob

    explicit = text_path or knobs.raw("TPUFLOW_TEXT_FILE")
    if explicit:
        if not os.path.exists(explicit):
            # An explicitly requested file must never silently degrade to
            # the synthetic stand-in (a typo'd path would otherwise train
            # on fake data while claiming real text).
            raise FileNotFoundError(
                f"lm_text: requested text file does not exist: {explicit}"
            )
        return explicit
    txts = sorted(_glob.glob(os.path.join(data_dir or _default_dir(), "*.txt")))
    return txts[0] if txts else None


def _load_text_lm(
    data_dir: str, seq_len: int, text_path: str | None = None
) -> Dataset:
    """Byte-level LM dataset from a local text file — the zero-dependency
    real-data path for the GPT family (no tokenizer assets needed: the
    vocabulary is the 256 byte values).

    Source resolution: explicit ``text_path`` → ``TPUFLOW_TEXT_FILE`` env →
    first ``*.txt`` under the data dir. The file's bytes chunk into
    non-overlapping ``seq_len + 1`` windows (input = window[:-1], target =
    window[1:]), split 95/5 into train/test along document order. With no
    file present, a deterministic byte-pattern corpus stands in
    (``synthetic=True``), mirroring the image datasets' fallback policy.
    """
    path = resolve_text_path(data_dir, text_path)
    if path is None:
        # No file anywhere: the deterministic stand-in, shifted into the
        # printable-byte range (reuses the lm_synth generator, one pattern
        # source to maintain).
        base = _load_synthetic_lm(512, seq_len, 95)
        return Dataset(
            "lm_text",
            Split(base.train.images + 32, base.train.labels + 32),
            Split(base.test.images + 32, base.test.labels + 32),
            256,
            synthetic=True,
        )
    with open(path, "rb") as f:
        raw = np.frombuffer(f.read(), dtype=np.uint8)
    n_win = len(raw) // (seq_len + 1)
    if n_win < 4:
        raise ValueError(
            f"{path}: need at least {4 * (seq_len + 1)} bytes for "
            f"seq_len={seq_len}, have {len(raw)}"
        )
    tokens = (
        raw[: n_win * (seq_len + 1)].reshape(n_win, seq_len + 1).astype(np.int32)
    )
    n_train = max(int(n_win * 0.95), 1)
    if n_train == tokens.shape[0]:
        n_train -= 1
    train = Split(tokens[:n_train, :-1], tokens[:n_train, 1:])
    test = Split(tokens[n_train:, :-1], tokens[n_train:, 1:])
    return Dataset("lm_text", train, test, 256, synthetic=False)


def _fetch_enabled() -> bool:
    from tpuflow.data.fetch import fetch_enabled

    return fetch_enabled()


def _real_source_present(name: str, data_dir: str) -> bool:
    """True when real (non-synthetic) source files for ``name`` exist
    under ``data_dir`` right now — the signal that a synthetic npz cache
    is stale and a rebuild would produce real data."""
    if name in ("fashion_mnist", "mnist"):
        return all(
            _find(data_dir, [n]) is not None
            for n in (
                "train-images-idx3-ubyte", "train-labels-idx1-ubyte",
                "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte",
            )
        )
    if name == "cifar10":
        # The directory alone is not enough: a partially extracted
        # tarball would steer the loader off a usable synthetic cache
        # and into a FileNotFoundError on the missing batch files.
        batch_dir = os.path.join(data_dir, "cifar-10-batches-py")
        return all(
            os.path.exists(os.path.join(batch_dir, f))
            for f in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]
        )
    return False


def _load_fashion_mnist(data_dir: str, name: str) -> Dataset:
    prefix = "" if name == "fashion_mnist" else ""

    def find_all():
        return {
            "train_images": _find(data_dir, [prefix + "train-images-idx3-ubyte"]),
            "train_labels": _find(data_dir, [prefix + "train-labels-idx1-ubyte"]),
            "test_images": _find(data_dir, [prefix + "t10k-images-idx3-ubyte"]),
            "test_labels": _find(data_dir, [prefix + "t10k-labels-idx1-ubyte"]),
        }

    files = find_all()
    if not all(files.values()) and name == "fashion_mnist":
        # D16: env-gated (TPUFLOW_FETCH=1) checksum-verified download
        # under a FileLock (reference my_ray_module.py:41-67); offline or
        # disabled falls through to the pre-placed/synthetic behavior.
        from tpuflow.data.fetch import maybe_fetch_fashion_mnist

        if maybe_fetch_fashion_mnist(data_dir):
            files = find_all()
    if all(files.values()):
        train = Split(
            _normalize(_read_idx(files["train_images"])),
            _read_idx(files["train_labels"]).astype(np.int32),
        )
        test = Split(
            _normalize(_read_idx(files["test_images"])),
            _read_idx(files["test_labels"]).astype(np.int32),
        )
        return Dataset(name, train, test, 10, synthetic=False)
    n_train = int(knobs.raw("TPUFLOW_SYNTH_TRAIN_N", 60_000))
    n_test = int(knobs.raw("TPUFLOW_SYNTH_TEST_N", 10_000))
    train, test = _synth_classification(
        seed=20, n_train=n_train, n_test=n_test, shape=(28, 28), num_classes=10
    )
    return Dataset(name, train, test, 10, synthetic=True)


def _load_cifar10(data_dir: str) -> Dataset:
    batch_dir = os.path.join(data_dir, "cifar-10-batches-py")
    if os.path.isdir(batch_dir):
        xs, ys = [], []
        for i in range(1, 6):
            with open(os.path.join(batch_dir, f"data_batch_{i}"), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.extend(d[b"labels"])
        train_x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        with open(os.path.join(batch_dir, "test_batch"), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        test_x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return Dataset(
            "cifar10",
            Split(_normalize(train_x), np.asarray(ys, np.int32)),
            Split(_normalize(test_x), np.asarray(d[b"labels"], np.int32)),
            10,
            synthetic=False,
        )
    n_train = int(knobs.raw("TPUFLOW_SYNTH_TRAIN_N", 50_000))
    n_test = int(knobs.raw("TPUFLOW_SYNTH_TEST_N", 10_000))
    spec = _DATASET_SPECS["cifar10"]
    train, test = _synth_classification(
        seed=30, n_train=n_train, n_test=n_test, shape=spec["shape"],
        num_classes=spec["num_classes"],
    )
    return Dataset("cifar10", train, test, spec["num_classes"], synthetic=True)


def _load_synthetic_imagenet(size: int) -> Dataset:
    """ImageNet-shaped synthetic data (224x224x3, 1000 classes) for the
    ResNet-50 acceptance config; sized down by default to fit dev machines.
    TPUFLOW_SYNTH_TRAIN_N/TPUFLOW_SYNTH_TEST_N override, same knobs as the
    other synthetic fallbacks."""
    spec = _DATASET_SPECS["imagenet_synth"]
    train, test = _synth_classification(
        seed=40,
        n_train=int(knobs.raw("TPUFLOW_SYNTH_TRAIN_N", size)),
        n_test=int(
            knobs.raw("TPUFLOW_SYNTH_TEST_N", max(size // 10, 100))
        ),
        shape=spec["shape"],
        num_classes=spec["num_classes"],
    )
    return Dataset(
        "imagenet_synth", train, test, spec["num_classes"], synthetic=True
    )


def load_dataset(
    name: str = "fashion_mnist",
    *,
    data_dir: str | None = None,
    synthetic_size: int = 2_000,
    seq_len: int = 64,
    vocab_size: int = 512,
    text_path: str | None = None,
) -> Dataset:
    """Load (or synthesize) a dataset by name, with npz caching under a
    FileLock so only one process per host does the decode/generation.
    ``seq_len``/``vocab_size`` apply to the 'lm_synth' language-model
    dataset (its Split holds token ids, not images)."""
    data_dir = data_dir or _default_dir()
    os.makedirs(data_dir, exist_ok=True)
    if name == "imagenet_synth":
        # Deterministic generation; too large to be worth an npz cache.
        return _load_synthetic_imagenet(synthetic_size)
    if name == "lm_synth":
        # Deterministic + parameterized by shape: cheap to regenerate, and
        # an npz cache keyed only on the name would collide across shapes.
        return _load_synthetic_lm(synthetic_size, seq_len, vocab_size)
    if name == "lm_text":
        # One file read + reshape: cheaper than an npz round-trip, and the
        # cache key problem is the same as lm_synth's.
        return _load_text_lm(data_dir, seq_len, text_path)
    cache = os.path.join(data_dir, f"{name}_cache.npz")
    with FileLock(os.path.join(data_dir, f".{name}.lock")):
        if os.path.exists(cache):
            z = np.load(cache)
            cached_synthetic = bool(z["synthetic"])
            # A stale SYNTHETIC cache must not shadow real data the
            # loader could produce now: rebuild when real source files
            # have appeared since the cache was written, or when the
            # user enabled fetching for a dataset that has a fetcher.
            # Otherwise honor the cache — rebuilding would regenerate
            # identical synthetic data and rewrite the npz every load.
            real_possible = _real_source_present(name, data_dir) or (
                name == "fashion_mnist" and _fetch_enabled()
            )
            cached_ds = Dataset(
                name,
                Split(z["train_x"], z["train_y"]),
                Split(z["test_x"], z["test_y"]),
                int(z["num_classes"]),
                cached_synthetic,
            )
            if not (cached_synthetic and real_possible):
                return cached_ds
            # The cache records a synthetic stand-in but real source
            # files (or an explicitly enabled fetch) have appeared: a
            # stale synthetic cache must not silently defeat the request
            # for real bytes — fall through and rebuild. The cached
            # dataset is kept as a FALLBACK: present-but-corrupt source
            # files (truncated download, interrupted extract) must
            # degrade back to the still-valid synthetic stand-in, not
            # turn every subsequent load into a crash.
        else:
            cached_ds = None
        try:
            if name in ("fashion_mnist", "mnist"):
                ds = _load_fashion_mnist(data_dir, name)
            elif name == "cifar10":
                ds = _load_cifar10(data_dir)
            elif name == "imagenet_synth":
                ds = _load_synthetic_imagenet(synthetic_size)
            else:
                raise KeyError(
                    f"unknown dataset {name!r}; available: fashion_mnist, "
                    "mnist, cifar10, imagenet_synth, lm_synth, lm_text"
                )
        except KeyError:
            raise  # unknown name is a caller bug, never maskable
        except Exception as e:
            if cached_ds is not None:
                print(
                    f"[tpuflow.data] real source for {name!r} present but "
                    f"unreadable ({e!r}); serving cached synthetic "
                    "stand-in", file=sys.stderr,
                )
                return cached_ds
            raise
        np.savez(
            cache,
            train_x=ds.train.images,
            train_y=ds.train.labels,
            test_x=ds.test.images,
            test_y=ds.test.labels,
            num_classes=ds.num_classes,
            synthetic=ds.synthetic,
        )
        return ds
