"""Env-gated, checksum-verified dataset fetcher (closes SURVEY D16).

TPU-native counterpart of the reference's FileLock'd FashionMNIST
download (my_ray_module.py:41-67: torchvision fetches under
``FileLock(".fashion_lock")`` so one gang worker downloads while the
rest wait). Same pattern here, with two hard rules the reference leaves
implicit:

- **Opt-in only** (``TPUFLOW_FETCH=1``): the default behavior is
  byte-identical to before — pre-placed IDX files or the labeled
  synthetic stand-in. Training environments are commonly air-gapped;
  nothing should ever touch the network unasked.
- **Checksum-verified, atomic**: bytes land in ``<name>.part`` and are
  renamed into place only after the digest matches, so a torn download
  or a tampered mirror can never produce a silently-wrong dataset.

Base URL override: ``TPUFLOW_FETCH_BASE_URL`` (e.g. an internal mirror;
also how the unit tests point the fetcher at a local HTTP fixture).
"""

from __future__ import annotations

import hashlib
import os
import urllib.error
import urllib.request

from tpuflow.utils.locking import FileLock
from tpuflow.utils import knobs

# Fashion-MNIST registry: gz filename -> (default source, digest). The
# digests are the published torchvision ones (md5 — what upstream
# distributes); the verifier accepts "md5:..." or "sha256:..." prefixes.
_FASHION_MNIST_BASE = "http://fashion-mnist.s3-website.eu-central-1.amazonaws.com/"
FASHION_MNIST_FILES: dict[str, str] = {
    "train-images-idx3-ubyte.gz": "md5:8d4fb7e6c68d591d4c3dfef9ec88bf0d",
    "train-labels-idx1-ubyte.gz": "md5:25c81989df183df01b3e8a0aad5dffbe",
    "t10k-images-idx3-ubyte.gz": "md5:bef4ecab320f06d8554ea6380940ec79",
    "t10k-labels-idx1-ubyte.gz": "md5:bb300cfdad3c16e7a12a480ee83cd310",
}


def fetch_enabled() -> bool:
    return knobs.raw("TPUFLOW_FETCH") == "1"


def _digest(path: str, spec: str) -> bool:
    algo, _, want = spec.partition(":")
    h = hashlib.new(algo)
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest() == want.lower()


def fetch_file(
    url: str, dest: str, checksum: str | None = None, timeout: float = 60.0
) -> str:
    """Download ``url`` to ``dest`` atomically, verifying ``checksum``
    ("algo:hex") before the rename. Raises on any failure; never leaves
    a partial or unverified file at ``dest``."""
    part = dest + ".part"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r, open(
            part, "wb"
        ) as f:
            while True:
                chunk = r.read(1 << 20)
                if not chunk:
                    break
                f.write(chunk)
        if checksum and not _digest(part, checksum):
            raise ValueError(
                f"{url}: checksum mismatch (expected {checksum}); refusing "
                "to install the file — override the source via "
                "TPUFLOW_FETCH_BASE_URL if the registry digest is stale"
            )
        os.replace(part, dest)
        return dest
    finally:
        try:
            os.remove(part)
        except OSError:
            pass


def fetch_idx_files(
    data_dir: str,
    files: dict[str, str],
    base_url: str,
    *,
    timeout: float = 60.0,
) -> bool:
    """Fetch every missing registry file into ``data_dir`` under ONE
    FileLock (gang semantics: the winner downloads, the rest block and
    then see the files). Returns True when all files are present
    afterwards; False (with a log line, no raise) when the network is
    unreachable — the caller falls back exactly as if fetching were
    disabled."""
    os.makedirs(data_dir, exist_ok=True)
    base = knobs.raw("TPUFLOW_FETCH_BASE_URL", base_url)
    if not base.endswith("/"):
        base += "/"
    with FileLock(os.path.join(data_dir, ".fetch.lock")):
        for name, checksum in files.items():
            dest = os.path.join(data_dir, name)
            bare = dest[:-3] if name.endswith(".gz") else dest
            if os.path.exists(dest) or os.path.exists(bare):
                continue  # another worker (or a pre-placement) won
            try:
                fetch_file(base + name, dest, checksum, timeout=timeout)
            except (urllib.error.URLError, OSError, TimeoutError) as e:
                # Offline tolerance: unreachable network degrades to the
                # no-fetch behavior. A checksum mismatch is NOT caught —
                # wrong bytes must fail loudly, not silently degrade.
                print(
                    f"[tpuflow.data] fetch of {name} failed ({e!r:.120}); "
                    "falling back to pre-placed/synthetic data"
                )
                return False
    return all(
        os.path.exists(os.path.join(data_dir, n))
        or os.path.exists(
            os.path.join(data_dir, n[:-3] if n.endswith(".gz") else n)
        )
        for n in files
    )


def maybe_fetch_fashion_mnist(data_dir: str) -> bool:
    """The D16 entry point ``_load_fashion_mnist`` calls when its files
    are missing: no-op unless ``TPUFLOW_FETCH=1``."""
    if not fetch_enabled():
        return False
    return fetch_idx_files(
        data_dir, FASHION_MNIST_FILES, _FASHION_MNIST_BASE
    )
