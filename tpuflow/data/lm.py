"""LM-family data plumbing shared by the GPT flows (train AND eval).

Flows stay ~reference-sized shells (reference train_flow.py is a 100-line
wrapper over its library stack); the corpus sizing, loader construction,
and source provenance for the language-model datasets live here.
"""

from __future__ import annotations

import hashlib
import os
from typing import Any

from tpuflow.data.datasets import load_dataset, resolve_text_path
from tpuflow.data.loader import ShardedLoader


def lm_corpus_size(batch_size: int, steps: int) -> int:
    """Docs in the lm_synth corpus for a run's parameters — ONE source of
    truth shared by the loader and the ``synthetic_size_used`` artifact an
    eval flow mirrors to see the identical test split."""
    return max(batch_size * steps, batch_size)


def text_source_record(
    text_path: str | None = None, data_dir: str | None = None
) -> dict[str, Any]:
    """Resolve the 'lm_text' source and fingerprint it: ``{"path", "sha256",
    "bytes"}`` (path None = synthetic stand-in). Training records this as a
    run artifact; eval passes the recorded path back and errors on a hash
    mismatch — the corpus can't silently differ between the two flows."""
    path = resolve_text_path(data_dir, text_path)
    if path is None:
        return {"path": None, "sha256": None, "bytes": 0}
    h = hashlib.sha256()
    n = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
            n += len(chunk)
    return {"path": os.path.abspath(path), "sha256": h.hexdigest(), "bytes": n}


def check_text_source(record: dict[str, Any]) -> None:
    """Verify a recorded text source still has the recorded content.
    Raises with a precise message on a missing file or changed bytes —
    never lets an eval silently score against a different corpus."""
    path = record.get("path")
    if path is None:
        # Training used the synthetic stand-in; if resolution NOW finds a
        # real file, scoring it would silently compare apples to oranges.
        found = resolve_text_path()
        if found is not None:
            raise ValueError(
                f"training used the synthetic lm_text stand-in but {found} "
                "resolves now; unset TPUFLOW_TEXT_FILE / clean the data dir "
                "or re-train on the file"
            )
        return
    current = text_source_record(text_path=path)
    if current["sha256"] != record.get("sha256"):
        raise ValueError(
            f"lm_text corpus changed since training: {path} now hashes "
            f"{current['sha256']} (recorded {record.get('sha256')}); "
            "re-train or point TPUFLOW_TEXT_FILE at the original file"
        )


def make_lm_loaders(
    batch_size: int,
    steps: int,
    seq_len: int,
    vocab: int,
    dataset: str = "lm_synth",
    text_path: str | None = None,
) -> tuple[ShardedLoader, ShardedLoader]:
    """Sharded train/val LM loaders (D4/D16 for the GPT family): yield
    ``{'x': tokens[:, :-1], 'y': tokens[:, 1:]}`` with the same seeded
    per-epoch reshuffle semantics as the image loaders (set_epoch ↔
    reference my_ray_module.py:149-151). 'lm_synth' is the deterministic
    stand-in; 'lm_text' trains byte-level on a local text file (drop a
    .txt into $TPUFLOW_DATA_DIR or point TPUFLOW_TEXT_FILE at one).

    Epoch length honors ``steps`` (keeping the LR decay horizon,
    epochs*steps, truthful) via max_batches: each epoch's reshuffle ranges
    over the WHOLE corpus, so successive epochs see different windows of a
    large file. The held-out loader pads+masks its ragged tail so every
    test window counts in the validation perplexity.
    """
    if dataset == "lm_text":
        ds = load_dataset("lm_text", seq_len=seq_len, text_path=text_path)
        if vocab < 256:
            raise ValueError(
                f"lm_text is byte-level (vocab 256) but the model's "
                f"vocab_size is {vocab}"
            )
        if ds.train.images.shape[0] < batch_size:
            raise ValueError(
                f"lm_text corpus yields only {ds.train.images.shape[0]} "
                f"windows of seq_len+1 bytes — fewer than one batch of "
                f"{batch_size}; use a bigger file or smaller batch size"
            )
    elif dataset == "lm_synth":
        ds = load_dataset(
            "lm_synth",
            synthetic_size=lm_corpus_size(batch_size, steps),
            seq_len=seq_len,
            vocab_size=vocab,
        )
    else:
        raise ValueError(
            f"unknown dataset {dataset!r}; available: lm_synth, lm_text"
        )
    train = ShardedLoader(
        ds.train, batch_size=batch_size, shuffle=True, max_batches=steps
    )
    val = ShardedLoader(
        ds.test,
        batch_size=batch_size,
        shuffle=False,
        pad_tail=True,
        drop_last=False,
    )
    return train, val
