"""Per-host sharded batch loader with seeded per-epoch reshuffle.

TPU-native replacement for ``DataLoader`` + ``DistributedSampler`` as wrapped
by ``ray.train.torch.prepare_data_loader`` (reference my_ray_module.py:70-76,
128-129): each data-parallel shard sees 1/world of the data, the per-epoch
reshuffle is a permutation seeded by (seed, epoch) — the ``set_epoch``
semantics of my_ray_module.py:149-151 — and train batches are fixed-shape
(drop_last) so the jitted step never recompiles. Validation keeps the ragged
tail by padding + masking (consumed by make_eval_step's ``mask``).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np

from tpuflow.data.datasets import Split
from tpuflow.utils import knobs


def _take(arr: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Batch row gather; float32 image rows go through the multithreaded
    native copy (tpuflow/_native/io.cpp dataio_gather_f32)."""
    if arr.dtype == np.float32 and arr.ndim >= 2:
        from tpuflow import _native

        return _native.gather_f32(arr, idx)
    return arr[idx]


@dataclasses.dataclass
class ShardedLoader:
    """Iterate fixed-shape batches of one shard of a Split.

    ``num_shards``/``shard_index`` default to a single shard; the trainer sets
    them to (data-parallel world, this worker's rank). When the shard sizes
    are uneven the permutation is wrap-padded so every shard sees the same
    number of batches — the same trick DistributedSampler uses, which keeps
    the collective-running gang in lockstep.
    """

    split: Split
    batch_size: int
    shuffle: bool = False
    seed: int = 0
    shard_index: int = 0
    num_shards: int = 1
    drop_last: bool = True
    pad_tail: bool = False  # emit a final padded+masked batch (eval mode)
    # Cap on batches per epoch (None = all). The per-epoch permutation still
    # ranges over the WHOLE split, so successive epochs cover different
    # subsets — bounding epoch length without pinning training to a prefix.
    max_batches: int | None = None

    def __post_init__(self):
        if not 0 <= self.shard_index < self.num_shards:
            raise ValueError(
                f"shard_index {self.shard_index} out of range for "
                f"{self.num_shards} shards"
            )
        self._epoch = 0
        self._skip_next = 0

    def set_epoch(self, epoch: int) -> None:
        """Reseed the shuffle for a new epoch (parity: sampler.set_epoch,
        reference my_ray_module.py:149-151)."""
        self._epoch = epoch

    def skip_batches(self, n: int) -> None:
        """Skip the first ``n`` batches of the NEXT iteration (one-shot).

        Deterministic mid-epoch resume (ISSUE 5): the per-epoch
        permutation is a pure function of (seed, epoch), so after a
        restore whose checkpoint metadata recorded the loader cursor
        (epoch, batches consumed, seed), skipping exactly the consumed
        batches replays the epoch's REMAINDER bit-for-bit — no batch is
        trained twice and none is dropped. The skip applies once: the
        following epochs iterate from their head as usual.
        """
        self._skip_next = max(int(n), 0)

    def reshard(self, shard_index: int, num_shards: int) -> None:
        """Re-key this loader to a resized data-parallel world (ISSUE 7:
        elastic mesh shrink/grow re-forms the gang mid-run).

        Only the stride slice over the per-epoch permutation changes —
        the permutation itself is a pure function of ``(seed, epoch)``,
        so ``data_state`` continuity composes with the resize: feeding
        the cursor recorded by the OLD world into ``set_epoch`` +
        ``skip_batches`` resumes the epoch DETERMINISTICALLY under the
        new shard map (same permutation, new stride). Row-level
        continuity across the resize boundary is approximate — the old
        and new strides interleave rows differently — but epoch and
        step accounting stay exact, which is what the train loops key
        on.
        """
        if not 0 <= shard_index < num_shards:
            raise ValueError(
                f"shard_index {shard_index} out of range for "
                f"{num_shards} shards"
            )
        self.shard_index = shard_index
        self.num_shards = num_shards

    def state_dict(self, batches_consumed: int) -> dict:
        """The loader cursor a checkpoint should persist for deterministic
        mid-epoch resume: pair with ``set_epoch`` + ``skip_batches`` on
        the restoring side (CheckpointManager.save(data_state=...))."""
        return {
            "epoch": int(self._epoch),
            "batch_index": int(batches_consumed),
            "seed": int(self.seed),
        }

    def _indices(self) -> np.ndarray:
        n = len(self.split)
        if self.shuffle:
            order = np.random.default_rng(
                (self.seed, self._epoch)
            ).permutation(n)
        else:
            order = np.arange(n)
        if self.num_shards > 1:
            per = -(-n // self.num_shards)  # ceil
            padded = np.concatenate([order, order[: per * self.num_shards - n]])
            order = padded[self.shard_index :: self.num_shards]
        return order

    def __len__(self) -> int:
        n = len(self._indices())
        if self.drop_last and not self.pad_tail:
            count = n // self.batch_size
        else:
            count = -(-n // self.batch_size)
        if self.max_batches is not None:
            count = min(count, self.max_batches)
        return count

    def __iter__(self) -> Iterator[dict]:
        order = self._indices()
        if self.max_batches is not None:
            order = order[: self.max_batches * self.batch_size]
        bs = self.batch_size
        skip, self._skip_next = self._skip_next, 0
        if skip:
            # Mid-epoch resume: drop exactly the already-consumed prefix;
            # the permutation above is identical for the same (seed,
            # epoch), so what remains is the epoch's exact tail.
            order = order[skip * bs :]
        n_full = len(order) // bs
        for b in range(n_full):
            idx = order[b * bs : (b + 1) * bs]
            yield {
                "x": _take(self.split.images, idx),
                "y": self.split.labels[idx],
                "mask": np.ones(bs, np.float32),
            }
        tail = len(order) - n_full * bs
        if tail and self.pad_tail:
            idx = order[n_full * bs :]
            pad = bs - tail
            pad_idx = np.concatenate([idx, np.repeat(idx[-1:], pad)])
            mask = np.concatenate(
                [np.ones(tail, np.float32), np.zeros(pad, np.float32)]
            )
            yield {
                "x": _take(self.split.images, pad_idx),
                "y": self.split.labels[pad_idx],
                "mask": mask,
            }
        elif tail and not self.drop_last:
            idx = order[n_full * bs :]
            yield {
                "x": _take(self.split.images, idx),
                "y": self.split.labels[idx],
                "mask": np.ones(tail, np.float32),
            }


def prefetch_depth(default: int = 2) -> int:
    """Resolve the device-prefetch depth: ``TPUFLOW_PREFETCH_DEPTH``
    beats ``default``; values <= 0 DISABLE prefetch (the loops then
    assemble + place batches inline, no thread spawned — the overhead
    pin in tests/test_data.py holds the disabled path to one int check
    per call). A malformed value falls back to ``default``."""
    import os

    env = knobs.raw("TPUFLOW_PREFETCH_DEPTH")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    return default


def prefetch_to_device(loader, mesh, *, depth: int | None = None, keys=None,
                       place=None):
    """Pipeline batch assembly + host→device placement against compute.

    A background thread assembles batches (the threaded C++ gather) and
    places them on the mesh (``dist.shard_batch``, or the caller's
    ``place``) up to ``depth`` ahead, while the main thread's jitted
    steps run — double-buffering the host side of the input pipeline the
    way ``prepare_data_loader``'s device iterator does in the reference
    stack (my_ray_module.py:128-129). Safe under multi-host: placement
    is per-process local (no collectives).

    ``depth``: buffered batches; ``None`` resolves via
    :func:`prefetch_depth` (``TPUFLOW_PREFETCH_DEPTH``, default 2).
    Depth <= 0 disables the pipeline entirely: batches are assembled and
    placed inline on the consumer thread — no thread, no queue — which
    is the knob for platforms where a background device_put is unwanted.
    ``keys``: optional subset of batch entries to keep (e.g. ("x", "y")).
    ``place``: optional ``batch -> placed_batch`` callable run on the
    prefetch thread (default ``dist.shard_batch`` onto ``mesh``) — the
    train legs pass their own sharded ``device_put`` so the placement
    matches the step's batch sharding exactly.

    Telemetry: per-batch ``data.batch_wait_s`` histogram plus the
    ``data.host_wait_s`` gauge (the time the consumer actually blocked —
    ~0 on every prefetch hit is the "input pipeline is off the critical
    path" evidence), and ``data.prefetch_hit``/``miss`` counters.
    """
    from tpuflow import dist, obs

    if place is None:
        place = lambda batch: dist.shard_batch(batch, mesh)  # noqa: E731
    if depth is None:
        depth = prefetch_depth()
    if depth <= 0:
        # Disabled path: inline assembly + placement, no thread spawned.
        # Kept deliberately bare — one generator frame over the loader —
        # so disabling prefetch never costs more than the work it defers.
        def _inline():
            obs_on = obs.enabled()
            for batch in loader:
                if obs_on:
                    import time

                    t0 = time.monotonic()
                if keys is not None:
                    batch = {k: batch[k] for k in keys}
                placed = place(batch)
                if obs_on:
                    wait = time.monotonic() - t0
                    obs.histogram("data.batch_wait_s", wait)
                    obs.gauge("data.host_wait_s", wait)
                    obs.counter("data.prefetch_miss")
                yield placed

        return _inline()
    return _prefetch_threaded(loader, place, depth, keys)


def _prefetch_threaded(loader, place, depth: int, keys):
    import queue
    import threading

    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    done = object()
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _worker():
        try:
            for batch in loader:
                if keys is not None:
                    batch = {k: batch[k] for k in keys}
                if not _put(place(batch)):
                    return  # consumer went away (early break)
            _put(done)
        except BaseException as e:  # surfaced on the consuming thread
            _put(e)

    thread = threading.Thread(target=_worker, daemon=True)
    thread.start()
    # Telemetry (tpuflow.obs): batch-wait vs prefetch-hit timing — the
    # "was the input pipeline ever the bottleneck" evidence. Resolved once
    # outside the loop; disabled runs take the bare q.get path.
    from tpuflow import obs

    obs_on = obs.enabled()
    try:
        while True:
            if obs_on:
                import time

                hit = not q.empty()
                t0 = time.monotonic()
                item = q.get()
                wait = time.monotonic() - t0
                obs.histogram("data.batch_wait_s", wait)
                # The overlap proof: ~0 on every hit means the input
                # pipeline ran entirely behind device compute.
                obs.gauge("data.host_wait_s", wait)
                if hit:
                    obs.counter("data.prefetch_hit")
                else:
                    obs.counter("data.prefetch_miss")
            else:
                item = q.get()
            if item is done:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        thread.join(timeout=1.0)


def get_dataloaders(
    batch_size: int,
    *,
    dataset: str = "fashion_mnist",
    val_only: bool = False,
    as_rows: bool = False,
    data_dir: str | None = None,
    seed: int = 0,
    shard_index: int = 0,
    num_shards: int = 1,
):
    """Parity entry point for the reference's ``get_dataloaders(batch_size,
    val_only, as_ray_ds)`` (my_ray_module.py:30-76): returns (train, val)
    ShardedLoaders, a val-only loader, or — with ``as_rows=True`` — the eval
    split as a list of ``{"features", "labels"}`` rows, matching the
    ``ray.data.from_items`` mode consumed by the batch-inference engine
    (my_ray_module.py:32-36,50)."""
    from tpuflow.data.datasets import load_dataset

    ds = load_dataset(dataset, data_dir=data_dir)
    if as_rows:
        return [
            {"features": ds.test.images[i], "labels": int(ds.test.labels[i])}
            for i in range(len(ds.test))
        ]
    val = ShardedLoader(
        ds.test,
        batch_size,
        shuffle=False,  # parity: val loader unshuffled (my_ray_module.py:74)
        pad_tail=True,
        drop_last=False,
    )
    # The registry's class count rides on the loaders so trainers can size
    # model heads from the data instead of re-deriving per dataset name.
    val.num_classes = ds.num_classes
    if val_only:
        return val
    train = ShardedLoader(
        ds.train,
        batch_size,
        shuffle=True,  # parity: train loader shuffled (my_ray_module.py:73)
        seed=seed,
        shard_index=shard_index,
        num_shards=num_shards,
    )
    train.num_classes = ds.num_classes
    return train, val
