"""Distributed communication backend facade (mesh, gang init, shardings).

TPU-native replacement for the reference stack's NCCL/Gloo + torch.distributed
process-group runtime (exercised at reference my_ray_module.py:135,149,177 via
ray.train.torch.prepare_model / get_context): rendezvous is
``jax.distributed.initialize`` over DCN, collectives are XLA's over ICI, and
data-parallel gradient allreduce is emitted by the compiler from shardings —
there is no user-visible collective API, same encapsulation as the reference.
"""

from tpuflow.dist import membership
from tpuflow.dist.membership import Generation, MembershipTimeout, MeshReform
from tpuflow.dist.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_TENSOR,
    barrier,
    batch_sharding,
    data_axis_size,
    ensure_healthy_platform,
    force_cpu_platform,
    initialize,
    is_initialized,
    maybe_enable_async_collectives,
    maybe_enable_compile_cache,
    make_hybrid_mesh,
    make_mesh,
    process_count,
    process_index,
    replicate,
    replicated,
    seed_compile_cache,
    serialize_steps,
    step_fence,
    shard_batch,
    shutdown,
)

__all__ = [
    "AXIS_DATA",
    "Generation",
    "MembershipTimeout",
    "MeshReform",
    "membership",
    "AXIS_EXPERT",
    "AXIS_FSDP",
    "AXIS_SEQ",
    "AXIS_TENSOR",
    "barrier",
    "batch_sharding",
    "data_axis_size",
    "ensure_healthy_platform",
    "force_cpu_platform",
    "initialize",
    "is_initialized",
    "make_hybrid_mesh",
    "maybe_enable_async_collectives",
    "maybe_enable_compile_cache",
    "make_mesh",
    "process_count",
    "process_index",
    "replicate",
    "replicated",
    "seed_compile_cache",
    "serialize_steps",
    "step_fence",
    "shard_batch",
    "shutdown",
]
