"""Elastic gang membership: epoch-numbered mesh generations over a file
rendezvous (ISSUE 7).

The classic gang (PR 2) treats a member loss at process-lifecycle
granularity: the supervisor kills the survivors and ``@retry`` requeues
the whole attempt from the last checkpoint. Podracer-style systems treat
preemptible capacity as the normal case instead — this module is the
mechanism that makes the gang *elastic*: on member loss the supervisor
announces a new **mesh generation** (a monotonically numbered plan naming
the surviving roster and a fresh rendezvous address); survivors drain
in-flight work at their next step fence, tear the old ``jax.distributed``
world down, re-rendezvous as the new generation with a shrunk
data-parallel axis, restore from the multi-tier checkpoint (cross-topology
restore is bit-identical), and continue. When capacity returns, a
relaunched member requests to join and the next generation grows the gang
back.

Protocol (all files live in ``TPUFLOW_MEMBERSHIP_DIR``, set by the gang
launcher to a per-step directory on storage every member shares):

- ``plan.json``              — the CURRENT generation plan, written
  atomically by the supervisor. Members poll it (one ``stat`` per step
  fence); a plan whose ``generation`` exceeds the member's current one is
  a pending re-form.
- ``gen_<g>.joined.<m>``     — member ``m`` connected generation ``g``'s
  world (written after a successful re-init; the supervisor's formation
  watch counts these).
- ``join.<m>``               — a relaunched member ``m`` asks to be
  included in the next (grow) generation.
- ``done.<m>``               — member ``m`` finished the step body
  cleanly (exit-ordering handshake + supervisor forgiveness marker).

Member identity is the ORIGINAL gang rank (``TPUFLOW_PROCESS_ID``); it
never changes across generations and keys the heartbeat file, the log
file and the telemetry ``proc``. The *dense* ``jax`` process id of a
generation is the member's index in the sorted roster — so the lowest
surviving member is always the coordinator of every generation (member 0
in practice: coordinator loss falls back to requeue-the-world, see
``flow/runner.py``).

Runtime teardown notes (the part jax does not support out of the box,
validated against jax 0.4.37 / XLA's coordination service):

- The default distributed client **aborts the process** when the
  coordination service reports a peer death (``client.h:80``) and its
  Python ``missed_heartbeat_callback`` binding is unusable. Elastic gangs
  therefore build the service with an effectively-infinite
  missed-heartbeat budget — failure detection is the supervisor's and
  gloo's job (a dead peer's TCP sockets close instantly, so the blocked
  collective *raises* within milliseconds) — and the client with
  ``shutdown_on_destruction=False``.
- Dropping the Python reference to a client does NOT stop its
  heartbeat/poll threads, and destroying a service that zombie clients
  still poll aborts *them*. Old generations' clients and services are
  therefore **leaked on purpose** (module-level stash, reclaimed at
  process exit); gang members that re-formed exit via ``os._exit`` after
  a done-file handshake in which the service-holding coordinator exits
  last.
- ``xla_bridge._clear_backends()`` misses the ``process_count`` /
  ``local_devices`` lru caches; :func:`_teardown_runtime` clears them
  explicitly or the new generation inherits the old world's shape.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any
from tpuflow.utils import knobs

__all__ = [
    "Generation",
    "MeshReform",
    "MembershipTimeout",
    "enabled",
    "member_id",
    "current_generation",
    "current_plan",
    "pending_reform",
    "reform_after_failure",
    "elastic_initialize",
    "join_generation",
    "quiesce_and_reform",
    "announce",
    "read_plan",
    "joined_members",
    "await_formed",
    "request_join",
    "join_requests",
    "await_plan_including",
    "mark_done",
    "await_done",
    "holds_leaked_runtime",
    "roster_diff",
    "reset",
]

_PLAN_FILE = "plan.json"


class MembershipTimeout(TimeoutError):
    """A rendezvous (formation ack wait, plan wait) missed its deadline —
    the caller falls back to the requeue-the-world verdict."""


@dataclasses.dataclass(frozen=True)
class Generation:
    """One epoch of gang membership: who is in the world and where it
    rendezvouses. ``roster`` holds ORIGINAL member ids; the dense jax
    process id of a member is its index in the sorted roster."""

    generation: int
    roster: tuple[int, ...]
    coordinator: str            # host:port of this generation's rendezvous
    reason: str = "init"        # init | shrink | grow
    deadline: float = 0.0       # unix ts by which the re-form must complete

    def __post_init__(self):
        object.__setattr__(self, "roster", tuple(sorted(self.roster)))

    @property
    def num_processes(self) -> int:
        return len(self.roster)

    def process_id(self, member: int) -> int:
        """Dense jax process id of ``member`` in this generation."""
        return self.roster.index(member)

    def to_json(self) -> dict:
        return {
            "generation": self.generation,
            "roster": list(self.roster),
            "coordinator": self.coordinator,
            "reason": self.reason,
            "deadline": self.deadline,
        }

    @classmethod
    def from_json(cls, obj: dict) -> "Generation":
        return cls(
            generation=int(obj["generation"]),
            roster=tuple(int(m) for m in obj["roster"]),
            coordinator=str(obj["coordinator"]),
            reason=str(obj.get("reason", "init")),
            deadline=float(obj.get("deadline", 0.0)),
        )


class MeshReform(Exception):
    """Control-flow signal raised at a step fence when a new generation is
    pending: the loop must drain, hand state to the checkpoint, and let
    its reform handler tear down + re-rendezvous (mirrors the health
    observatory's ``_RollbackSignal``)."""

    def __init__(self, plan: Generation):
        self.plan = plan
        super().__init__(
            f"mesh re-form to generation {plan.generation} "
            f"({plan.reason}, {plan.num_processes} members)"
        )


def roster_diff(
    old: tuple[int, ...] | list[int], new: tuple[int, ...] | list[int]
) -> tuple[list[int], list[int]]:
    """``(lost, gained)`` members between two rosters."""
    o, n = set(old), set(new)
    return sorted(o - n), sorted(n - o)


# ----------------------------------------------------------- member state
# Per-process view of the current generation, plus the deliberately leaked
# old-generation runtime objects (see the module docstring).
_STATE: dict[str, Any] = {"plan": None, "generation": 0}
_LEAKED: list[Any] = []
_PLAN_CACHE: tuple[float, Generation | None] = (-1.0, None)


def reset() -> None:
    """Forget member-side state (test isolation; leaked runtimes stay
    leaked — they are a process-lifetime commitment)."""
    global _PLAN_CACHE
    _STATE["plan"] = None
    _STATE["generation"] = 0
    _PLAN_CACHE = (-1.0, None)


def membership_dir() -> str | None:
    return knobs.raw("TPUFLOW_MEMBERSHIP_DIR") or None


def enabled() -> bool:
    """Whether this process is a member of an elastic gang."""
    return membership_dir() is not None


def member_id() -> int:
    """This process's ORIGINAL gang rank (stable across generations)."""
    try:
        return int(knobs.raw("TPUFLOW_PROCESS_ID", "0"))
    except ValueError:
        return 0


def current_generation() -> int:
    return int(_STATE["generation"])


def current_plan() -> Generation | None:
    return _STATE["plan"]


def holds_leaked_runtime() -> bool:
    """True when this process stashed old-generation services/clients —
    it must exit LAST (its teardown closes sockets peers may still poll)."""
    return bool(_LEAKED)


# ------------------------------------------------------------- plan files
def _atomic_write(path: str, payload: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def announce(mdir: str, plan: Generation) -> None:
    """Supervisor: publish ``plan`` as the current generation (atomic)."""
    os.makedirs(mdir, exist_ok=True)
    _atomic_write(os.path.join(mdir, _PLAN_FILE), plan.to_json())


def read_plan(mdir: str) -> Generation | None:
    try:
        with open(os.path.join(mdir, _PLAN_FILE)) as f:
            return Generation.from_json(json.load(f))
    except (OSError, ValueError, KeyError):
        return None


def pending_reform() -> Generation | None:
    """Member fence check: the current plan when it names a LATER
    generation than the one this process is in, else None. One ``stat``
    per call on the unchanged-plan fast path (the fence cadence)."""
    global _PLAN_CACHE
    mdir = membership_dir()
    if mdir is None:
        return None
    path = os.path.join(mdir, _PLAN_FILE)
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        return None
    cached_mtime, cached_plan = _PLAN_CACHE
    if mtime != cached_mtime:
        cached_plan = read_plan(mdir)
        _PLAN_CACHE = (mtime, cached_plan)
    plan = cached_plan
    if plan is None or plan.generation <= current_generation():
        return None
    if member_id() not in plan.roster:
        # The supervisor counted this member out (e.g. it was judged lost
        # while alive). Nothing useful to re-form into.
        return None
    return plan


def reform_after_failure(
    exc: BaseException | None = None, timeout_s: float | None = None
) -> Generation | None:
    """Collective-failure classifier: after a collective raised (a dead
    peer's sockets close instantly, so survivors see e.g. "Gloo ...
    Connection closed by peer" within milliseconds), wait briefly for the
    supervisor — which detects the death on its own poll cadence — to
    announce the re-form plan. Returns the plan (the failure WAS a member
    loss) or None (a genuine error: the caller re-raises ``exc``)."""
    if not enabled():
        return None
    if timeout_s is None:
        timeout_s = float(knobs.raw("TPUFLOW_REFORM_WAIT_S", "10"))
    deadline = time.monotonic() + max(timeout_s, 0.0)
    while True:
        plan = pending_reform()
        if plan is not None:
            return plan
        if time.monotonic() >= deadline:
            return None
        time.sleep(0.05)


# ------------------------------------------------------- ack / done files
def _touch(mdir: str, name: str) -> None:
    try:
        os.makedirs(mdir, exist_ok=True)
        _atomic_write(os.path.join(mdir, name), {"ts": time.time()})
    except OSError:
        pass


def _present(mdir: str, prefix: str) -> set[int]:
    out: set[int] = set()
    try:
        names = os.listdir(mdir)
    except OSError:
        return out
    for n in names:
        if n.startswith(prefix) and not n.endswith(".tmp"):
            try:
                out.add(int(n[len(prefix):].partition(".")[0]))
            except ValueError:
                continue
    return out


def joined_members(mdir: str, generation: int) -> set[int]:
    return _present(mdir, f"gen_{generation}.joined.")


def await_formed(
    mdir: str, plan: Generation, *, poll_s: float = 0.05,
    now: Any = time.time,
) -> None:
    """Supervisor: block until every roster member acked joining
    ``plan``'s generation, or raise :class:`MembershipTimeout` at the
    plan's deadline (→ fall back to requeue-the-world)."""
    want = set(plan.roster)
    while True:
        if joined_members(mdir, plan.generation) >= want:
            return
        if plan.deadline and now() > plan.deadline:
            have = sorted(joined_members(mdir, plan.generation))
            raise MembershipTimeout(
                f"generation {plan.generation} missed its re-form deadline:"
                f" joined {have} of {sorted(want)}"
            )
        time.sleep(poll_s)


def request_join(member: int | None = None) -> None:
    """Relaunched member: ask the supervisor for inclusion in the next
    (grow) generation."""
    mdir = membership_dir()
    if mdir is not None:
        _touch(mdir, f"join.{member if member is not None else member_id()}")


def join_requests(mdir: str) -> set[int]:
    return _present(mdir, "join.")


def clear_join_request(mdir: str, member: int) -> None:
    try:
        os.unlink(os.path.join(mdir, f"join.{member}"))
    except OSError:
        pass


def await_plan_including(
    member: int, timeout_s: float, *, poll_s: float = 0.05
) -> Generation:
    """Relaunched member: block until the current plan's roster includes
    ``member`` (the supervisor's grow announcement)."""
    mdir = membership_dir()
    if mdir is None:
        raise MembershipTimeout("no membership dir")
    deadline = time.monotonic() + timeout_s
    while True:
        plan = read_plan(mdir)
        if plan is not None and member in plan.roster:
            return plan
        if time.monotonic() > deadline:
            raise MembershipTimeout(
                f"no generation included member {member} within "
                f"{timeout_s:.0f}s"
            )
        time.sleep(poll_s)


def mark_done(member: int | None = None) -> None:
    """Member: the step body finished cleanly. Doubles as the supervisor's
    forgiveness marker (post-completion teardown crashes of a re-formed
    member must not fail the step) and the exit-ordering handshake."""
    mdir = membership_dir()
    if mdir is not None:
        _touch(mdir, f"done.{member if member is not None else member_id()}")


def done_members(mdir: str) -> set[int]:
    return _present(mdir, "done.")


def await_done(members: set[int], timeout_s: float) -> bool:
    """Leaked-runtime holder: wait (bounded) for the given members' done
    markers before exiting — its exit closes the old services' sockets,
    which must happen after every zombie-client peer is gone."""
    mdir = membership_dir()
    if mdir is None:
        return True
    deadline = time.monotonic() + timeout_s
    while not members <= done_members(mdir):
        if time.monotonic() > deadline:
            return False
        time.sleep(0.05)
    return True


# -------------------------------------------------- runtime (re)lifecycle
def _distributed_state():
    from jax._src import distributed as jdist

    return jdist.global_state


def elastic_initialize(plan: Generation, *, timeout_s: float = 300.0) -> None:
    """Bring up generation ``plan``'s ``jax.distributed`` world for this
    member with a teardown-capable runtime (see the module docstring):
    the coordinator (dense id 0) hosts a coordination service whose
    missed-heartbeat budget is effectively infinite (failure detection
    belongs to the supervisor + gloo), every member's client skips the
    shutdown-on-destruction barrier. Emits the ``dist.mesh_generation``
    gauge. Single-member generations skip the runtime entirely."""
    from tpuflow import obs

    me = member_id()
    pid = plan.process_id(me)
    gs = _distributed_state()
    if plan.num_processes > 1:
        from jax._src.lib import xla_extension

        if pid == 0:
            svc = xla_extension.get_distributed_runtime_service(
                "[::]:" + plan.coordinator.rsplit(":", 1)[1],
                plan.num_processes,
                heartbeat_interval=10,
                max_missing_heartbeats=1_000_000,
            )
            _LEAKED.append(svc)
            gs.service = svc
        cli = xla_extension.get_distributed_runtime_client(
            plan.coordinator,
            pid,
            init_timeout=int(max(timeout_s, 1.0)),
            shutdown_on_destruction=False,
            use_compression=True,
        )
        cli.connect()
        _LEAKED.append(cli)
        gs.client = cli
    gs.process_id = pid
    gs.num_processes = plan.num_processes
    gs.coordinator_address = plan.coordinator
    _STATE["plan"] = plan
    _STATE["generation"] = plan.generation
    from tpuflow.dist import mesh as _mesh

    _mesh._initialized_multihost = plan.num_processes > 1
    obs.gauge(
        "dist.mesh_generation",
        float(plan.generation),
        members=plan.num_processes,
        reason=plan.reason,
    )


def join_generation(plan: Generation, *, timeout_s: float = 300.0) -> None:
    """Relaunched member: enter ``plan``'s world (fresh process — no old
    runtime to tear down) and ack the join."""
    elastic_initialize(plan, timeout_s=timeout_s)
    mdir = membership_dir()
    if mdir is not None:
        _touch(mdir, f"gen_{plan.generation}.joined.{member_id()}")


def _teardown_runtime() -> None:
    """Abandon the current generation's runtime WITHOUT collective
    shutdown barriers (peers may be dead): stash the client/service so
    their threads keep a live referent (zombie threads outlive the Python
    reference — see module docstring), then clear every backend cache a
    re-initialization consults. All device arrays become invalid; callers
    must have handed state to the checkpoint already."""
    import jax
    from jax._src import xla_bridge

    gs = _distributed_state()
    gs.preemption_sync_manager = None
    if gs.client is not None:
        _LEAKED.append(gs.client)
        gs.client = None
    if gs.service is not None:
        _LEAKED.append(gs.service)
        gs.service = None
    xla_bridge._clear_backends()
    # _clear_backends misses these lru caches; stale entries would make
    # the new generation report the OLD world's process count/devices.
    for cached in ("process_count", "local_devices"):
        fn = getattr(xla_bridge, cached, None)
        if hasattr(fn, "cache_clear"):
            fn.cache_clear()
    jax.clear_caches()
    from tpuflow.dist import mesh as _mesh

    _mesh._initialized_multihost = False


def quiesce_and_reform(plan: Generation) -> None:
    """Member-side re-form: tear the old world down and join ``plan``.

    The caller (the train loop's ``MeshReform`` handler) has already
    drained in-flight work and handed state to the checkpoint — every
    device array dies here. The join is acked for the supervisor's
    formation watch; connect() itself is the rendezvous barrier (it
    retries until the new coordinator's service is up, bounded by the
    plan deadline).

    A single-process world re-forming into a single-process generation
    (the degenerate case in-process tests exercise) keeps its backend:
    there is no distributed runtime to replace, and clearing backends
    would invalidate device arrays held elsewhere in the process."""
    timeout = max(plan.deadline - time.time(), 5.0) if plan.deadline else 120.0
    gs = _distributed_state()
    if plan.num_processes == 1 and gs.client is None:
        _STATE["plan"] = plan
        _STATE["generation"] = plan.generation
        from tpuflow import obs

        obs.gauge(
            "dist.mesh_generation",
            float(plan.generation),
            members=1,
            reason=plan.reason,
        )
        mdir = membership_dir()
        if mdir is not None:
            _touch(mdir, f"gen_{plan.generation}.joined.{member_id()}")
        return
    _teardown_runtime()
    join_generation(plan, timeout_s=timeout)
