"""Mesh construction, multi-host gang initialization, and sharding helpers.

Capability parity map (reference `outerbounds/ray-torch-distributed-checkpoint`):

- ``initialize``    ↔ Ray Train's rendezvous + torch.distributed process-group
  init done before the worker loop runs (reference my_ray_module.py:149,177 and
  the @metaflow_ray gang barrier with ``all_nodes_started_timeout``,
  train_flow.py:42). Here it is ``jax.distributed.initialize`` over DCN with an
  initialization timeout.
- ``make_mesh``     ↔ the implicit world of DDP ranks. A named
  ``jax.sharding.Mesh`` with axes ``('data','fsdp','tensor','seq')`` so DP,
  FSDP, tensor and sequence/context parallelism are all layouts on one object.
- ``batch_sharding``/``replicated``/``shard_batch`` ↔ prepare_data_loader's
  rank-sharding + DDP's replicate-and-allreduce (my_ray_module.py:128-135):
  sharding the batch along 'data' while params are replicated makes GSPMD emit
  the gradient all-reduce over ICI inside the jitted step.
- ``barrier``       ↔ the implicit per-epoch barrier in ray.train.report()
  (my_ray_module.py:203).
"""

from __future__ import annotations

import logging
import math
import os
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from tpuflow.utils import knobs

logger = logging.getLogger("tpuflow.dist")

# Canonical mesh axis names. DP shards batches on 'data'; FSDP shards params &
# optimizer state on ('data','fsdp'); tensor parallelism shards weight matrices
# on 'tensor'; ring/all-to-all sequence parallelism shards the sequence
# dimension on 'seq'.
AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_TENSOR = "tensor"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"

_DEFAULT_AXES = (AXIS_DATA, AXIS_FSDP, AXIS_TENSOR, AXIS_SEQ, AXIS_EXPERT)

_initialized_multihost = False


def _platform_is_cpu() -> bool:
    """Best-effort 'is this process targeting XLA:CPU?' WITHOUT forcing
    backend initialization (callers run before init on purpose — probing
    a dead TPU tunnel from here would hang them). Pre-init the verdict
    comes from the platform selection config/env that force_cpu_platform
    sets; an unset platform means 'default' (an accelerator when one
    exists), which reports False."""
    try:
        backends = getattr(jax._src.xla_bridge, "_backends", None)
        if backends:  # initialized: the authoritative answer is free
            return jax.default_backend() == "cpu"
    except Exception:
        pass
    selected = ""
    try:
        selected = jax.config.jax_platforms or ""
    except AttributeError:
        pass
    selected = selected or os.environ.get("JAX_PLATFORMS", "")
    if selected:
        return selected.split(",")[0].strip().lower() == "cpu"
    # No explicit selection: on a CPU-only host the 'default' platform IS
    # XLA:CPU, so fall back to ensure_healthy_platform's probe verdict
    # (it records the probed backend name for exactly this kind of
    # pre-init consumer). Unset means no probe ran — an accelerator-
    # targeting entry point — and reports False.
    return knobs.raw("TPUFLOW_PLATFORM_BACKEND", "") == "cpu"


def maybe_enable_compile_cache(run_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a durable directory.

    On real TPU the first compile of a training step costs 20-40 s; the
    persistent cache makes every LATER process (retry attempt, resumed
    run, next epoch's eval flow, gang restart) load the compiled
    executable instead of recompiling — the same jit program key hits
    across processes. Default ON at ``$TPUFLOW_HOME/compile_cache``
    (compilation caching is keyed on HLO + config, never stale);
    ``TPUFLOW_COMPILE_CACHE`` recognizes 0/false/off (disable),
    1/true/on/unset (default directory), and ``run`` (key the cache
    under ``<run_dir>/compile_cache`` — for deployments where only the
    run directory rides shared storage, e.g. a requeued k8s gang whose
    pod-local ``$HOME`` is ephemeral: every retry/requeue attempt of
    the run shares the cache even though each lands on a fresh pod);
    any other value is used as the cache directory itself. ``run``
    with no ``run_dir`` known falls back to the default directory.
    Returns the directory in use, or None.
    Safe to call any number of times and before/after backend init —
    every train entry point (train_gpt, Trainer.fit, gang members, the
    flow runner, bench children) calls it, so the cache is default-on
    without any caller wiring.

    CPU platforms are excluded: jaxlib's XLA:CPU AOT loader
    (cpu_aot_loader.cc) re-checks LLVM machine features when it
    deserializes a cached executable, and XLA's tuning pseudo-features
    (+prefer-no-scatter/+prefer-no-gather) never appear in the host
    feature probe — reloads warn about a machine mismatch and can
    abort the process outright (observed: deterministic SIGABRT in the
    pipeline-parallel acceptance test when its step reloaded from
    cache). CPU compiles are seconds, so the cache buys nothing there;
    ``TPUFLOW_COMPILE_CACHE_CPU=1`` force-enables for experiments.
    """
    knob = knobs.raw("TPUFLOW_COMPILE_CACHE", "")
    if knob.lower() in ("0", "false", "off"):
        return None
    if (
        _platform_is_cpu()
        and knobs.raw("TPUFLOW_COMPILE_CACHE_CPU") != "1"
    ):
        return None
    if knob.lower() == "run":
        # Per-run-dir keying: callers that know their run/storage dir
        # pass it through (train_gpt, Trainer.fit, gang_exec). Unknown
        # run dir → default directory, never a literal './run'.
        knob = (
            os.path.join(run_dir, "compile_cache") if run_dir else ""
        )
    elif knob.lower() in ("", "1", "true", "on"):
        # Conventional enable spellings mean "default directory" — NOT a
        # relative directory literally named '1' in whatever cwd each
        # process happens to have (which would silently give every
        # process a disjoint cache).
        knob = ""
    cache_dir = knob or os.path.join(
        knobs.raw(
            "TPUFLOW_HOME", os.path.join(os.path.expanduser("~"), ".tpuflow")
        ),
        "compile_cache",
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except (OSError, AttributeError):
        return None  # unwritable dir / very old jax: silently off
    return cache_dir


# libtpu scheduling flags that let the TPU compiler's latency-hiding
# scheduler run collectives ASYNCHRONOUSLY and slide them behind compute
# (ISSUE 10 comm/compute overlap — the other half of the per-microbatch
# reduce-scatter the accumulation scan issues; without these the
# collective still serializes after its producer). The MaxText-style
# staging: appended to LIBTPU_INIT_ARGS, which libtpu reads ONCE at
# backend init — call any time before the first jax device touch.
_ASYNC_COLLECTIVE_FLAGS = (
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_enable_async_all_gather=true",
)


def maybe_enable_async_collectives() -> bool:
    """Stage the async-collective libtpu flags into ``LIBTPU_INIT_ARGS``.

    Returns True when the flags are (now) staged. No-op — returns False —
    on CPU platforms (libtpu never loads; the env var would be inert
    noise in test processes) and under ``TPUFLOW_COMM_OVERLAP=0`` (the
    same knob that turns off the per-microbatch reduce-scatter in
    ``train.step.make_train_step``, so one switch governs the whole
    overlap story). Flags already present — e.g. an operator's own
    LIBTPU_INIT_ARGS — are never duplicated or overridden: an explicit
    ``--xla_tpu_enable_async_collective_fusion=false`` wins.
    Call sites: gang member bootstrap (flow.gang_exec) and the in-process
    train entry (train.train_gpt), both ahead of backend init.
    """
    if knobs.raw("TPUFLOW_COMM_OVERLAP", "1").lower() in (
        "0", "false", "off",
    ):
        return False
    if _platform_is_cpu():
        return False
    current = os.environ.get("LIBTPU_INIT_ARGS", "")
    added = []
    for flag in _ASYNC_COLLECTIVE_FLAGS:
        name = flag.split("=", 1)[0]
        if name in current:
            continue  # operator already took a position on this flag
        added.append(flag)
    if added:
        os.environ["LIBTPU_INIT_ARGS"] = " ".join(
            ([current] if current else []) + added
        )
    return True


def seed_compile_cache(src_dir: str, cache_dir: str) -> int:
    """Rsync-style one-way seed of a prewarmed persistent compile cache
    (ISSUE 9 startup-latency satellite): copy every cache entry from
    ``src_dir`` (written ahead of time by ``tools/prewarm_cache.py``)
    into ``cache_dir`` that isn't already there. Entries are
    content-keyed by XLA (filename = hash of HLO + compile options), so
    an existing name IS the same bytes — never overwritten, and a
    half-copied file can't poison the cache because the copy goes
    through a temp name + atomic rename. Returns the number of entries
    copied; missing/unreadable source dirs are a no-op (prewarm is an
    optimization, never a launch gate)."""
    import shutil

    copied = 0
    try:
        names = sorted(os.listdir(src_dir))
    except OSError:
        return 0
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        return 0
    for name in names:
        src = os.path.join(src_dir, name)
        dst = os.path.join(cache_dir, name)
        if not os.path.isfile(src) or os.path.exists(dst):
            continue
        try:
            tmp = f"{dst}.seed.{os.getpid()}.tmp"
            shutil.copy2(src, tmp)
            os.replace(tmp, dst)
            copied += 1
        except OSError:
            continue  # best effort: a bad entry just compiles normally
    return copied


def force_cpu_platform(n_devices: int = 8, *, exact: bool = False) -> None:
    """Select an n-device host-CPU JAX platform, if backends aren't up yet.

    Shared bootstrap for every entry point that must not touch real chips
    (tests, dryruns, CPU benches, gang subprocesses): sets the platform env
    var for child processes, then applies the config updates that take
    effect before backend initialization. ``exact`` pins the device count
    even when the inherited config asks for more (gang subprocesses own a
    fixed per-process slice of the virtual world). If a backend is already
    initialized the updates are skipped silently — callers that need a
    device-count guarantee should assert on ``len(jax.devices())``.
    """
    try:
        jax.config.update("jax_platforms", "cpu")
        if hasattr(jax.config, "jax_num_cpu_devices"):
            if exact or jax.config.jax_num_cpu_devices < n_devices:
                jax.config.update("jax_num_cpu_devices", n_devices)
        else:
            # Older jax (< 0.5) has no jax_num_cpu_devices config: the
            # virtual device count comes from XLA_FLAGS, honored only if
            # set before backend init (same timing contract as the config).
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags or exact:
                import re

                flags = re.sub(
                    r"--xla_force_host_platform_device_count=\d+", "", flags
                ).strip()
                os.environ["XLA_FLAGS"] = (
                    f"{flags} "
                    f"--xla_force_host_platform_device_count={n_devices}"
                ).strip()
    except RuntimeError:
        # Backends already initialized: leave the parent's platform AND the
        # env untouched so subprocesses don't silently diverge from it.
        return
    os.environ["JAX_PLATFORMS"] = "cpu"


def ensure_healthy_platform(
    n_cpu_devices: int = 8, *, probe_timeout_s: float = 90.0
) -> str:
    """Make sure first device use won't hang; fall back to CPU if it would.

    Accelerator platforms behind a network tunnel can hang indefinitely at
    backend initialization (observed: ``jax.devices()`` never returning on an
    unreachable single-chip TPU proxy). Flow CLIs and benches call this before
    any JAX device use: it probes ``jax.devices()`` in a short-lived
    subprocess with a timeout, and selects the host-CPU platform (with
    ``n_cpu_devices`` virtual devices) when the probe fails or times out —
    the failure-detection counterpart of the reference's cluster-formation
    timeout (reference train_flow.py:42 all_nodes_started_timeout).

    Returns the platform chosen: 'default' (healthy) or 'cpu' (fallback).
    The verdict is cached in TPUFLOW_PLATFORM_PROBED (inherited by gang
    subprocesses) and in a short-TTL file under TPUFLOW_HOME so repeated CLI
    invocations don't re-pay the probe (a dead tunnel would otherwise stall
    every command by the full timeout).
    """
    import subprocess
    import sys

    if knobs.raw("TPUFLOW_FORCE_CPU") == "1":
        force_cpu_platform(n_cpu_devices)
        return "cpu"
    if _platform_is_cpu():
        # Platform already pinned to CPU (test conftest, gang subprocess,
        # bench parent): there is no accelerator init to protect against,
        # and the subprocess probe targets the DEFAULT platform — with a
        # hanging tunnel it would charge this already-decided process the
        # full probe timeout (observed: every flow-CLI test paying 90 s
        # while the axon tunnel hung half-open). Still force the virtual
        # device count: a child that merely INHERITED JAX_PLATFORMS=cpu
        # would otherwise come up with 1 device (no-op if a backend is
        # already initialized).
        force_cpu_platform(n_cpu_devices)
        return "cpu"
    cached = knobs.raw("TPUFLOW_PLATFORM_PROBED") or _probe_cache_read()
    if cached == "cpu":
        force_cpu_platform(n_cpu_devices)
        return "cpu"
    if cached == "default":
        return "default"
    backend = ""
    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; print(jax.default_backend())",
            ],
            timeout=probe_timeout_s,
            capture_output=True,
            text=True,
        )
        healthy = proc.returncode == 0
        if healthy:
            backend = proc.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        healthy = False
    verdict = "default" if healthy else "cpu"
    os.environ["TPUFLOW_PLATFORM_PROBED"] = verdict
    # The probed backend name ('tpu'/'cpu'/...) lets callers decide whether
    # the healthy default is actually an accelerator (bench train leg).
    os.environ["TPUFLOW_PLATFORM_BACKEND"] = backend
    _probe_cache_write(verdict, backend)
    if not healthy:
        logger.warning(
            "default JAX platform failed its %ds health probe; falling back "
            "to the host-CPU platform with %d virtual devices",
            int(probe_timeout_s),
            n_cpu_devices,
        )
        force_cpu_platform(n_cpu_devices)
    return verdict


_PROBE_CACHE_TTL_S = 600.0


def _probe_cache_path() -> str:
    home = knobs.raw(
        "TPUFLOW_HOME", os.path.join(os.path.expanduser("~"), ".tpuflow")
    )
    return os.path.join(home, "platform_probe.json")


def _probe_cache_read() -> str | None:
    import json
    import time

    try:
        with open(_probe_cache_path()) as f:
            rec = json.load(f)
        if time.time() - float(rec["time"]) < _PROBE_CACHE_TTL_S:
            os.environ.setdefault(
                "TPUFLOW_PLATFORM_BACKEND", rec.get("backend", "")
            )
            return rec["verdict"]
    except (OSError, ValueError, KeyError):
        pass
    return None


def _probe_cache_write(verdict: str, backend: str = "") -> None:
    import json
    import time

    path = _probe_cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(
                {"verdict": verdict, "backend": backend, "time": time.time()},
                f,
            )
        os.replace(tmp, path)
    except OSError:
        pass


def is_initialized() -> bool:
    """True if multi-host ``jax.distributed`` was initialized by us."""
    return _initialized_multihost


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    *,
    timeout_s: float = 300.0,
) -> None:
    """Gang-initialize the multi-host runtime (no-op for a single process).

    Parity: the @metaflow_ray cluster formation barrier with
    ``all_nodes_started_timeout=60*5`` (reference train_flow.py:42) — all
    processes must join within ``timeout_s`` or initialization fails (and the
    flow layer's retry wrapper reruns the step).

    Arguments may also come from the standard env vars consumed by
    ``jax.distributed.initialize`` (auto-detection on TPU pod slices).
    """
    global _initialized_multihost
    if _initialized_multihost:
        return
    env_world = knobs.raw("TPUFLOW_NUM_PROCESSES")
    if num_processes is None and env_world is not None:
        num_processes = int(env_world)
        coordinator_address = coordinator_address or knobs.raw(
            "TPUFLOW_COORDINATOR", "127.0.0.1:42042"
        )
        process_id = (
            process_id
            if process_id is not None
            else int(knobs.raw("TPUFLOW_PROCESS_ID", "0"))
        )
    if (
        num_processes is not None
        and num_processes > 1
        and knobs.raw("TPUFLOW_MEMBERSHIP_DIR")
    ):
        # Elastic gang (ISSUE 7): generation 0 comes up through the
        # membership runtime — a teardown-capable client/service pair —
        # so a later member loss can re-form the mesh in place instead of
        # requeueing the world. Same rendezvous semantics, same timeout.
        from tpuflow.dist import membership

        plan = membership.Generation(
            generation=0,
            roster=tuple(range(num_processes)),
            coordinator=coordinator_address or "127.0.0.1:42042",
            reason="init",
        )
        membership.elastic_initialize(plan, timeout_s=timeout_s)
        _initialized_multihost = True
        logger.info(
            "elastic gang initialized: process %d/%d (generation 0)",
            jax.process_index(),
            jax.process_count(),
        )
        return
    if num_processes is None or num_processes <= 1:
        if num_processes is None and _looks_multihost():
            # Real pod slice with no explicit config: let jax auto-detect the
            # cluster (TPU metadata / Cloud env) rather than silently running
            # N disconnected single-host jobs.
            jax.distributed.initialize(initialization_timeout=int(timeout_s))
            _initialized_multihost = True
            return
        # Single-process (possibly multi-device) — nothing to rendezvous.
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        initialization_timeout=int(timeout_s),
    )
    _initialized_multihost = True
    logger.info(
        "gang initialized: process %d/%d, %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.device_count(),
    )


def _looks_multihost() -> bool:
    """Heuristic: are we one worker of a multi-host TPU pod slice? Checked
    only when the caller gave no explicit gang config."""
    for var in ("TPU_WORKER_ID", "CLOUD_TPU_TASK_ID", "MEGASCALE_SLICE_ID"):
        if var in os.environ:
            hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
            return "," in hostnames or var != "TPU_WORKER_ID"
    return False


def shutdown() -> None:
    """Tear down the multi-host runtime if we started it.

    An elastic gang that re-formed at least once never reaches here — its
    members exit via the membership done-handshake + ``os._exit`` (zombie
    runtime threads from torn-down generations make ordinary interpreter
    teardown unsafe; see ``dist.membership``). A generation-0 elastic
    world shuts down like any other: every member is alive, so the
    client's shutdown barrier completes normally."""
    global _initialized_multihost
    if _initialized_multihost:
        jax.distributed.shutdown()
        _initialized_multihost = False


def process_index() -> int:
    """This host's rank (↔ get_world_rank at host granularity)."""
    return jax.process_index()


def process_count() -> int:
    """Number of host processes in the gang."""
    return jax.process_count()


def make_mesh(
    axes: Mapping[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a named device mesh.

    ``axes`` maps axis name → size; a size of ``-1`` (at most one) is inferred
    from the device count. Default: all devices on the 'data' axis — the pure
    data-parallel layout matching the reference's DDP world
    (reference my_ray_module.py:240-243 ScalingConfig(num_workers)).

    Unlisted canonical axes are appended with size 1 so sharding rules that
    mention e.g. 'fsdp' or 'tensor' always resolve against any tpuflow mesh.
    """
    devices = list(devices if devices is not None else jax.devices())
    ndev = len(devices)
    if axes is None:
        axes = {AXIS_DATA: ndev}
    axes = dict(axes)
    unknown = [k for k, v in axes.items() if v == -1]
    if len(unknown) > 1:
        raise ValueError(f"at most one axis may be -1, got {unknown}")
    known = math.prod(v for v in axes.values() if v != -1)
    if unknown:
        if ndev % known:
            raise ValueError(f"{ndev} devices not divisible by {known}")
        axes[unknown[0]] = ndev // known
    total = math.prod(axes.values())
    if total != ndev:
        raise ValueError(
            f"mesh {dict(axes)} wants {total} devices but {ndev} are available"
        )
    for name in _DEFAULT_AXES:
        axes.setdefault(name, 1)
    names = tuple(axes.keys())
    shape = tuple(axes[n] for n in names)
    try:
        # Topology-aware assignment: on a real slice this lays mesh axes onto
        # the ICI torus (nearest-neighbor links for the inner axes) instead of
        # whatever order the flat device list happens to have.
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(
            shape, devices=devices, allow_split_physical_axes=True
        )
    except Exception:  # non-TPU platforms / unusual topologies
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, names)


def make_hybrid_mesh(
    dcn_axes: Mapping[str, int],
    ici_axes: Mapping[str, int],
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Multi-slice mesh: ``dcn_axes`` partition across TPU slices (traffic
    rides the data-center network), ``ici_axes`` partition within each slice
    (traffic rides the chip interconnect).

    The standard multi-pod recipe — e.g. 2× v5e-16 slices as
    ``make_hybrid_mesh({"data": 2}, {"fsdp": 16})``: the gradient all-reduce
    crosses DCN once per step (bandwidth-tolerant), while FSDP's per-layer
    all-gathers/reduce-scatters stay on ICI (latency-critical) — the axis
    placement SURVEY.md §1's scaling model prescribes. Axis sizes must
    multiply to the slice count and per-slice device count respectively;
    canonical axes missing from either map are appended at size 1 (on the
    ICI side) so every tpuflow sharding rule resolves.

    Slices are identified by ``device.slice_index`` (TPU runtimes expose
    it); on a multi-process CPU gang — the dev-mode analogue of pod
    slices over DCN, where every CPU device reports slice 0 —
    ``device.process_index`` stands in, so one host == one slice and the
    DCN axes partition across the gang's processes. On single-slice or
    CPU platforms a DCN product of 1 degrades to exactly ``make_mesh``
    semantics.
    """
    devices = list(devices if devices is not None else jax.devices())

    def _slice_id(d) -> int:
        return getattr(d, "slice_index", 0) or 0
    dcn_axes = dict(dcn_axes)
    ici_axes = dict(ici_axes)
    overlap = set(dcn_axes) & set(ici_axes)
    if overlap:
        raise ValueError(f"axes {sorted(overlap)} appear in both dcn and ici maps")
    n_slices = math.prod(dcn_axes.values()) if dcn_axes else 1
    if n_slices == 1:
        return make_mesh({**dcn_axes, **ici_axes}, devices=devices)
    if any(v == -1 for v in (*dcn_axes.values(), *ici_axes.values())):
        raise ValueError(
            "-1 axis inference is not supported in multi-slice hybrid "
            "meshes; specify every axis size explicitly"
        )

    slice_ids = sorted({_slice_id(d) for d in devices})
    if len(slice_ids) != n_slices and all(
        getattr(d, "platform", "") == "cpu" for d in devices
    ):
        # Multi-process CPU gang (the dev-mode analogue of pod slices
        # over DCN): every CPU device reports slice_index 0, so the
        # process becomes the slice — one host == one slice, DCN axes
        # partition across the gang's processes.
        def _slice_id(d) -> int:  # noqa: F811 — deliberate rebind
            return getattr(d, "process_index", 0)

        slice_ids = sorted({_slice_id(d) for d in devices})
    if len(slice_ids) != n_slices:
        raise ValueError(
            f"dcn axes {dict(dcn_axes)} want {n_slices} slices but the "
            f"devices span {len(slice_ids)} (slice ids {slice_ids})"
        )
    per_slice = [d for d in devices if _slice_id(d) == slice_ids[0]]
    n_ici = math.prod(ici_axes.values())
    if any(
        sum(1 for d in devices if _slice_id(d) == s) != len(per_slice)
        for s in slice_ids
    ) or n_ici != len(per_slice):
        raise ValueError(
            f"ici axes {dict(ici_axes)} want {n_ici} devices per slice; "
            f"slices are uneven or sized differently"
        )
    for name in _DEFAULT_AXES:
        if name not in dcn_axes:
            ici_axes.setdefault(name, 1)
    names = tuple(dcn_axes.keys()) + tuple(ici_axes.keys())
    shape = tuple(dcn_axes.values()) + tuple(ici_axes.values())
    try:
        from jax.experimental import mesh_utils

        # create_hybrid_device_mesh takes same-length per-axis (ici, dcn)
        # shapes whose elementwise product is the mesh shape: our DCN axes
        # are ici-size 1 and vice versa, giving DCN axes outermost
        # (contiguous slices) and ICI axes laid onto each slice's torus.
        dev_array = mesh_utils.create_hybrid_device_mesh(
            (1,) * len(dcn_axes) + tuple(ici_axes.values()),
            tuple(dcn_axes.values()) + (1,) * len(ici_axes),
            devices=devices,
            allow_split_physical_axes=True,
        )
    except Exception as e:
        # Fallback: group by slice id (outer = DCN), flat order within.
        # Correct slice placement, but the ICI axes lose torus-aware layout
        # — say so instead of silently degrading collective locality.
        logger.warning(
            "create_hybrid_device_mesh failed (%s); falling back to "
            "slice-grouped flat device order — ICI collectives may not be "
            "nearest-neighbor",
            e,
        )
        by_slice = [
            [d for d in devices if _slice_id(d) == s]
            for s in slice_ids
        ]
        dev_array = np.asarray(by_slice).reshape(shape)
    return Mesh(dev_array, names)


def data_axis_size(mesh: Mesh) -> int:
    """Number of data-parallel shards (the reference's world size,
    my_ray_module.py:149)."""
    size = 1
    for name in (AXIS_DATA, AXIS_FSDP):
        if name in mesh.shape:
            size *= mesh.shape[name]
    return size


def batch_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Sharding for a batch: leading dim split over the data(+fsdp) axes.

    Parity: DistributedSampler's each-rank-sees-1/world slice
    (reference my_ray_module.py:128-129), expressed as a layout instead of a
    sampler wrapper.
    """
    data_axes = tuple(n for n in (AXIS_DATA, AXIS_FSDP) if n in mesh.shape)
    spec = P(data_axes if data_axes else None, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (parity: DDP's replicated parameters and the
    rank-0 broadcast at wrap time, reference my_ray_module.py:135)."""
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh):
    """Place a host-local pytree of numpy arrays onto the mesh, sharded on the
    batch dimension.

    Single-process: a plain device_put with the batch sharding. Multi-host:
    each process contributes its local shard
    (``jax.make_array_from_process_local_data``), the TPU-native analogue of
    per-rank DataLoader shards (reference my_ray_module.py:128-129).

    Batches whose leading dim does not divide by the data-shard count (e.g.
    a 2-row debug batch on an 8-way mesh — a case the reference's per-worker
    batch math ``global//num_workers``, my_ray_module.py:230, never produces)
    are REPLICATED instead: every device computes the full batch, the
    data-axis grad reduction averages identical values, so the numerics are
    unchanged and only the parallel speedup is lost. Multi-host raises,
    since a replicated global array cannot be assembled from distinct
    per-host shards.
    """
    nshard = data_axis_size(mesh)
    nproc = jax.process_count()
    # Multi-host: each process feeds its local slice, which must divide by
    # the shards this process contributes (global shards / processes).
    if nproc > 1 and nshard % nproc != 0:
        raise ValueError(
            f"{nshard}-way data sharding cannot be fed evenly by {nproc} "
            "processes; make the mesh data axes a multiple of the process "
            "count"
        )
    local_shards = nshard // nproc if nproc > 1 else nshard

    def _put(x):
        x = np.asarray(x)
        if x.ndim == 0:
            # Scalar leaves (loss weights, epoch ids) have no batch dim.
            sharding = replicated(mesh)
        elif nproc > 1:
            if x.shape[0] % local_shards != 0:
                raise ValueError(
                    f"local batch dim {x.shape[0]} is not divisible by the "
                    f"{local_shards} data shards this process contributes "
                    f"({nshard}-way sharding over {nproc} processes); pad "
                    "the batch (see data.ShardedLoader) or shrink the mesh"
                )
            sharding = batch_sharding(mesh, x.ndim)
        elif x.shape[0] % nshard != 0:
            if (x.shape[0], nshard) not in _warned_replicate:
                _warned_replicate.add((x.shape[0], nshard))
                logger.warning(
                    "batch dim %d not divisible by %d-way data sharding; "
                    "replicating (correct but unparallelized)",
                    x.shape[0],
                    nshard,
                )
            sharding = replicated(mesh)
        else:
            sharding = batch_sharding(mesh, x.ndim)
        if nproc > 1:
            return jax.make_array_from_process_local_data(sharding, x)
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(_put, batch)


_warned_replicate: set = set()


def replicate(tree, mesh: Mesh):
    """Place a pytree fully-replicated on the mesh (parity: DDP's replicated
    params + rank-0 broadcast at wrap time, reference my_ray_module.py:135).
    Also normalizes mixed/committed device placements after a restore."""
    sharding = replicated(mesh)
    if jax.process_count() == 1:
        return jax.device_put(tree, sharding)

    # Multi-host: device_put rejects shardings that span non-addressable
    # (remote-host) devices. Host leaves become global replicated arrays
    # from the identical per-process copies (same mechanism shard_batch
    # uses for scalar leaves); already-global arrays — e.g. a multi-host
    # restore's output — reshard through a jitted identity, which XLA
    # lowers to whatever collective the move needs.
    def place(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return jax.jit(lambda a: a, out_shardings=sharding)(x)
        return jax.make_array_from_process_local_data(sharding, np.asarray(x))

    return jax.tree_util.tree_map(place, tree)


def serialize_steps() -> bool:
    """True when a hot loop must block each step before dispatching the next.

    XLA:CPU's collective rendezvous (rendezvous.cc) *terminates the
    process* when a participant thread fails to arrive within 40 s. On an
    oversubscribed host-CPU simulation (8 virtual devices on a 1-core dev
    box) asynchronously queued train-step programs plus the Python
    dispatch loop starve the per-device executor threads long enough to
    trip exactly that: the first epoch of the MLP flow died with
    "Expected 8 threads to join the rendezvous, but only 7 of them
    arrived" at op_id=1. Blocking per step keeps at most one collective
    program in flight and parks the Python thread, which is precisely
    the regime every test and bench leg already runs green. Accelerator
    platforms return False and keep fully async dispatch.
    """
    return jax.default_backend() == "cpu" and len(jax.devices()) > 1


def step_fence(x):
    """Block on ``x`` when :func:`serialize_steps` says the platform needs
    serialized dispatch; a no-op pass-through on accelerators. Hot loops
    call this unconditionally on each step's output so the decision (and
    its rationale, above) lives in exactly one place."""
    if serialize_steps():
        jax.block_until_ready(x)
    return x


def barrier(name: str = "tpuflow") -> None:
    """Block until all processes reach this point (parity: the collective
    behavior of ray.train.report, reference my_ray_module.py:203-205)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
