"""Flow orchestration layer: the Metaflow-capability replacement.

Authoring: ``FlowSpec``, ``@step``, ``Parameter``, ``current``; decorators
``@retry``, ``@tpu`` (gang), ``@kubernetes``, ``@pypi``, ``@card``,
``@device_profile``, ``@schedule``, ``@trigger_on_finish``; client API
``Run``/``Task``/``namespace``; card components ``Markdown``/``Table``/
``Image``. See tpuflow.flow.runner for execution semantics."""

from tpuflow.flow.cards import (
    CardBuffer,
    Image,
    Markdown,
    Table,
    metrics_table,
    timeline_card,
    training_curve_card,
)
from tpuflow.flow.client import (
    Flow,
    Run,
    Task,
    default_namespace,
    get_namespace,
    namespace,
)
from tpuflow.flow.decorators import (
    card,
    device_profile,
    kubernetes,
    pypi,
    retry,
    schedule,
    tpu,
    trigger_on_finish,
)
from tpuflow.flow.spec import FlowSpec, Parameter, current, step

__all__ = [
    "CardBuffer",
    "Flow",
    "FlowSpec",
    "default_namespace",
    "get_namespace",
    "Image",
    "Markdown",
    "Parameter",
    "Run",
    "Table",
    "Task",
    "card",
    "metrics_table",
    "timeline_card",
    "training_curve_card",
    "current",
    "device_profile",
    "kubernetes",
    "namespace",
    "pypi",
    "retry",
    "schedule",
    "step",
    "tpu",
    "trigger_on_finish",
]
