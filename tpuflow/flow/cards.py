"""Card components + HTML renderer.

Replaces metaflow.cards as the eval flow uses them (eval_flow.py:15,56,
96-139): ``Markdown``, ``Table`` (rows of component/str cells), and
``Image.from_matplotlib``. A step decorated with ``@card`` gets
``current.card`` — an appendable buffer rendered to ``card.html`` in the task
directory when the step completes."""

from __future__ import annotations

import base64
import html
import io
from typing import Any, Sequence


class Markdown:
    """Markdown component (headers, bold, inline text — the subset the
    reference cards use)."""

    def __init__(self, text: str):
        self.text = text

    def _render(self) -> str:
        lines = []
        for line in self.text.split("\n"):
            stripped = line.strip()
            if stripped.startswith("#"):
                level = len(stripped) - len(stripped.lstrip("#"))
                level = min(level, 6)
                lines.append(
                    f"<h{level}>{html.escape(stripped[level:].strip())}</h{level}>"
                )
            elif stripped:
                text = html.escape(stripped)
                # minimal **bold** support
                while "**" in text:
                    text = text.replace("**", "<b>", 1).replace("**", "</b>", 1)
                lines.append(f"<p>{text}</p>")
        return "\n".join(lines)


class Image:
    """Image component; ``from_matplotlib`` rasterizes a figure to PNG
    (↔ Image.from_matplotlib, eval_flow.py:124,134)."""

    def __init__(self, png_bytes: bytes):
        self.png_bytes = png_bytes

    @classmethod
    def from_matplotlib(cls, fig) -> "Image":
        buf = io.BytesIO()
        fig.savefig(buf, format="png", bbox_inches="tight")
        return cls(buf.getvalue())

    def _render(self) -> str:
        b64 = base64.b64encode(self.png_bytes).decode()
        return f'<img src="data:image/png;base64,{b64}"/>'


class Table:
    """Table of rows; cells may be components or plain values
    (↔ Table, eval_flow.py:109,134-139)."""

    def __init__(self, rows: Sequence[Sequence[Any]], headers: Sequence[str] = ()):
        self.rows = rows
        self.headers = headers

    def _render(self) -> str:
        parts = ["<table border='1' cellpadding='4' style='border-collapse:collapse'>"]
        if self.headers:
            parts.append(
                "<tr>"
                + "".join(f"<th>{html.escape(str(h))}</th>" for h in self.headers)
                + "</tr>"
            )
        for row in self.rows:
            cells = []
            for cell in row:
                if hasattr(cell, "_render"):
                    cells.append(f"<td>{cell._render()}</td>")
                else:
                    cells.append(f"<td>{html.escape(str(cell))}</td>")
            parts.append("<tr>" + "".join(cells) + "</tr>")
        parts.append("</table>")
        return "\n".join(parts)


def metrics_table(records: Sequence[dict]) -> Table:
    """A Table of per-step/per-epoch metric dicts with consistent float
    formatting (4 decimals; 1 decimal for magnitudes ≥ 100, e.g. token
    rates). One renderer shared by every card that shows a metrics history,
    so the same record never formats differently across cards."""

    def fmt(v):
        if isinstance(v, float):
            return f"{v:.1f}" if abs(v) >= 100 else f"{v:.4f}"
        return v

    headers = list(records[0].keys()) if records else []
    return Table(
        [[fmt(r.get(h)) for h in headers] for r in records], headers=headers
    )


class CardBuffer:
    """``current.card`` — append components during the step
    (↔ current.card.append, eval_flow.py:98-100,109)."""

    def __init__(self):
        self.components: list[Any] = []

    def append(self, component: Any) -> None:
        self.components.append(component)

    def render_html(self, title: str = "tpuflow card") -> str:
        body = "\n".join(
            c._render() if hasattr(c, "_render") else f"<p>{html.escape(str(c))}</p>"
            for c in self.components
        )
        return (
            "<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>{html.escape(title)}</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "table{font-size:13px}</style></head>"
            f"<body>{body}</body></html>"
        )


class _GanttBar:
    """One timeline bar: offset + width as percentages of the run span.
    Rendered as nested divs so the bar scales with the column width."""

    def __init__(self, left_pct: float, width_pct: float, color: str):
        self.left_pct = left_pct
        self.width_pct = width_pct
        self.color = color

    def _render(self) -> str:
        return (
            "<div style='position:relative;width:240px;height:12px;"
            "background:#f1f0ec'>"
            f"<div style='position:absolute;left:{self.left_pct:.2f}%;"
            f"width:{max(self.width_pct, 0.5):.2f}%;height:12px;"
            f"background:{self.color}'></div></div>"
        )


# Goodput-bucket → color for the stacked goodput bar: productive green,
# lost time in the warning/subsystem hues, residual near-background.
_GOODPUT_COLORS = (
    ("step", "#2e9960"),
    ("replay", "#c2b33a"),
    ("compile", "#2a78d6"),
    ("restore", "#9268d4"),
    ("data_wait", "#eb6834"),
    ("ckpt", "#8a8782"),
    ("resize", "#d08a3a"),
    ("requeue_gap", "#d05252"),
    ("other", "#e5e4e0"),
)


class _GoodputBar:
    """One 100%-stacked horizontal bar over the goodput buckets — the
    run's wall-clock decomposition at a glance (hover a segment for the
    bucket name + seconds)."""

    def __init__(self, goodput: dict):
        self.goodput = goodput

    def _render(self) -> str:
        wall = max(float(self.goodput.get("wall_s", 0.0)), 1e-9)
        buckets = self.goodput.get("buckets", {})
        cells = []
        for bucket, color in _GOODPUT_COLORS:
            v = float(buckets.get(bucket, 0.0))
            if v <= 0:
                continue
            cells.append(
                f"<div title='{bucket}: {v:.3f}s' "
                f"style='width:{100.0 * v / wall:.2f}%;"
                f"background:{color};height:16px'></div>"
            )
        return (
            "<div style='display:flex;width:480px;height:16px;"
            "background:#f1f0ec'>" + "".join(cells) + "</div>"
        )


# Span-name → bar color (categorical slots of the validated palette; one
# hue per subsystem so the Gantt reads by layer).
_TIMELINE_COLORS = {
    "flow.": "#8a8782",
    "train.": "#2a78d6",
    "ckpt.": "#eb6834",
    "data.": "#2e9960",
    "infer.": "#9268d4",
    "serve.": "#d08a3a",
    "fleet.": "#3a9ec2",
    "device.": "#c2b33a",
}


def _span_color(name: str) -> str:
    for prefix, color in _TIMELINE_COLORS.items():
        if name.startswith(prefix):
            return color
    return "#8a8782"


def timeline_card(buf, events: Sequence[dict], summary: dict | None = None) -> None:
    """Run-level observability card (the tentpole's L1 surface): headline
    metrics + a per-span Gantt-style table over the merged event stream +
    subsystem aggregates. Rendered by FlowRunner into ``timeline.html`` at
    the run root when the run finishes (success or failure). Appends into
    ``buf``; cards must never fail the run, so callers wrap in try/except.
    """
    from tpuflow import obs

    if not events:
        return
    if summary is None:
        summary = obs.summarize(events)
    buf.append(Markdown("# Run timeline"))

    headline = summary.get("headline", {})
    if headline:
        def fmt(k, v):
            if "bytes" in k:
                return f"{v / 1e6:.1f} MB"
            if "gbps" in k:
                return f"{v:.2f} GB/s"
            if k.endswith("_s"):
                return f"{v:.4f} s"
            if "rate" in k or "mfu" in k:
                return f"{v:.3f}"
            return f"{v:,.1f}" if isinstance(v, float) else str(v)

        buf.append(Markdown("## Headline"))
        buf.append(
            Table(
                [[k, fmt(k, v)] for k, v in sorted(headline.items())],
                headers=["metric", "value"],
            )
        )

    # Training health (ISSUE 3): anomalies, rollbacks, and profiler
    # windows get their own section — the first thing a babysitter scans.
    health = summary.get("health") or {}
    if (
        health.get("anomalies")
        or health.get("rollbacks")
        or health.get("profiles")
    ):
        buf.append(Markdown("## Training health"))
        rows = []
        for a in health.get("anomalies", []):
            detail = ", ".join(
                f"{k}={v}"
                for k, v in sorted(a.items())
                if k not in ("ts", "proc", "detector", "step")
            )
            rows.append(
                ["anomaly", a.get("detector", "?"), a.get("step", ""), detail]
            )
        for r in health.get("rollbacks", []):
            rows.append(
                [
                    "rollback",
                    r.get("detector", "?"),
                    r.get("step", ""),
                    f"from step {r.get('from_step', '?')}, "
                    f"lr_scale {r.get('lr_scale', 1.0)}",
                ]
            )
        for p in health.get("profiles", []):
            rows.append(
                [
                    "profile",
                    "trace",
                    f"{p.get('start_step', '?')}–{p.get('stop_step', '?')}",
                    p.get("dir", ""),
                ]
            )
        buf.append(
            Table(rows, headers=["event", "kind", "step", "detail"])
        )
        last = health.get("last") or {}
        if last:
            buf.append(
                Table(
                    [
                        [k, f"{v:.6g}"]
                        for k, v in sorted(last.items())
                        if k != "step"
                    ],
                    headers=["last gauge", "value"],
                )
            )

    # Goodput ledger (ISSUE 6): the wall-clock decomposition + one lane
    # per launch attempt, so a requeued run's card shows what each
    # attempt cost and where the gaps were.
    goodput = summary.get("goodput") or {}
    if goodput.get("wall_s") and goodput.get("steps_timed"):
        wall = goodput["wall_s"]
        buf.append(Markdown("## Goodput"))
        buf.append(
            Markdown(
                f"**{100.0 * goodput.get('fraction', 0.0):.1f}%** of "
                f"{wall:.1f} s wall went to productive train steps."
            )
        )
        buf.append(_GoodputBar(goodput))
        buf.append(
            Table(
                [
                    [
                        bucket,
                        f"{goodput['buckets'].get(bucket, 0.0):.3f}s",
                        f"{100.0 * goodput['buckets'].get(bucket, 0.0) / wall:.1f}%",
                    ]
                    for bucket, _c in _GOODPUT_COLORS
                    if goodput["buckets"].get(bucket)
                ],
                headers=["bucket", "seconds", "share"],
            )
        )
        attempts = goodput.get("attempts") or []
        if len(attempts) > 1:
            buf.append(Markdown("## Attempt lanes"))
            buf.append(
                Table(
                    [
                        [
                            f"attempt {a['attempt']}",
                            " ".join(f"p{p}" for p in a.get("procs", [])),
                            f"+{a['start_s']:.3f}s",
                            f"{a['dur_s']:.3f}s",
                            _GanttBar(
                                100.0 * a["start_s"] / wall,
                                100.0 * a["dur_s"] / wall,
                                "#2a78d6",
                            ),
                        ]
                        for a in attempts
                    ],
                    headers=["attempt", "procs", "start", "dur", ""],
                )
            )

    # Serving observatory (ISSUE 13): a run that fed a ServeEngine gets
    # its own section — load, latency, the engine-time ledger's last
    # fractions, and SLO accounting — mirroring what /metrics and
    # `python -m tpuflow.obs serve-summary` report.
    counters = summary.get("counters", {})
    gauges = summary.get("gauges", {})
    if counters.get("serve.requests") or "serve.queue_depth" in gauges:
        buf.append(Markdown("## Serving"))
        rows = []
        if counters.get("serve.requests"):
            rows.append(
                ["requests completed", f"{counters['serve.requests']:,.0f}"]
            )
        if counters.get("serve.tokens"):
            rows.append(
                ["tokens served", f"{counters['serve.tokens']:,.0f}"]
            )
        if counters.get("serve.slo_violations"):
            rows.append(
                [
                    "SLO violations",
                    f"{counters['serve.slo_violations']:,.0f}",
                ]
            )
        for name, label, spec in (
            ("serve.queue_depth", "queue depth (last/max)", "{:.0f}"),
            ("serve.slot_occupancy", "slot occupancy (last/max)", "{:.2f}"),
            ("serve.ttft_s", "TTFT s (last/max)", "{:.4f}"),
            ("serve.idle_fraction", "ledger: idle fraction", "{:.3f}"),
            ("serve.decode_fraction", "ledger: decode fraction", "{:.3f}"),
            ("serve.prefill_fraction", "ledger: prefill fraction",
             "{:.3f}"),
            ("serve.decode_utilization", "decode utilization", "{:.3f}"),
            ("serve.masked_row_waste", "masked-row waste", "{:.3f}"),
            ("serve.spec_accept_rate", "spec accept rate", "{:.3f}"),
            ("serve.pages_free", "pages free (last)", "{:.0f}"),
        ):
            g = gauges.get(name)
            if not g:
                continue
            val = spec.format(g.get("last", 0.0))
            if "last/max" in label:
                val += f" / {spec.format(g.get('max', 0.0))}"
            rows.append([label, val])
        if rows:
            buf.append(Table(rows, headers=["serving metric", "value"]))

    # Fleet observatory (ISSUE 14): a run that polled a serving fleet
    # (tpuflow.obs.fleet) gets a Fleet section — replica count/health,
    # aggregate QPS, and the staleness evidence trail — mirroring the
    # `fleet-summary` headline.
    stale_events = [
        e
        for e in events
        if e.get("kind") == "event" and e.get("name") == "fleet.replica_stale"
    ]
    if "fleet.size" in gauges or stale_events:
        buf.append(Markdown("## Fleet"))
        rows = []
        g = gauges.get("fleet.size")
        if g:
            rows.append(
                ["replicas tracked (last/max)",
                 f"{g.get('last', 0.0):.0f} / {g.get('max', 0.0):.0f}"]
            )
        g = gauges.get("fleet.qps")
        if g:
            rows.append(["fleet QPS (last)", f"{g.get('last', 0.0):.3g}"])
        if stale_events:
            rows.append(["replica-stale events", f"{len(stale_events):,d}"])
            culprits = sorted(
                {str(e.get("replica")) for e in stale_events if e.get("replica")}
            )
            if culprits:
                rows.append(["stale replicas", ", ".join(culprits[:8])])
        if rows:
            buf.append(Table(rows, headers=["fleet metric", "value"]))

    # Device observatory (ISSUE 15): a run whose device reported — HBM
    # gauges, the compiled-program ledger, a static budget verdict, or
    # an anomaly-triggered capture — gets a Device section mirroring
    # `python -m tpuflow.obs device-summary`.
    prog_events = [
        e for e in events
        if e.get("kind") == "event" and e.get("name") == "device.program"
    ]
    cap_events = [
        e for e in events
        if e.get("kind") == "event" and e.get("name") == "prof.capture"
    ]
    budget_events = [
        e for e in events
        if e.get("kind") == "event" and e.get("name") == "device.hbm_budget"
    ]
    if "device.hbm_used" in gauges or prog_events or cap_events:
        buf.append(Markdown("## Device"))
        rows = []
        for name, label in (
            ("device.hbm_used", "HBM used (last/max)"),
            ("device.hbm_peak", "HBM peak (max)"),
            ("device.hbm_limit", "HBM limit"),
        ):
            g = gauges.get(name)
            if not g:
                continue
            val = f"{g.get('last', 0.0) / 2**30:.3f} GiB"
            if "last/max" in label:
                val += f" / {g.get('max', 0.0) / 2**30:.3f} GiB"
            elif "(max)" in label:
                val = f"{g.get('max', 0.0) / 2**30:.3f} GiB"
            rows.append([label, val])
        used_g = gauges.get("device.hbm_peak") or gauges.get(
            "device.hbm_used"
        )
        limit_g = gauges.get("device.hbm_limit")
        if used_g and limit_g and limit_g.get("last"):
            rows.append([
                "HBM peak fraction",
                f"{used_g.get('max', 0.0) / limit_g['last']:.3f}",
            ])
        if prog_events:
            programs = sorted(
                {str(e.get("program")) for e in prog_events if e.get("program")}
            )
            rows.append(["compiled programs in ledger", f"{len(programs)}"])
            rows.append(["programs", ", ".join(programs[:12])])
        if budget_events:
            b = budget_events[-1]
            verdict = f"{float(b.get('resident_bytes', 0.0)) / 2**30:.3f} GiB resident"
            if b.get("resident_frac") is not None:
                verdict += (
                    f" = {100.0 * float(b['resident_frac']):.1f}% of limit"
                )
            if b.get("over"):
                verdict += " [OVER]"
            rows.append(["static HBM budget", verdict])
        if cap_events:
            rows.append(["triggered captures", f"{len(cap_events):,d}"])
            reasons = [str(e.get("reason")) for e in cap_events if e.get("reason")]
            if reasons:
                rows.append(["capture reasons", ", ".join(reasons[:8])])
        if rows:
            buf.append(Table(rows, headers=["device metric", "value"]))

    # Alert engine (ISSUE 16): a run during which any declarative rule
    # fired gets an Alerts section — one row per fired event with its
    # severity, runbook anchor, and whether it resolved before run end.
    fired_events = [
        e for e in events
        if e.get("kind") == "event" and e.get("name") == "alert.fired"
    ]
    resolved_events = [
        e for e in events
        if e.get("kind") == "event" and e.get("name") == "alert.resolved"
    ]
    if fired_events or resolved_events:
        buf.append(Markdown("## Alerts"))
        resolved_count: dict[str, int] = {}
        for e in resolved_events:
            rule = str(e.get("rule"))
            resolved_count[rule] = resolved_count.get(rule, 0) + 1
        rows = []
        for e in fired_events:
            rule = str(e.get("rule"))
            if resolved_count.get(rule, 0) > 0:
                resolved_count[rule] -= 1
                state = "resolved"
            else:
                state = "STILL ACTIVE at run end"
            rows.append([
                rule,
                str(e.get("severity", "?")),
                str(e.get("message", ""))[:80],
                state,
                f"#{e.get('runbook', '')}",
            ])
        buf.append(Table(
            rows,
            headers=["alert", "severity", "message", "state", "runbook"],
        ))

    spans = [
        e for e in events if e.get("kind") == "span" and e.get("dur_s", 0) > 0
    ]
    if spans:
        t0 = min(e["ts"] for e in spans)
        t1 = max(e["ts"] + e["dur_s"] for e in spans)
        total = max(t1 - t0, 1e-9)
        buf.append(Markdown("## Timeline"))
        rows = []
        # The run span covers everything — show the inner structure only.
        for e in sorted(spans, key=lambda e: e["ts"]):
            if e["name"] == "flow.run":
                continue
            label = e["name"]
            if e.get("step"):
                label += f" [{e['step']}]"
            detail = []
            if e.get("bytes"):
                detail.append(f"{float(e['bytes']) / 1e6:.1f} MB")
            if e.get("gbps"):
                detail.append(f"{float(e['gbps']):.2f} GB/s")
            if e.get("tokens_per_s"):
                detail.append(f"{float(e['tokens_per_s']):.0f} tok/s")
            rows.append(
                [
                    label,
                    f"p{e.get('proc', 0)}",
                    f"+{e['ts'] - t0:.3f}s",
                    f"{e['dur_s']:.3f}s",
                    " ".join(detail),
                    _GanttBar(
                        100.0 * (e["ts"] - t0) / total,
                        100.0 * e["dur_s"] / total,
                        _span_color(e["name"]),
                    ),
                ]
            )
        buf.append(
            Table(
                rows,
                headers=["span", "proc", "start", "dur", "detail", ""],
            )
        )

    agg = summary.get("spans", {})
    if agg:
        buf.append(Markdown("## Span aggregates"))
        buf.append(
            Table(
                [
                    [n, s["count"], f"{s['total_s']:.3f}s",
                     f"{s['mean_s']:.4f}s", f"{s['max_s']:.4f}s"]
                    for n, s in sorted(agg.items())
                ],
                headers=["span", "count", "total", "mean", "max"],
            )
        )
    counters = summary.get("counters", {})
    hists = summary.get("histograms", {})
    if counters or hists:
        buf.append(Markdown("## Counters and histograms"))
        rows = [[n, "counter", f"{v:,.0f}", "", ""]
                for n, v in sorted(counters.items())]
        rows += [
            [n, "histogram", h["count"], f"{h['p50']:.5f}", f"{h['max']:.5f}"]
            for n, h in sorted(hists.items())
        ]
        buf.append(
            Table(rows, headers=["name", "kind", "count/total", "p50", "max"])
        )


def training_curve_card(buf, records: Sequence[dict]) -> None:
    """Training-curve card (D14): per-epoch loss chart + metrics table +
    final-perplexity headline — the train-side sibling of the eval flows'
    error-analysis card, shared so every training flow renders the same
    report. Chart style follows the dataviz method: one axis (both series
    are token-level loss in nats — perplexity stays in the table),
    categorical slots 1-2 of the validated reference palette, 2px lines,
    recessive grid, legend for two series. Appends into ``buf``
    (``current.card``); cards must never fail the run, so chart errors
    degrade to a note."""
    if not records:
        return
    buf.append(Markdown("# Training curves"))
    last = records[-1]
    if "ppl" in last:
        buf.append(
            Markdown(
                f"Final **val perplexity {last['ppl']:.2f}** "
                f"(val loss {last['val_loss']:.4f}) after "
                f"{len(records)} epoch(s)."
            )
        )
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots(figsize=(6, 3.2), facecolor="#fcfcfb")
        ax.set_facecolor("#fcfcfb")
        xs = [r["epoch"] for r in records]
        ax.plot(
            xs,
            [r["train_loss"] for r in records],
            color="#2a78d6",
            linewidth=2,
            marker="o",
            markersize=4,
            label="train loss",
        )
        if "val_loss" in last:
            ax.plot(
                xs,
                [r["val_loss"] for r in records],
                color="#eb6834",
                linewidth=2,
                marker="o",
                markersize=4,
                label="val loss",
            )
            ax.legend(frameon=False)
        from matplotlib.ticker import MaxNLocator

        ax.xaxis.set_major_locator(MaxNLocator(integer=True))
        ax.set_xlabel("epoch")
        ax.set_ylabel("loss (nats/token)")
        ax.grid(True, color="#e5e4e0", linewidth=0.5)
        for side in ("top", "right"):
            ax.spines[side].set_visible(False)
        fig.tight_layout()
        buf.append(Image.from_matplotlib(fig))
        plt.close(fig)
    except Exception as e:  # cards must never fail the run
        buf.append(Markdown(f"(chart unavailable: {e})"))
    buf.append(metrics_table(records))
