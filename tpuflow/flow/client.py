"""Client API: Run / Task handles + namespace.

Replaces the Metaflow client as the reference uses it for cross-run/cross-flow
checkpoint handoff (train_flow.py:69-73: ``Run(pathspec).data.result``;
eval_flow.py:45-49: ``Task(pathspec).data.result``; eval_flow.py:32-36
namespace switching)."""

from __future__ import annotations

import os
from typing import Any

from tpuflow.flow import store

_NAMESPACE: str | None = None


def namespace(ns: str | None) -> str | None:
    """↔ metaflow.namespace(...) (eval_flow.py:36): recorded for API parity;
    the local datastore is single-namespace, so this only tags reads."""
    global _NAMESPACE
    _NAMESPACE = ns
    return ns


class _DataNamespace:
    """Attribute access over a dict of artifacts (↔ run.data.result)."""

    def __init__(self, artifacts: dict[str, Any]):
        self._artifacts = artifacts

    def __getattr__(self, name: str) -> Any:
        try:
            return self._artifacts[name]
        except KeyError:
            raise AttributeError(f"no artifact {name!r}") from None

    def __contains__(self, name: str) -> bool:
        return name in self._artifacts


class Task:
    """Handle to one task: ``Task("Flow/run_id/step/task_id")``
    (↔ eval_flow.py:45)."""

    def __init__(self, pathspec: str):
        parts = pathspec.strip("/").split("/")
        if len(parts) != 4:
            raise ValueError(
                f"task pathspec must be Flow/run_id/step/task_id, got {pathspec!r}"
            )
        self.flow, self.run_id, self.step, self.task_id = (
            parts[0],
            parts[1],
            parts[2],
            int(parts[3]),
        )
        self.pathspec = pathspec
        if not os.path.isdir(
            store.task_dir(self.flow, self.run_id, self.step, self.task_id)
        ):
            raise KeyError(f"no such task: {pathspec}")

    @property
    def data(self) -> _DataNamespace:
        return _DataNamespace(
            store.load_artifacts(self.flow, self.run_id, self.step, self.task_id)
        )


class Run:
    """Handle to one run: ``Run("Flow/run_id")`` (↔ train_flow.py:73,
    eval_flow.py:48). ``run.data`` merges artifacts along executed-step order,
    later steps winning — matching the reference's read of end-of-run state."""

    def __init__(self, pathspec: str):
        parts = pathspec.strip("/").split("/")
        if len(parts) != 2:
            raise ValueError(f"run pathspec must be Flow/run_id, got {pathspec!r}")
        self.flow, self.run_id = parts
        self.pathspec = pathspec
        if not os.path.isdir(store.run_dir(self.flow, self.run_id)):
            raise KeyError(f"no such run: {pathspec}")

    @property
    def meta(self) -> dict:
        return store.read_run_meta(self.flow, self.run_id)

    @property
    def successful(self) -> bool:
        return self.meta.get("status") == "success"

    @property
    def data(self) -> _DataNamespace:
        merged: dict[str, Any] = {}
        for rec in self.meta.get("steps", []):
            merged.update(
                store.load_artifacts(
                    self.flow, self.run_id, rec["step"], rec["head_task"]
                )
            )
        return _DataNamespace(merged)

    def __getitem__(self, step: str) -> Task:
        for rec in self.meta.get("steps", []):
            if rec["step"] == step:
                return Task(
                    f"{self.flow}/{self.run_id}/{step}/{rec['head_task']}"
                )
        raise KeyError(f"step {step!r} not found in {self.pathspec}")
