"""Client API: Run / Task handles + namespace.

Replaces the Metaflow client as the reference uses it for cross-run/cross-flow
checkpoint handoff (train_flow.py:69-73: ``Run(pathspec).data.result``;
eval_flow.py:45-49: ``Task(pathspec).data.result``; eval_flow.py:32-36
namespace switching)."""

from __future__ import annotations

import os
from typing import Any

from tpuflow.flow import store
from tpuflow.utils import knobs

# Sentinel distinguishing "never set" (default user namespace) from an
# explicit namespace(None) (global — resolve everything), matching the
# reference client's semantics (eval_flow.py:32-36: a namespace parameter
# scopes which runs the client resolves; empty string = global).
_UNSET = object()
_NAMESPACE: Any = _UNSET


def default_namespace() -> str:
    """The namespace runs are produced under when none is set explicitly:
    ``TPUFLOW_NAMESPACE`` env, else ``user:<login>`` (the Metaflow
    convention)."""
    ns = knobs.raw("TPUFLOW_NAMESPACE")
    if ns:
        return ns
    import getpass

    try:
        user = getpass.getuser()
    except (KeyError, OSError):
        user = f"uid{os.getuid()}"
    return f"user:{user}"


def get_namespace() -> str | None:
    """The active namespace: explicit ``namespace(...)`` value if one was
    set this process (None = global), else the default user namespace."""
    if _NAMESPACE is _UNSET:
        return default_namespace()
    return _NAMESPACE


def namespace(ns: str | None) -> str | None:
    """↔ metaflow.namespace(...) (eval_flow.py:36). Scopes which runs the
    client resolves: ``Run``/``Task``/``Flow`` raise on objects produced
    under a different namespace. ``namespace(None)`` switches to the
    global namespace (everything resolves)."""
    global _NAMESPACE
    _NAMESPACE = ns
    return ns


def _check_visible(kind: str, pathspec: str, produced_ns: str | None) -> None:
    """Raise when an object lies outside the active namespace. Runs from
    before namespace recording (no ``namespace`` key in run.json) stay
    visible everywhere."""
    active = get_namespace()
    if active is None or produced_ns is None:
        return
    if produced_ns != active:
        raise KeyError(
            f"{kind} {pathspec} belongs to namespace {produced_ns!r}, not "
            f"the active {active!r}; call namespace({produced_ns!r}) to "
            "read it, or namespace(None) for the global namespace"
        )


class _DataNamespace:
    """Attribute access over a dict of artifacts (↔ run.data.result)."""

    def __init__(self, artifacts: dict[str, Any]):
        self._artifacts = artifacts

    def __getattr__(self, name: str) -> Any:
        try:
            return self._artifacts[name]
        except KeyError:
            raise AttributeError(f"no artifact {name!r}") from None

    def __contains__(self, name: str) -> bool:
        return name in self._artifacts


class Task:
    """Handle to one task: ``Task("Flow/run_id/step/task_id")``
    (↔ eval_flow.py:45)."""

    def __init__(self, pathspec: str):
        parts = pathspec.strip("/").split("/")
        if len(parts) != 4:
            raise ValueError(
                f"task pathspec must be Flow/run_id/step/task_id, got {pathspec!r}"
            )
        self.flow, self.run_id, self.step, self.task_id = (
            parts[0],
            parts[1],
            parts[2],
            int(parts[3]),
        )
        self.pathspec = pathspec
        if not os.path.isdir(
            store.task_dir(self.flow, self.run_id, self.step, self.task_id)
        ):
            raise KeyError(f"no such task: {pathspec}")
        try:
            meta = store.read_run_meta(self.flow, self.run_id)
        except (OSError, ValueError):  # missing or mid-write run.json
            meta = {}
        _check_visible("task", pathspec, meta.get("namespace"))

    @property
    def data(self) -> _DataNamespace:
        return _DataNamespace(
            store.load_artifacts(self.flow, self.run_id, self.step, self.task_id)
        )


class Run:
    """Handle to one run: ``Run("Flow/run_id")`` (↔ train_flow.py:73,
    eval_flow.py:48). ``run.data`` merges artifacts along executed-step order,
    later steps winning — matching the reference's read of end-of-run state."""

    def __init__(self, pathspec: str):
        parts = pathspec.strip("/").split("/")
        if len(parts) != 2:
            raise ValueError(f"run pathspec must be Flow/run_id, got {pathspec!r}")
        self.flow, self.run_id = parts
        self.pathspec = pathspec
        if not os.path.isdir(store.run_dir(self.flow, self.run_id)):
            raise KeyError(f"no such run: {pathspec}")
        try:
            # Cached for .meta/.successful: one read serves the namespace
            # check and the common read-a-finished-run pattern (the
            # latest-successful scan would otherwise parse run.json three
            # times per candidate). .meta refreshes while non-terminal.
            self._meta = store.read_run_meta(self.flow, self.run_id)
        except (OSError, ValueError):  # missing or mid-write run.json
            self._meta = {}
        _check_visible("run", pathspec, self._meta.get("namespace"))

    @property
    def meta(self) -> dict:
        # A finished run's metadata is immutable — serve the cached read.
        # While the run is still in flight, refresh so status/steps track
        # the live run.json (atomic replace on the writer side).
        if self._meta.get("status") not in ("success", "failed"):
            try:
                self._meta = store.read_run_meta(self.flow, self.run_id)
            except (OSError, ValueError):
                pass
        return self._meta

    @property
    def successful(self) -> bool:
        return self.meta.get("status") == "success"

    @property
    def data(self) -> _DataNamespace:
        merged: dict[str, Any] = {}
        for rec in self.meta.get("steps", []):
            merged.update(
                store.load_artifacts(
                    self.flow, self.run_id, rec["step"], rec["head_task"]
                )
            )
        return _DataNamespace(merged)

    def __getitem__(self, step: str) -> Task:
        for rec in self.meta.get("steps", []):
            if rec["step"] == step:
                return Task(
                    f"{self.flow}/{self.run_id}/{step}/{rec['head_task']}"
                )
        raise KeyError(f"step {step!r} not found in {self.pathspec}")

    # ----------------------------------------------------------- telemetry
    def events(self) -> list[dict]:
        """The run's merged telemetry stream (tpuflow.obs events): the
        committed ``events.jsonl`` when the runner finished the merge, else
        merged on the fly from the gang-worker fragments (a still-running
        or crashed run stays readable). Empty list when the run recorded
        no telemetry (TPUFLOW_OBS=0)."""
        from tpuflow import obs

        return obs.load_run_events(store.run_dir(self.flow, self.run_id))

    def telemetry(self) -> dict:
        """Aggregated telemetry (``obs.summarize`` of ``events()``): span
        aggregates, counters, histograms, and the headline metrics the
        timeline card shows — how downstream flows (eval) read the
        training run's step-time/tokens-per-s/checkpoint-GB/s evidence."""
        from tpuflow import obs

        return obs.summarize(self.events())

    def health(self) -> dict:
        """The run's training-health view (``obs.health_summary`` of the
        merged stream): anomaly/rollback/profile-capture events, the last
        ``health.*`` numerics gauges, nonfinite-step and dropped-event
        totals — how a babysitting tool answers "did this run diverge,
        and what did the loop do about it" without scraping logs."""
        from tpuflow import obs

        return obs.health_summary(self.events())

    def goodput(self) -> dict:
        """The run's goodput ledger (``obs.compute_goodput`` of the
        merged stream): wall time decomposed into productive step seconds
        vs compile / restore / data-wait / checkpoint / rollback-replay /
        requeue-gap buckets, stitched across gang members and launch
        attempts — the "what fraction of wall-clock actually trained,
        and where did the rest go" answer the observatory exists for."""
        from tpuflow import obs

        return obs.compute_goodput(self.events())


class Flow:
    """Handle to a flow's run history: ``Flow("TpuGptTrain")`` — the
    namespace-scoped resolution surface (↔ metaflow.Flow: the reference's
    client resolves latest/successful runs within the active namespace,
    eval_flow.py:32-36)."""

    def __init__(self, name: str):
        self.name = name
        if not os.path.isdir(store.flow_dir(name)):
            raise KeyError(f"no such flow: {name}")

    def runs(self) -> list[Run]:
        """All resolvable runs in the ACTIVE namespace, newest first.
        Out-of-namespace runs are skipped (not raised): enumeration is a
        filter, only direct pathspec access is an error."""
        out = []
        for entry in sorted(
            (e for e in os.listdir(store.flow_dir(self.name)) if e.isdigit()),
            key=int,
            reverse=True,
        ):
            try:
                out.append(Run(f"{self.name}/{entry}"))
            except KeyError:
                continue  # other namespace, or not a run dir
        return out

    @property
    def latest_run(self) -> Run:
        for run in self.runs():
            return run
        raise KeyError(
            f"flow {self.name} has no runs in namespace "
            f"{get_namespace()!r}"
        )

    @property
    def latest_successful_run(self) -> Run:
        for run in self.runs():
            if run.successful:
                return run
        raise KeyError(
            f"flow {self.name} has no successful runs in namespace "
            f"{get_namespace()!r}"
        )
