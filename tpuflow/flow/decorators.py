"""Step / flow decorators: retry, gang, card, resources, schedule, triggers.

Replaces the reference's decorator stack (train_flow.py:20,41-52,
eval_flow.py:15-19,56-68): ``@retry`` (fault tolerance), ``@tpu`` (the
@metaflow_ray-equivalent gang step: N processes form one jax.distributed gang
with a formation timeout, and only the head persists artifacts),
``@kubernetes``/``@pypi``-style resource/env records, ``@card``,
``@device_profile`` (the @gpu_profile equivalent), ``@schedule`` (cron
record), and ``@trigger_on_finish`` (event handoff)."""

from __future__ import annotations

from typing import Callable


def retry(
    times: int = 3, backoff_s: float = 2.0, max_backoff_s: float = 60.0
):
    """Step-level retry (↔ @retry(times=3), train_flow.py:41): a failed step
    reruns up to ``times`` extra attempts; combined with in-run checkpoint
    resume this bounds lost work to one epoch (SURVEY.md §5).

    Between attempts the runner sleeps an exponentially growing, jittered
    delay: attempt ``n`` waits ``min(max_backoff_s, backoff_s * 2**(n-1))``
    scaled by a uniform 0.5–1.0 jitter, so a gang of retrying flows does
    not stampede shared storage or the rendezvous coordinator. Preemption
    requeues (a member exiting with the requeue code) rerun the step
    WITHOUT consuming ``times`` — see tpuflow.utils.preempt."""

    def wrap(fn: Callable) -> Callable:
        fn.__retry_times__ = times
        fn.__retry_backoff_s__ = backoff_s
        fn.__retry_max_backoff_s__ = max_backoff_s
        return fn

    return wrap


def tpu(
    num_parallel: int | None = None,
    all_hosts_started_timeout: float = 300.0,
    heartbeat_timeout: float | None = None,
    min_members: int | None = None,
):
    """Gang step (↔ @metaflow_ray(all_nodes_started_timeout=60*5),
    train_flow.py:42): the step body runs as a gang of processes forming one
    ``jax.distributed`` world — process 0 is the head, and only the head's
    artifacts persist (the join step tolerates headless inputs exactly like
    train_flow.py:85-88). Locally the gang is simulated with N host processes
    on CPU devices; on a real pod slice each host runs the same step and the
    rendezvous happens over DCN.

    ``heartbeat_timeout``: a member whose heartbeat file (stamped at
    rendezvous and every fenced train step/report, tpuflow.utils.heartbeat)
    goes silent for this many seconds is treated as hung and the gang is
    killed promptly — well inside the flat rendezvous deadline. ``None``
    falls back to ``TPUFLOW_STALL_TIMEOUT_S`` (default 600). Members that
    never stamp are never judged.

    ``min_members``: the elastic-gang floor (ISSUE 7, TPUFLOW_ELASTIC=1):
    a member loss shrinks the mesh over the survivors as long as at least
    this many remain; below the floor the supervisor falls back to the
    classic requeue-the-world path. ``None`` falls back to
    ``TPUFLOW_GANG_MIN_MEMBERS`` (default 2). Also annotated onto the
    deployer's JobSet manifests (min/max member annotations)."""

    def wrap(fn: Callable) -> Callable:
        fn.__gang__ = {
            "num_parallel": num_parallel,
            "timeout": all_hosts_started_timeout,
            "heartbeat_timeout": heartbeat_timeout,
            "min_members": min_members,
        }
        return fn

    return wrap


def kubernetes(**resources):
    """Resource request record (↔ @kubernetes(gpu=N, compute_pool=...),
    train_flow.py:43-52). Locally informational; a deployer maps it to pod
    slice topology (e.g. topology='v5e-16')."""

    def wrap(fn: Callable) -> Callable:
        fn.__resources__ = resources
        return fn

    return wrap


def pypi(**env):
    """Per-step environment pin record (↔ @pypi(packages={...}),
    train_flow.py:43-50). This build vendors everything, so it is a record."""

    def wrap(fn: Callable) -> Callable:
        fn.__pypi__ = env
        return fn

    return wrap


def card(type: str = "blank"):
    """Attach a report card to the step (↔ @card(type="blank"),
    eval_flow.py:56): the step gets ``current.card`` to append
    Markdown/Table/Image components; rendered to card.html on completion."""

    def wrap(fn: Callable) -> Callable:
        fn.__card__ = type
        return fn

    return wrap


def device_profile(interval: float = 1.0, trace: bool = False):
    """Device metrics sampling during the step (↔ @gpu_profile(interval=1),
    train_flow.py:51): samples per-device memory stats every ``interval``
    seconds on a background thread into profile.json in the task dir.
    ``trace=True`` additionally captures a ``jax.profiler`` trace of the
    whole step (viewable in XProf/TensorBoard) under ``trace/``."""

    def wrap(fn: Callable) -> Callable:
        fn.__device_profile__ = {"interval": interval, "trace": trace}
        return fn

    return wrap


def schedule(cron: str):
    """Flow-level cron record (↔ @schedule(cron="*/5 * * * *"),
    train_flow.py:20). ``deploy`` writes it to the deployment record; an
    external scheduler (or the ``trigger`` CLI) fires runs — the handoff
    semantics are in scope, the cron daemon is infra (SURVEY.md §2b D10)."""

    def wrap(cls):
        cls.__schedule__ = cron
        return cls

    return wrap


def trigger_on_finish(flow: str):
    """Event trigger (↔ @trigger_on_finish(flow="RayTorchTrain"),
    eval_flow.py:19): when the named flow finishes successfully it appends an
    event record; running this flow with ``--triggered`` consumes the newest
    unconsumed event and exposes ``current.trigger.run``."""

    def wrap(cls):
        cls.__trigger_on_finish__ = flow
        return cls

    return wrap
