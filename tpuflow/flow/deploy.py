"""Deployer: materialize Kubernetes manifests from a flow's decorators.

Closes the loop the reference leaves to the Outerbounds platform: there,
``@kubernetes(gpu=1, compute_pool=...)`` + ``@pypi(packages={...})``
(train_flow.py:43-52) and ``argo-workflows create`` (README.md:27-45) turn
the flow into scheduled pods. Here ``python flows/train_flow.py deploy``
consumes the same decorator records and writes runnable artifacts:

- a **JobSet** per gang (``@tpu``) step — one Job of ``hosts`` completions
  with TPU resource requests (``google.com/tpu``), GKE TPU node selectors
  derived from ``@kubernetes(topology=...)``, and the gang rendezvous env
  (``TPUFLOW_NUM_PROCESSES`` / ``TPUFLOW_COORDINATOR``) wired to the
  JobSet's stable pod DNS — the k8s shape of the local subprocess gang
  (runner._exec_gang);
- a plain **Job** per non-gang step with resources;
- a **CronJob** when the flow carries ``@schedule(cron=...)``
  (↔ train_flow.py:20);
- a **requirements-<step>.txt** lock per ``@pypi(packages={...})`` record,
  referenced from the container spec as an env var so the image build/init
  layer can install the exact pins.

Manifests are plain dicts serialized to YAML; ``kubectl apply -f`` shapes,
no cluster access attempted (this environment has none — the generator is
the deployable artifact, validated structurally by tests/test_deploy.py).
"""

from __future__ import annotations

import os
from typing import Any

from tpuflow.utils.preempt import REQUEUE_EXIT_CODE
from tpuflow.utils import knobs


def _requeue_pod_failure_policy() -> dict:
    """Preemption parity with the local supervisor: a member that drained
    and exited with the requeue code must rerun WITHOUT consuming the
    Job's ``backoffLimit`` (= the @retry budget), exactly like
    runner.StepPreempted locally. ``Ignore`` makes Kubernetes recreate the
    pod without counting the failure."""
    return {
        "rules": [
            {
                "action": "Ignore",
                "onExitCodes": {
                    "operator": "In",
                    "values": [REQUEUE_EXIT_CODE],
                },
            }
        ]
    }

def _trace_env() -> list[dict]:
    """End-to-end tracing (ISSUE 18): the trace knobs set at render
    time ride the pod env so the front door (which mints the context)
    and every replica share one sampling policy and — on shared
    storage — one span directory. Literal accessor names on purpose:
    tpulint's declared-name pass checks them statically."""
    pairs = (
        ("TPUFLOW_TRACE", knobs.raw("TPUFLOW_TRACE")),
        ("TPUFLOW_TRACE_SAMPLE", knobs.raw("TPUFLOW_TRACE_SAMPLE")),
        ("TPUFLOW_TRACE_DIR", knobs.raw("TPUFLOW_TRACE_DIR")),
    )
    return [
        {"name": tk, "value": str(tv)}
        for tk, tv in pairs
        if tv is not None
    ]


# chips per host and default 2-D ICI topology per v5e/v6e slice size; v4/v5p
# use 4-chip hosts with 3-D topologies (coarse entries for the common ones).
_TPU_SLICES: dict[str, dict[int, str]] = {
    "v5e": {1: "1x1", 4: "2x2", 8: "2x4", 16: "4x4", 32: "4x8", 64: "8x8",
            128: "8x16", 256: "16x16"},
    "v6e": {1: "1x1", 4: "2x2", 8: "2x4", 16: "4x4", 32: "4x8", 64: "8x8",
            128: "8x16", 256: "16x16"},
    "v5p": {8: "2x2x1", 16: "2x2x2", 32: "2x4x2", 64: "4x4x2"},
    "v4": {8: "2x2x1", 16: "2x2x2", 32: "2x4x2", 64: "4x4x2"},
}
_ACCELERATOR = {
    "v5e": "tpu-v5-lite-podslice",
    "v6e": "tpu-v6e-slice",
    "v5p": "tpu-v5p-slice",
    "v4": "tpu-v4-podslice",
}
_CHIPS_PER_HOST = {"v5e": 4, "v6e": 4, "v5p": 4, "v4": 4}


def parse_topology(topology: str) -> dict[str, Any]:
    """'v5e-16' → {generation, chips, hosts, chips_per_host, grid,
    accelerator}. Unknown sizes still deploy (grid omitted)."""
    gen, _, chips_s = topology.partition("-")
    chips = int(chips_s) if chips_s.isdigit() else 1
    if gen not in _ACCELERATOR:
        raise ValueError(
            f"unknown TPU generation {gen!r} in topology {topology!r}; "
            f"known: {sorted(_ACCELERATOR)}"
        )
    per_host = min(_CHIPS_PER_HOST[gen], chips)
    return {
        "generation": gen,
        "chips": chips,
        "hosts": max(chips // _CHIPS_PER_HOST[gen], 1),
        "chips_per_host": per_host,
        "grid": _TPU_SLICES[gen].get(chips),
        "accelerator": _ACCELERATOR[gen],
    }


def _flow_script(flow_cls) -> str:
    """Container-workdir-relative path of the file defining the flow."""
    import inspect

    mod = inspect.getmodule(flow_cls)
    path = getattr(mod, "__file__", None)
    if not path:
        return f"flows/{flow_cls.__name__.lower()}.py"
    path = os.path.abspath(path)
    rel = os.path.relpath(path, os.getcwd())
    # Inside the repo → use the repo-relative path (the image's workdir is
    # the repo root); outside (e.g. a test tmpdir) → just the file name.
    return rel if not rel.startswith("..") else os.path.basename(path)


def _container(
    flow_name: str, flow_cls_name: str, step_name: str, step_fn, image: str,
    script: str,
) -> dict:
    """Pod container running ONE step of the flow against shared storage.

    The entrypoint is the gang-member bootstrap (tpuflow.flow.gang_exec)
    with ``--from-store`` artifact sourcing: it joins the jax.distributed
    world from the TPUFLOW_* env this manifest wires up, loads upstream
    artifacts from the run's datastore (shared across the Jobs of a run),
    executes the step body, and persists its artifacts. $(VAR) in args is
    expanded by Kubernetes from the container env.
    """
    pypi = getattr(step_fn, "__pypi__", None) or {}
    env = [
        {"name": "TPUFLOW_FLOW", "value": flow_name},
        {"name": "TPUFLOW_STEP", "value": step_name},
        {"name": "TPUFLOW_RUN_ID", "value": f"k8s-{flow_name.lower()}"},
    ]
    if pypi.get("packages"):
        env.append(
            {
                "name": "TPUFLOW_REQUIREMENTS",
                "value": f"/deploy/requirements-{step_name}.txt",
            }
        )
    return {
        "name": f"{flow_name.lower()}-{step_name}".replace("_", "-"),
        "image": image,
        "command": [
            "python",
            "-m",
            "tpuflow.flow.gang_exec",
            script,
            flow_cls_name,
            step_name,
            "$(TPUFLOW_RUN_ID)",
            "$(TPUFLOW_PROCESS_ID)",
            "--from-store",
        ],
        "env": env,
    }


def _gang_jobset(
    flow_name: str, step_name: str, step_fn, *, image: str, script: str
) -> dict:
    """JobSet for a gang step: `hosts` pods forming one jax.distributed
    world, the k8s analogue of runner._exec_gang's local subprocess gang."""
    res = getattr(step_fn, "__resources__", None) or {}
    topo = parse_topology(res.get("topology", "v5e-8"))
    gang = getattr(step_fn, "__gang__", {}) or {}
    name = f"{flow_name.lower()}-{step_name}".replace("_", "-")
    container = _container(flow_name, flow_name, step_name, step_fn, image, script)
    container["resources"] = {
        "limits": {"google.com/tpu": topo["chips_per_host"]}
    }
    # Rendezvous: process 0's pod DNS name is stable under JobSet
    # (<jobset>-<job>-0-0.<jobset>), the DCN equivalent of the local
    # 127.0.0.1:port coordinator.
    container["env"] += [
        {"name": "TPUFLOW_NUM_PROCESSES", "value": str(topo["hosts"])},
        {
            "name": "TPUFLOW_PROCESS_ID",
            "valueFrom": {
                "fieldRef": {
                    "fieldPath": (
                        "metadata.annotations"
                        "['batch.kubernetes.io/job-completion-index']"
                    )
                }
            },
        },
        {
            "name": "TPUFLOW_COORDINATOR",
            "value": f"{name}-workers-0-0.{name}:8476",
        },
        {
            "name": "TPUFLOW_GANG_TIMEOUT",
            "value": str(gang.get("timeout", 300.0)),
        },
    ]
    node_selector = {
        "cloud.google.com/gke-tpu-accelerator": topo["accelerator"],
    }
    if topo["grid"]:
        node_selector["cloud.google.com/gke-tpu-topology"] = topo["grid"]
    if res.get("compute_pool"):
        node_selector["cloud.google.com/gke-nodepool"] = res["compute_pool"]
    # Elastic gang envelope (ISSUE 7): the min/max member annotations tell
    # autoscalers/operators the resize window the supervisor honors — a
    # member loss shrinks the mesh down to min-members (below that it
    # falls back to requeue-the-world), and requeued capacity grows it
    # back up to the full host count.
    import os as _os

    min_members = gang.get("min_members") or int(
        knobs.raw("TPUFLOW_GANG_MIN_MEMBERS", "2")
    )
    annotations = {
        "tpuflow.dev/min-gang-members": str(min(min_members, topo["hosts"])),
        "tpuflow.dev/max-gang-members": str(topo["hosts"]),
        "tpuflow.dev/elastic": knobs.raw("TPUFLOW_ELASTIC", "0"),
    }
    return {
        "apiVersion": "jobset.x-k8s.io/v1alpha2",
        "kind": "JobSet",
        "metadata": {"name": name, "annotations": annotations},
        "spec": {
            "replicatedJobs": [
                {
                    "name": "workers",
                    "replicas": 1,
                    "template": {
                        "spec": {
                            "parallelism": topo["hosts"],
                            "completions": topo["hosts"],
                            "backoffLimit": int(
                                getattr(step_fn, "__retry_times__", 0)
                            ),
                            "podFailurePolicy": _requeue_pod_failure_policy(),
                            "completionMode": "Indexed",
                            "template": {
                                "spec": {
                                    "nodeSelector": node_selector,
                                    "restartPolicy": "Never",
                                    # Preemption grace mirrors the gang
                                    # rendezvous budget: SIGTERM → drain a
                                    # final checkpoint → requeue exit, with
                                    # at least as long as members wait for
                                    # each other before the SIGKILL.
                                    "terminationGracePeriodSeconds": int(
                                        gang.get("timeout", 300.0) or 300
                                    ),
                                    "containers": [container],
                                }
                            },
                        }
                    },
                }
            ]
        },
    }


def _plain_job(
    flow_name: str, step_name: str, step_fn, *, image: str, script: str
) -> dict:
    res = getattr(step_fn, "__resources__", None) or {}
    name = f"{flow_name.lower()}-{step_name}".replace("_", "-")
    container = _container(flow_name, flow_name, step_name, step_fn, image, script)
    container["env"] += [
        {"name": "TPUFLOW_NUM_PROCESSES", "value": "1"},
        {"name": "TPUFLOW_PROCESS_ID", "value": "0"},
    ]
    spec: dict[str, Any] = {"restartPolicy": "Never", "containers": [container]}
    if res.get("topology"):
        topo = parse_topology(res["topology"])
        container["resources"] = {
            "limits": {"google.com/tpu": topo["chips_per_host"]}
        }
        spec["nodeSelector"] = {
            "cloud.google.com/gke-tpu-accelerator": topo["accelerator"],
            **(
                {"cloud.google.com/gke-tpu-topology": topo["grid"]}
                if topo["grid"]
                else {}
            ),
        }
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": name},
        "spec": {
            "backoffLimit": int(getattr(step_fn, "__retry_times__", 0)),
            "podFailurePolicy": _requeue_pod_failure_policy(),
            "template": {"spec": spec},
        },
    }


def _cronjob(flow_name: str, cron: str, *, image: str, script: str) -> dict:
    name = f"{flow_name.lower()}-schedule".replace("_", "-")
    return {
        "apiVersion": "batch/v1",
        "kind": "CronJob",
        "metadata": {"name": name},
        "spec": {
            "schedule": cron,
            "concurrencyPolicy": "Forbid",
            "jobTemplate": {
                "spec": {
                    "template": {
                        "spec": {
                            "restartPolicy": "Never",
                            "containers": [
                                {
                                    "name": name,
                                    "image": image,
                                    "command": ["python", script, "run"],
                                }
                            ],
                        }
                    }
                }
            },
        },
    }


def serving_deployment(
    name: str,
    *,
    topology: str = "v5e-8",
    image: str = "tpuflow:latest",
    replicas: int = 1,
    metrics_port: int = 8080,
    command: list[str] | None = None,
    compute_pool: str | None = None,
    max_slots: int | None = None,
    prefill_chunk: int | None = None,
    buckets: list[int] | None = None,
    slo_ttft_ms: float | None = None,
    slo_itl_ms: float | None = None,
    drain_grace_s: int = 120,
    env: dict[str, str] | None = None,
) -> dict:
    """apps/v1 Deployment for a LONG-LIVED serving gang (ISSUE 8): each
    replica is one single-host TPU pod running a continuous-batching
    ``ServeEngine`` loop (``tpuflow.infer.serve.serve_forever`` — the
    container ``command`` must build the engine and enter it).

    A Deployment, not a Job: serving has no completion — replicas restart
    forever, scale horizontally, and drain on SIGTERM
    (``terminationGracePeriodSeconds`` covers the engine finishing its
    live slots before the pod dies; serve_forever stops admitting the
    moment the preemption flag is raised). The live ``/metrics`` +
    ``/status`` exporter doubles as the readiness probe — a pod is
    routable exactly when its engine answers — and the ``TPUFLOW_SERVE_*``
    knobs ride the pod env so the engine shape is declared beside the
    hardware it runs on.
    """
    dep_name = name.lower().replace("_", "-")
    topo = parse_topology(topology)
    penv = [
        {"name": "TPUFLOW_OBS_HTTP_PORT", "value": str(metrics_port)},
        # The probe (and a cluster scraper) come in over the pod IP.
        {"name": "TPUFLOW_OBS_HTTP_HOST", "value": "0.0.0.0"},
        {"name": "TPUFLOW_PREEMPT_GRACE_S", "value": str(drain_grace_s)},
        # Fleet identity (ISSUE 14): the pod name IS the replica id —
        # stamped into /status and the registration file so a fleet
        # snapshot names the pod an operator would kubectl into.
        {
            "name": "TPUFLOW_FLEET_REPLICA_ID",
            "valueFrom": {"fieldRef": {"fieldPath": "metadata.name"}},
        },
    ]
    if max_slots is not None:
        penv.append(
            {"name": "TPUFLOW_SERVE_SLOTS", "value": str(max_slots)}
        )
    if prefill_chunk is not None:
        penv.append(
            {
                "name": "TPUFLOW_SERVE_PREFILL_CHUNK",
                "value": str(prefill_chunk),
            }
        )
    if buckets:
        penv.append(
            {
                "name": "TPUFLOW_SERVE_BUCKETS",
                "value": ",".join(str(int(b)) for b in buckets),
            }
        )
    # Declared latency SLOs (ISSUE 13): the engine emits
    # serve.slo_violation events + the violation counter the moment a
    # replica misses them — declared beside the hardware, like the
    # engine-shape knobs above.
    if slo_ttft_ms is not None:
        penv.append(
            {
                "name": "TPUFLOW_SERVE_SLO_TTFT_MS",
                "value": str(float(slo_ttft_ms)),
            }
        )
    if slo_itl_ms is not None:
        penv.append(
            {
                "name": "TPUFLOW_SERVE_SLO_ITL_MS",
                "value": str(float(slo_itl_ms)),
            }
        )
    penv.extend(_trace_env())
    for k, v in sorted((env or {}).items()):
        penv.append({"name": str(k), "value": str(v)})
    container = {
        "name": dep_name,
        "image": image,
        "command": command
        or ["python", "-m", "tpuflow.infer.serve"],
        "env": penv,
        "ports": [{"name": "metrics", "containerPort": metrics_port}],
        "resources": {
            "limits": {"google.com/tpu": topo["chips_per_host"]}
        },
        "readinessProbe": {
            "httpGet": {"path": "/status", "port": metrics_port},
            "periodSeconds": 5,
        },
    }
    node_selector = {
        "cloud.google.com/gke-tpu-accelerator": topo["accelerator"],
    }
    if topo["grid"]:
        node_selector["cloud.google.com/gke-tpu-topology"] = topo["grid"]
    if compute_pool:
        node_selector["cloud.google.com/gke-nodepool"] = compute_pool
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": dep_name,
            "annotations": {"tpuflow.dev/serving": "1"},
        },
        "spec": {
            "replicas": int(replicas),
            "selector": {"matchLabels": {"app": dep_name}},
            "template": {
                "metadata": {
                    "labels": {"app": dep_name},
                    # Scrape annotations (ISSUE 14): a cluster
                    # Prometheus discovers every replica's /metrics —
                    # including the mergeable TTFT/ITL histogram
                    # buckets — without per-fleet scrape config.
                    "annotations": {
                        "prometheus.io/scrape": "true",
                        "prometheus.io/port": str(metrics_port),
                        "prometheus.io/path": "/metrics",
                    },
                },
                "spec": {
                    "nodeSelector": node_selector,
                    "terminationGracePeriodSeconds": int(drain_grace_s),
                    "containers": [container],
                },
            },
        },
    }


def serving_service(name: str, *, metrics_port: int = 8080) -> dict:
    """ClusterIP Service in front of the serving Deployment's replicas
    (the scrape/ingress target; selector matches serving_deployment)."""
    dep_name = name.lower().replace("_", "-")
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": dep_name},
        "spec": {
            "selector": {"app": dep_name},
            "ports": [
                {
                    "name": "metrics",
                    "port": metrics_port,
                    "targetPort": metrics_port,
                }
            ],
        },
    }


def serving_headless_service(name: str, *, metrics_port: int = 8080) -> dict:
    """HEADLESS Service (clusterIP: None) beside the ClusterIP one: its
    DNS name resolves to EVERY ready pod's IP instead of one virtual IP,
    which is the fleet observatory's k8s discovery mode (ISSUE 14) — put
    ``http://<name>-fleet:<port>`` in ``TPUFLOW_FLEET_REPLICAS`` and
    ``tpuflow.obs.fleet`` expands the A records into one replica per
    pod. ``publishNotReadyAddresses`` keeps a draining/unready replica
    visible so the observatory marks it degraded rather than losing it."""
    dep_name = name.lower().replace("_", "-")
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": f"{dep_name}-fleet"},
        "spec": {
            "clusterIP": "None",
            "publishNotReadyAddresses": True,
            "selector": {"app": dep_name},
            "ports": [
                {
                    "name": "metrics",
                    "port": metrics_port,
                    "targetPort": metrics_port,
                }
            ],
        },
    }


def router_deployment(
    name: str,
    *,
    image: str = "tpuflow:latest",
    replicas: int = 1,
    port: int = 8900,
    fleet_target: str | None = None,
    command: list[str] | None = None,
    timeout_s: float | None = None,
    retries: int | None = None,
    queue_timeout_s: float | None = None,
    autoscale: bool = False,
    env: dict[str, str] | None = None,
) -> dict:
    """apps/v1 Deployment for the front-door router (ISSUE 17): the
    fleet's single client-facing ingress, running
    ``tpuflow.infer.frontdoor`` against the serving fleet's headless
    discovery Service.

    A HOST deployment, not a TPU one — the router is pure python over
    snapshot dicts and sockets, so it requests no accelerator and needs
    no node selector: it schedules anywhere, restarts instantly, and
    scales by cheap replicas. ``fleet_target`` is what the router's
    fleet observatory polls — point it at the serving fleet's
    ``http://<serving>-fleet:<metrics_port>`` headless Service (or a
    registration dir on shared storage). The readiness probe hits the
    router's own ``/healthz``; its ``/status`` serves the ``router_*``
    counters the reroute_spike alert feeds on.
    """
    dep_name = name.lower().replace("_", "-")
    penv = [
        {"name": "TPUFLOW_ROUTER_PORT", "value": str(int(port))},
        # Clients and the probe come in over the pod IP.
        {"name": "TPUFLOW_ROUTER_HOST", "value": "0.0.0.0"},
    ]
    if fleet_target:
        penv.append(
            {"name": "TPUFLOW_ROUTER_TARGET", "value": str(fleet_target)}
        )
    if timeout_s is not None:
        penv.append(
            {
                "name": "TPUFLOW_ROUTER_TIMEOUT_S",
                "value": str(float(timeout_s)),
            }
        )
    if retries is not None:
        penv.append(
            {"name": "TPUFLOW_ROUTER_RETRIES", "value": str(int(retries))}
        )
    if queue_timeout_s is not None:
        penv.append(
            {
                "name": "TPUFLOW_ROUTER_QUEUE_TIMEOUT_S",
                "value": str(float(queue_timeout_s)),
            }
        )
    if autoscale:
        penv.append({"name": "TPUFLOW_ROUTER_AUTOSCALE", "value": "1"})
    penv.extend(_trace_env())
    for k, v in sorted((env or {}).items()):
        penv.append({"name": str(k), "value": str(v)})
    container = {
        "name": dep_name,
        "image": image,
        "command": command or ["python", "-m", "tpuflow.infer.frontdoor"],
        "env": penv,
        "ports": [{"name": "http", "containerPort": int(port)}],
        "readinessProbe": {
            "httpGet": {"path": "/healthz", "port": int(port)},
            "periodSeconds": 5,
        },
    }
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": dep_name,
            "annotations": {"tpuflow.dev/router": "1"},
        },
        "spec": {
            "replicas": int(replicas),
            "selector": {"matchLabels": {"app": dep_name}},
            "template": {
                "metadata": {"labels": {"app": dep_name}},
                "spec": {"containers": [container]},
            },
        },
    }


def router_service(name: str, *, port: int = 8900) -> dict:
    """ClusterIP Service in front of the router Deployment — the
    address clients (and the serving runbook's curl examples) use."""
    dep_name = name.lower().replace("_", "-")
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": dep_name},
        "spec": {
            "selector": {"app": dep_name},
            "ports": [
                {"name": "http", "port": int(port), "targetPort": int(port)}
            ],
        },
    }


def materialize_router(
    name: str, out_dir: str, *, image: str = "tpuflow:latest", **kw
) -> list[str]:
    """Write the router Deployment + Service YAML into ``out_dir``;
    returns the files written (kubectl-apply shapes, like
    materialize_serving)."""
    import yaml

    os.makedirs(out_dir, exist_ok=True)
    dep_name = name.lower().replace("_", "-")
    port = int(kw.get("port", 8900))
    written = []
    for fname, payload in (
        (
            f"{dep_name}.deployment.yaml",
            router_deployment(name, image=image, **kw),
        ),
        (
            f"{dep_name}.service.yaml",
            router_service(name, port=port),
        ),
    ):
        path = os.path.join(out_dir, fname)
        with open(path, "w") as f:
            yaml.safe_dump(payload, f, sort_keys=False)
        written.append(path)
    return written


def materialize_serving(
    name: str, out_dir: str, *, image: str = "tpuflow:latest", **kw
) -> list[str]:
    """Write the serving Deployment + Service (ClusterIP + headless
    fleet-discovery) YAML into ``out_dir``; returns the files written
    (kubectl-apply shapes, like materialize)."""
    import yaml

    os.makedirs(out_dir, exist_ok=True)
    dep_name = name.lower().replace("_", "-")
    metrics_port = int(kw.get("metrics_port", 8080))
    written = []
    for fname, payload in (
        (
            f"{dep_name}.deployment.yaml",
            serving_deployment(name, image=image, **kw),
        ),
        (
            f"{dep_name}.service.yaml",
            serving_service(name, metrics_port=metrics_port),
        ),
        (
            f"{dep_name}.headless.yaml",
            serving_headless_service(name, metrics_port=metrics_port),
        ),
    ):
        path = os.path.join(out_dir, fname)
        with open(path, "w") as f:
            yaml.safe_dump(payload, f, sort_keys=False)
        written.append(path)
    return written


def materialize(flow_cls, out_dir: str, *, image: str = "tpuflow:latest") -> list[str]:
    """Write manifests + requirement locks for ``flow_cls`` into ``out_dir``.

    Returns the list of files written. Gang steps become JobSets, other
    steps with resources become Jobs, ``@schedule`` becomes a CronJob, and
    every ``@pypi(packages=...)`` record becomes a pinned
    requirements-<step>.txt.
    """
    import yaml

    os.makedirs(out_dir, exist_ok=True)
    flow_name = flow_cls.__name__
    script = _flow_script(flow_cls)
    written: list[str] = []

    def emit(fname: str, payload) -> None:
        path = os.path.join(out_dir, fname)
        with open(path, "w") as f:
            if fname.endswith(".yaml"):
                yaml.safe_dump(payload, f, sort_keys=False)
            else:
                f.write(payload)
        written.append(path)

    steps = [
        (name, fn)
        for name, fn in vars(flow_cls).items()
        if callable(fn) and getattr(fn, "__is_step__", False)
    ]
    for name, fn in steps:
        pypi = getattr(fn, "__pypi__", None) or {}
        if pypi.get("packages"):
            lock = "".join(
                f"{pkg}=={ver}\n" for pkg, ver in sorted(pypi["packages"].items())
            )
            emit(f"requirements-{name}.txt", lock)
        if getattr(fn, "__gang__", None):
            emit(
                f"{flow_name.lower()}-{name}.jobset.yaml",
                _gang_jobset(flow_name, name, fn, image=image, script=script),
            )
        elif getattr(fn, "__resources__", None):
            emit(
                f"{flow_name.lower()}-{name}.job.yaml",
                _plain_job(flow_name, name, fn, image=image, script=script),
            )
    cron = getattr(flow_cls, "__schedule__", None)
    if cron:
        emit(
            f"{flow_name.lower()}.cronjob.yaml",
            _cronjob(flow_name, cron, image=image, script=script),
        )
    return written
