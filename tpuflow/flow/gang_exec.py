"""Gang member bootstrap: one host process of a gang step.

Invoked by FlowRunner._exec_gang as
``python -m tpuflow.flow.gang_exec <flow_file> <class> <step> <run_id>
<task_id> <state_path>`` with TPUFLOW_NUM_PROCESSES / TPUFLOW_PROCESS_ID /
TPUFLOW_COORDINATOR in the env. Each member joins the ``jax.distributed``
world (rendezvous with timeout ↔ @metaflow_ray's all_nodes_started_timeout,
train_flow.py:42), runs the step body SPMD, persists its artifacts to its own
task dir (head = task_id of the gang step; the join step reads all of them),
and shuts down.

On the local CPU simulation each member contributes
``TPUFLOW_GANG_LOCAL_DEVICES`` (default 1) virtual CPU devices with gloo
cross-process collectives — the dev-mode analogue of one TPU host per pod
slice."""

from __future__ import annotations

import importlib.util
import os
import pickle
import sys
from tpuflow.utils import knobs


def _bootstrap_jax() -> None:
    import jax

    if knobs.raw("TPUFLOW_FORCE_CPU") == "1":
        from tpuflow.dist import force_cpu_platform

        local = int(knobs.raw("TPUFLOW_GANG_LOCAL_DEVICES", "1"))
        force_cpu_platform(local, exact=True)
        if int(knobs.raw("TPUFLOW_NUM_PROCESSES", "1")) > 1:
            # Cross-process CPU collectives only exist for real gangs —
            # a 1-process member must not ask for gloo (jaxlib refuses to
            # build gloo collectives without a distributed client).
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    # Comm/compute overlap (ISSUE 10): stage the async-collective libtpu
    # scheduling flags BEFORE any backend touch, so the per-microbatch
    # gradient reduce-scatters the FSDP accumulation scan issues can
    # hide behind the next microbatch's backward. One knob
    # (TPUFLOW_COMM_OVERLAP=0) turns both halves off; CPU members no-op.
    from tpuflow.dist import maybe_enable_async_collectives

    maybe_enable_async_collectives()
    # Gang members share the persistent compile cache: after one worker
    # (or a previous attempt) compiled the step, the rest load it. With
    # TPUFLOW_COMPILE_CACHE=run the cache keys under the run directory
    # (the parent of the obs dir every member inherits) — the mode for
    # k8s gangs whose only shared storage is the run dir, so a requeued
    # attempt on a fresh pod still reloads the compiled step.
    from tpuflow.dist import maybe_enable_compile_cache, seed_compile_cache

    obs_dir = knobs.raw("TPUFLOW_OBS_DIR")
    cache_dir = maybe_enable_compile_cache(
        run_dir=os.path.dirname(obs_dir) if obs_dir else None
    )
    # Startup-latency satellite (ISSUE 9): a cache prewarmed AHEAD of
    # gang launch (tools/prewarm_cache.py, typically on the image or a
    # shared volume) seeds this member's cache before any jit runs —
    # the first step / decode block loads a compiled executable instead
    # of paying the measured 62.9 s compile inside wall-to-first-step.
    # Rsync-style: only entries absent here are copied, existing ones
    # never touched, and an unreadable source is a silent no-op.
    prewarm = knobs.raw("TPUFLOW_PREWARM_CACHE")
    if prewarm and cache_dir and prewarm != cache_dir:
        copied = seed_compile_cache(prewarm, cache_dir)
        if copied:
            print(
                f"[tpuflow] seeded {copied} prewarmed compile-cache "
                f"entries from {prewarm}"
            )


def _store_artifacts(flow_name: str, run_id: str, step_name: str) -> dict:
    """Artifacts of the most recently completed upstream task in the run's
    datastore — the k8s-pod replacement for the local launcher's pickled
    gang state (each step runs as its own Job against shared storage, the
    Metaflow execution model the deployer's manifests assume)."""
    from tpuflow.flow import store

    rd = store.run_dir(flow_name, run_id)
    if not os.path.isdir(rd):
        os.makedirs(rd, exist_ok=True)
        store.write_run_meta(
            flow_name, run_id, {"run_id": run_id, "status": "running"}
        )
        return {}
    best = None
    for root, _dirs, files in os.walk(rd):
        if "artifacts.json" not in files:
            continue
        # Only COMMITTED artifact saves count: the marker is written
        # strictly after artifacts.json + blobs (store.save_artifacts), so
        # a task that crashed mid-save — a failed attempt's partial
        # artifacts — can never be resurrected here by winning on mtime.
        if "artifacts.ok" not in files:
            continue
        parts = root.rstrip(os.sep).split(os.sep)
        if len(parts) < 2 or parts[-2] == step_name:
            continue  # not a task dir / the step being (re)run
        mtime = os.path.getmtime(os.path.join(root, "artifacts.ok"))
        if best is None or mtime > best[0]:
            best = (mtime, parts[-2], parts[-1])
    if best is None:
        return {}
    return store.load_artifacts(flow_name, run_id, best[1], int(best[2]))


def main(argv: list[str]) -> None:
    flow_file, class_name, step_name, run_id, task_id, state_path = argv
    # Preemption contract: SIGTERM (from the infrastructure, or from the
    # supervisor's grace-kill of a gang whose peer died) only SETS A FLAG;
    # the train loops check it at step boundaries, drain + commit a final
    # checkpoint, and raise Preempted — converted below into the requeue
    # exit code the supervisor treats as retry-without-budget.
    import signal

    from tpuflow.utils.preempt import (
        REQUEUE_EXIT_CODE,
        Preempted,
        request_preemption,
    )

    def _on_sigterm(signum, frame):
        # Flag first — the drain contract must hold even if forensics
        # fail. Then dump the flight ring: this SIGTERM may be the
        # supervisor's kill escalation (SIGKILL follows after the grace
        # window, when no further code runs), so now is the only chance
        # to leave a structured artifact; a clean preemption drain just
        # gains one extra file. dump_flight is signal-safe (ring
        # snapshot with a lock timeout) and never raises.
        request_preemption(signum, frame)
        try:
            from tpuflow.obs import flight as _flight

            _flight.dump_flight("sigterm")
        except Exception:
            pass

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # not the main thread (library embedding)
        pass
    from tpuflow.testing import faults

    faults.maybe_rendezvous_delay()
    _bootstrap_jax()

    spec = importlib.util.spec_from_file_location("_tpuflow_gang_flow", flow_file)
    module = importlib.util.module_from_spec(spec)
    sys.modules["_tpuflow_gang_flow"] = module
    spec.loader.exec_module(module)
    flow_cls = getattr(module, class_name)

    if state_path == "--from-store":
        state = {
            "artifacts": _store_artifacts(flow_cls.__name__, run_id, step_name)
        }
    else:
        with open(state_path, "rb") as f:
            state = pickle.load(f)

    from tpuflow import dist
    from tpuflow.dist import membership
    from tpuflow.flow import store
    from tpuflow.flow.spec import current

    timeout = float(knobs.raw("TPUFLOW_GANG_TIMEOUT", "300"))
    if (
        membership.enabled()
        and knobs.raw("TPUFLOW_GANG_REJOIN") == "1"
    ):
        # Requeued capacity rejoining an elastic gang (ISSUE 7): skip the
        # gen-0 rendezvous entirely — request inclusion, wait for the
        # supervisor's grow plan, and enter that generation's world. The
        # survivors hit the same generation at their next step fence.
        faults.maybe_rejoin_delay()
        me = membership.member_id()
        membership.request_join(me)
        plan = membership.await_plan_including(me, timeout_s=timeout)
        membership.join_generation(plan, timeout_s=timeout)
    else:
        # dist.initialize routes elastic gangs (TPUFLOW_MEMBERSHIP_DIR
        # set by the launcher) through the teardown-capable membership
        # runtime at generation 0.
        dist.initialize(timeout_s=timeout)
    # Deliberately NO heartbeat here: the first stamp comes from the train
    # loops (fenced steps / reports), so only members that demonstrably
    # adopted the protocol are ever judged for staleness — an arbitrary
    # quiet step body must not be reaped by the default stall timeout.
    # (A member hung in rendezvous itself is bounded by dist.initialize's
    # own timeout, which exits non-zero → supervisor fail-fast.)

    import jax

    flow = flow_cls()
    for k, v in state["artifacts"].items():
        setattr(flow, k, v)

    current.flow_name = flow_cls.__name__
    current.run_id = str(run_id)
    current.step_name = step_name
    current.task_id = int(task_id)
    current.gang_index = jax.process_index()
    current.gang_size = jax.process_count()
    current.tpu_storage_path = os.path.join(
        store.run_dir(flow_cls.__name__, run_id), "tpu_storage", step_name
    )
    os.makedirs(current.tpu_storage_path, exist_ok=True)

    # The recorder self-configures from TPUFLOW_OBS_DIR/TPUFLOW_OBS_PROC
    # (set by FlowRunner._exec_gang), so each member writes its own
    # events.p<proc>.jsonl beside the head's — merged at end of run.
    from tpuflow import obs
    from tpuflow.obs import export as obs_export

    # Live metrics endpoint (ISSUE 6, opt-in TPUFLOW_OBS_HTTP_PORT):
    # gang member 0 serves /metrics + /status for the whole gang.
    obs_export.maybe_start_from_env(proc=jax.process_index())

    fn = flow_cls.steps()[step_name]
    try:
        with obs.span(
            "flow.gang_member",
            step=step_name,
            gang_index=jax.process_index(),
            gang_size=jax.process_count(),
        ):
            fn(flow)
    except Preempted as e:
        # The loop already drained and committed its final checkpoint
        # (full save, or the fast local-tier emergency save when the
        # grace window was closing); exit with the requeue code —
        # os._exit, because surviving this far with a possibly-dead peer
        # means the shutdown barrier below could hang until the
        # collective timeout.
        from tpuflow.utils.preempt import grace_remaining_s

        grace = grace_remaining_s()
        spare = f" with {grace:.1f}s grace to spare" if grace is not None else ""
        print(f"[tpuflow] gang member preempted, requeueing{spare}: {e}")
        obs.flush()
        sys.stdout.flush()
        os._exit(REQUEUE_EXIT_CODE)
    except BaseException as e:
        # Fatal path: this member is about to exit non-zero and the
        # supervisor will record flow.member_failed — leave the
        # structured forensic artifact (ring + env fingerprint + THIS
        # stack) that the event references, then let the failure
        # propagate unchanged.
        from tpuflow.obs import flight as flight_mod

        flight_mod.dump_flight("unhandled_exception", e)
        obs.flush()
        raise
    # Run registry (ISSUE 16): member 0 appends this leg's headline
    # (goodput fraction, tokens/s, HBM peak) to the cross-run registry —
    # a single knob read when TPUFLOW_REGISTRY_PATH is unarmed, and
    # never a run failure when it is.
    if jax.process_index() == 0:
        from tpuflow.obs import registry as registry_mod

        registry_mod.maybe_append_live("train")
    obs.flush()

    # Every member persists its own artifacts; the head's land at the gang
    # step's task_id and are what the flow continues with (non-head members
    # mirror the reference's artifact-less worker tasks, train_flow.py:85-88).
    store.save_artifacts(
        flow_cls.__name__, run_id, step_name, int(task_id), flow._artifacts
        if jax.process_index() == 0
        else {},
    )
    if jax.process_index() == 0:
        # Hand the step's transition back to the parent runner.
        transition = getattr(flow, "_next", None)
        if transition is not None:
            import json

            tdir = store.task_dir(flow_cls.__name__, run_id, step_name, int(task_id))
            with open(os.path.join(tdir, "next.json"), "w") as f:
                json.dump({"target": transition.target}, f)
    dist.barrier("gang-step-done")
    if membership.enabled() and membership.current_generation() > 0:
        # This world was re-formed at least once: torn-down generations
        # left deliberately-leaked runtime threads (dist.membership), so
        # ordinary interpreter teardown is unsafe — their services' exit
        # would race peers' zombie poll threads into a fatal abort. Hand
        # the supervisor a done marker (its forgiveness token for exactly
        # that race), let the leaked-runtime holder (the coordinator)
        # exit LAST, and leave via os._exit.
        me = membership.member_id()
        membership.mark_done(me)
        if membership.holds_leaked_runtime():
            plan = membership.current_plan()
            others = set(plan.roster if plan else ()) - {me}
            membership.await_done(
                others,
                timeout_s=float(knobs.raw("TPUFLOW_KILL_GRACE_S", "5")),
            )
            import time as _time

            _time.sleep(0.2)  # let peers' exits finish closing sockets
        obs.flush()
        sys.stdout.flush()
        os._exit(0)
    dist.shutdown()


if __name__ == "__main__":
    main(sys.argv[1:])
