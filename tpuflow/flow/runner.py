"""Flow execution engine + CLI.

Drives a FlowSpec DAG the way the Metaflow runtime drives the reference's
(train_flow.py, eval_flow.py): steps execute in transition order from
``start`` to ``end``; ``@retry`` reruns failures; gang steps
(``num_parallel>1`` or ``@tpu``) launch N host processes that form one
``jax.distributed`` world with a formation timeout, only the head process
persisting artifacts (the reference's @metaflow_ray head/worker split,
train_flow.py:42 + the tolerant join at train_flow.py:85-88); completed runs
append trigger events consumed by ``--triggered`` downstream flows
(eval_flow.py:19,42). CLI: ``run`` / ``show`` / ``deploy`` / ``trigger``
mirroring the reference runbook (README.md:10-45)."""

from __future__ import annotations

import inspect
import json
import os
import pickle
import random
import signal
import socket
import subprocess
import sys
import threading
import time
import traceback
from typing import Any

from tpuflow import obs
from tpuflow.flow import store
from tpuflow.flow.cards import CardBuffer
from tpuflow.flow.client import Run
from tpuflow.flow.spec import FlowSpec, current
from tpuflow.utils.preempt import REQUEUE_EXIT_CODE
from tpuflow.utils import knobs


class StepFailed(Exception):
    pass


class StepPreempted(StepFailed):
    """A gang member exited with the requeue code (preemption drain): the
    step should rerun without consuming the @retry budget."""


# Injectable time sources: tests pin the jitter and capture the sleeps so
# backoff behavior is provable without real waiting (tier-1 has no sleeps).
_sleep = time.sleep
_random = random.random

# Supervisor poll cadence: bounds added per-gang-step latency while keeping
# fail-fast reaction in tens of milliseconds.
_GANG_POLL_S = 0.05


def _backoff_delay(
    attempt: int, backoff_s: float, max_backoff_s: float
) -> float:
    """Exponential backoff with 0.5–1.0 jitter for retry ``attempt`` (1-based)."""
    base = min(max_backoff_s, backoff_s * (2.0 ** (attempt - 1)))
    return base * (0.5 + 0.5 * _random())


class _GangInput:
    """One gang member's view passed to a join step (↔ metaflow join inputs,
    train_flow.py:83-88: non-head members lack artifacts — accessing them
    raises AttributeError, which the reference's try/except absorbs)."""

    def __init__(self, artifacts: dict[str, Any] | None):
        self._artifacts = artifacts or {}

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self._artifacts[name]
        except KeyError:
            raise AttributeError(f"no artifact {name!r} on this gang member") from None


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _DeviceProfiler:
    """Background sampler of per-device memory stats (↔ @gpu_profile's 1 s
    nvidia-smi polling, train_flow.py:51). Writes profile.json to the task
    dir."""

    def __init__(self, interval: float, out_path: str):
        self.interval = interval
        self.out_path = out_path
        self.samples: list[dict] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        import jax

        while not self._stop.is_set():
            entry: dict[str, Any] = {"ts": time.time(), "devices": []}
            for d in jax.local_devices():
                stats = {}
                try:
                    stats = d.memory_stats() or {}
                except Exception:
                    pass
                entry["devices"].append(
                    {
                        "id": d.id,
                        "bytes_in_use": stats.get("bytes_in_use"),
                        "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                    }
                )
            self.samples.append(entry)
            self._stop.wait(self.interval)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=5)
        # Record WHAT hardware was sampled, not just how much memory it
        # used: profile.json doubles as on-hardware execution evidence
        # (platform + device kinds), the TPU analogue of @gpu_profile's
        # nvidia-smi header.
        platform = None
        kinds: list[str] = []
        try:
            import jax

            platform = jax.default_backend()
            kinds = [d.device_kind for d in jax.local_devices()]
        except Exception:
            pass
        try:
            with open(self.out_path, "w") as f:
                json.dump(
                    {
                        "interval": self.interval,
                        "platform": platform,
                        "device_kinds": kinds,
                        "samples": self.samples,
                    },
                    f,
                )
        except OSError:
            pass
        # Absorb the sampler into the unified telemetry stream: the memory
        # gauges land beside the step spans so one timeline answers both
        # "where did time go" and "what did HBM do meanwhile".
        if obs.enabled():
            peaks: dict[int, int] = {}
            for entry in self.samples:
                for dev in entry["devices"]:
                    used = dev.get("bytes_in_use")
                    if used is not None:
                        obs.gauge(
                            "device.bytes_in_use", used,
                            ts=entry["ts"], device=dev["id"],
                        )
                    peak = dev.get("peak_bytes_in_use")
                    if peak is not None:
                        peaks[dev["id"]] = max(peaks.get(dev["id"], 0), peak)
            for dev_id, peak in sorted(peaks.items()):
                obs.gauge(
                    "device.peak_bytes_in_use", peak,
                    device=dev_id, platform=platform,
                )


class FlowRunner:
    def __init__(self, flow_cls: type[FlowSpec]):
        self.flow_cls = flow_cls
        self.flow_name = flow_cls.__name__

    # ----------------------------------------------------------------- run
    def run(
        self,
        params: dict[str, Any],
        *,
        triggered: bool = False,
        run_id: int | None = None,
    ) -> str:
        run_id = run_id if run_id is not None else store.new_run_id(self.flow_name)
        rdir = store.run_dir(self.flow_name, run_id)
        os.makedirs(rdir, exist_ok=True)
        from tpuflow.flow.client import default_namespace, get_namespace

        meta = {
            "flow": self.flow_name,
            "run_id": run_id,
            "status": "running",
            # Runs are produced under the active namespace; the client
            # resolves only same-namespace runs (flow.client._check_visible
            # ↔ reference eval_flow.py:32-36). A run is always produced
            # under a CONCRETE namespace — the global (None) scope is
            # read-only, so it falls back to the user default.
            "namespace": get_namespace() or default_namespace(),
            "params": {k: _jsonable(v) for k, v in params.items()},
            "started": time.time(),
            "steps": [],
            "schedule": getattr(self.flow_cls, "__schedule__", None),
            "trigger_on_finish": getattr(
                self.flow_cls, "__trigger_on_finish__", None
            ),
        }
        store.write_run_meta(self.flow_name, run_id, meta)

        flow = self.flow_cls()
        for name, value in params.items():
            setattr(flow, name, value)

        self._trigger_run = None
        if triggered:
            upstream = getattr(self.flow_cls, "__trigger_on_finish__", None)
            if upstream:
                events = [
                    e
                    for e in store.read_events(upstream)
                    if e.get("status") == "success"
                ]
                if events:
                    self._trigger_run = Run(events[-1]["run"])
                    meta["triggered_by"] = events[-1]["run"]

        steps = self.flow_cls.steps()
        if "start" not in steps or "end" not in steps:
            raise ValueError("flow must define 'start' and 'end' steps")

        step_name = "start"
        task_counter = 0
        pathspec = f"{self.flow_name}/{run_id}"
        print(f"[tpuflow] run {pathspec} starting")
        # Telemetry root for this run: the head process records here, gang
        # members inherit it via TPUFLOW_OBS_DIR (one events.p<proc>.jsonl
        # each), and the end-of-run merge produces <rdir>/events.jsonl.
        # TPUFLOW_OBS=0 disables recording entirely (README Observability).
        self._obs_dir = None
        if knobs.raw("TPUFLOW_OBS", "1") not in ("0", "false"):
            self._obs_dir = os.path.join(rdir, "obs")
            obs.configure(self._obs_dir, proc=0)
        run_span = obs.span("flow.run", flow=self.flow_name, run=str(run_id))
        run_span.__enter__()
        ran_gang = False
        try:
            while True:
                fn = steps[step_name]
                task_id = task_counter
                gang = getattr(fn, "__gang__", None)
                transition = getattr(flow, "_next", None)
                num_parallel = 1
                if transition is not None and transition.target == step_name:
                    num_parallel = transition.num_parallel
                if gang and gang.get("num_parallel"):
                    num_parallel = max(num_parallel, gang["num_parallel"])
                task_counter += num_parallel  # gang members own task_id..+N-1
                object.__setattr__(flow, "_next", None)

                retries = getattr(fn, "__retry_times__", 0)
                backoff_s = getattr(fn, "__retry_backoff_s__", 2.0)
                max_backoff_s = getattr(fn, "__retry_max_backoff_s__", 60.0)
                attempt = 0
                requeues = 0
                max_requeues = int(
                    knobs.raw("TPUFLOW_MAX_REQUEUES", "8")
                )
                while True:
                    try:
                        with obs.span(
                            "flow.step", step=step_name, task=task_id,
                            attempt=attempt, num_parallel=num_parallel,
                        ):
                            if num_parallel > 1:
                                ran_gang = True
                                gang_inputs = self._exec_gang(
                                    flow, step_name, run_id, task_id,
                                    num_parallel,
                                    timeout=(gang or {}).get("timeout", 300.0),
                                    stall_timeout=(gang or {}).get(
                                        "heartbeat_timeout"
                                    ),
                                    attempt=attempt + requeues,
                                    min_members=(gang or {}).get(
                                        "min_members"
                                    ),
                                )
                            else:
                                self._exec_local(
                                    flow, fn, step_name, run_id, task_id
                                )
                                # A following join sees this task as a
                                # 1-member gang (num_parallel=1 degenerate
                                # case).
                                gang_inputs = [
                                    _GangInput(dict(flow._artifacts))
                                ]
                        break
                    except StepPreempted:
                        # Preemption is routine, not a failure: the member
                        # drained a checkpoint and asked to be requeued, so
                        # the rerun does not consume the retry budget. A cap
                        # bounds pathological preemption storms.
                        requeues += 1
                        if requeues > max_requeues:
                            raise
                        print(
                            f"[tpuflow] step {step_name} preempted "
                            f"(requeue {requeues}/{max_requeues}), "
                            "relaunching without consuming retry budget"
                        )
                    except Exception:
                        attempt += 1
                        if attempt > retries:
                            raise
                        obs.counter("flow.retry", step=step_name,
                                    attempt=attempt)
                        delay = _backoff_delay(
                            attempt, backoff_s, max_backoff_s
                        )
                        obs.gauge(
                            "flow.retry_backoff_s", delay, step=step_name,
                            attempt=attempt,
                        )
                        print(
                            f"[tpuflow] step {step_name} failed "
                            f"(attempt {attempt}/{retries}), retrying in "
                            f"{delay:.1f}s:\n"
                            f"{traceback.format_exc(limit=3)}"
                        )
                        _sleep(delay)

                meta["steps"].append(
                    {"step": step_name, "head_task": task_id, "tasks": num_parallel}
                )
                store.write_run_meta(self.flow_name, run_id, meta)

                if step_name == "end":
                    break
                transition = getattr(flow, "_next", None)
                if transition is None:
                    raise StepFailed(
                        f"step {step_name!r} did not call self.next(...)"
                    )
                next_name = transition.target
                next_fn = steps[next_name]
                # A join step (2nd positional arg) receives gang inputs.
                if gang_inputs is not None and _takes_inputs(next_fn):
                    object.__setattr__(flow, "_join_inputs", gang_inputs)
                step_name = next_name
        except Exception as e:
            meta["status"] = "failed"
            meta["error"] = repr(e)
            meta["finished"] = time.time()
            run_span.set(status="failed")
            run_span.__exit__(None, None, None)
            self._finalize_obs(rdir, pathspec, meta)
            store.write_run_meta(self.flow_name, run_id, meta)
            print(f"[tpuflow] run {pathspec} FAILED: {e!r}")
            raise
        meta["status"] = "success"
        meta["finished"] = time.time()
        run_span.set(status="success")
        run_span.__exit__(None, None, None)
        # Run registry (ISSUE 16): in-process runs append their headline
        # here, while the recorder is still open so the registry.append
        # event merges into events.jsonl; gang runs already appended
        # from member 0 (gang_exec) and must not double-record.
        if not ran_gang:
            from tpuflow.obs import registry as registry_mod

            registry_mod.maybe_append_live("train")
        self._finalize_obs(rdir, pathspec, meta)
        store.write_run_meta(self.flow_name, run_id, meta)
        store.append_event(
            {"flow": self.flow_name, "run": pathspec, "status": "success"}
        )
        print(f"[tpuflow] run {pathspec} succeeded")
        return pathspec

    def _finalize_obs(self, rdir: str, pathspec: str, meta: dict) -> None:
        """Close the run's recorder, merge gang-worker event files into
        ``<rdir>/events.jsonl``, render the timeline card, and stamp the
        headline summary (``meta["telemetry"]``) plus the training-health
        view (``meta["health"]``, when anything happened) into run.json.
        Telemetry must never fail the run."""
        meta.setdefault("telemetry", {})
        try:
            obs.configure(None)  # flush + close the head recorder
            events = obs.merge_run_events(rdir)
            if not events:
                return
            summary = obs.summarize(events)
            from tpuflow.flow.cards import timeline_card

            buf = CardBuffer()
            timeline_card(buf, events, summary=summary)
            with open(os.path.join(rdir, "timeline.html"), "w") as f:
                f.write(buf.render_html(f"{pathspec} timeline"))
            meta["telemetry"] = summary.get("headline", {})
            health = summary.get("health") or {}
            if (
                health.get("anomalies")
                or health.get("rollbacks")
                or health.get("profiles")
                or health.get("dropped_events")
            ):
                # Only stamped when noteworthy: a clean run's run.json
                # stays as small as before this section existed.
                meta["health"] = health
        except Exception as e:
            print(f"[tpuflow] telemetry finalize failed (ignored): {e!r}")

    # ----------------------------------------------------- single-task exec
    def _exec_local(
        self, flow: FlowSpec, fn, step_name: str, run_id, task_id: int
    ) -> None:
        tdir = store.task_dir(self.flow_name, run_id, step_name, task_id)
        os.makedirs(tdir, exist_ok=True)
        from tpuflow.flow.spec import _Trigger

        current.flow_name = self.flow_name
        current.run_id = str(run_id)
        current.step_name = step_name
        current.task_id = task_id
        current.trigger = (
            _Trigger(self._trigger_run) if getattr(self, "_trigger_run", None) else None
        )
        current.tpu_storage_path = os.path.join(
            store.run_dir(self.flow_name, run_id), "tpu_storage", step_name
        )
        os.makedirs(current.tpu_storage_path, exist_ok=True)
        card_type = getattr(fn, "__card__", None)
        current.card = CardBuffer() if card_type else None

        profile_cfg = getattr(fn, "__device_profile__", None)
        profiler = (
            _DeviceProfiler(
                profile_cfg["interval"], os.path.join(tdir, "profile.json")
            )
            if profile_cfg
            else None
        )
        join_inputs = getattr(flow, "_join_inputs", None)
        if join_inputs is not None:
            object.__setattr__(flow, "_join_inputs", None)
        trace_ctx = None
        if profile_cfg and profile_cfg.get("trace"):
            import contextlib

            import jax

            trace_ctx = contextlib.ExitStack()
            try:
                jax.profiler.start_trace(os.path.join(tdir, "trace"))
                trace_ctx.callback(jax.profiler.stop_trace)
            except Exception:
                trace_ctx = None
        try:
            if profiler:
                with profiler:
                    self._call_step(flow, fn, join_inputs)
            else:
                self._call_step(flow, fn, join_inputs)
            if current.card is not None:
                with obs.span("flow.card_render", step=step_name):
                    with open(os.path.join(tdir, "card.html"), "w") as f:
                        f.write(
                            current.card.render_html(
                                f"{self.flow_name}/{run_id}/{step_name}"
                            )
                        )
            store.save_artifacts(
                self.flow_name, run_id, step_name, task_id, flow._artifacts
            )
        finally:
            if trace_ctx is not None:
                trace_ctx.close()
            current.card = None

    @staticmethod
    def _call_step(flow: FlowSpec, fn, join_inputs) -> None:
        if _takes_inputs(fn):
            fn(flow, join_inputs or [])
        else:
            fn(flow)

    # ------------------------------------------------------------ gang exec
    def _exec_gang(
        self,
        flow: FlowSpec,
        step_name: str,
        run_id,
        task_id: int,
        num_parallel: int,
        *,
        timeout: float,
        stall_timeout: float | None = None,
        attempt: int = 0,
        min_members: int | None = None,
    ) -> list[_GangInput]:
        """Launch N processes running the step body as one jax.distributed
        world (local simulation of the pod-slice gang, SURVEY.md §2b D8),
        then supervise them: fail fast on the first non-zero exit, detect
        hung members via heartbeat staleness, and classify requeue exits
        (preemption drains) separately from crashes."""
        tdir = store.task_dir(self.flow_name, run_id, step_name, task_id)
        os.makedirs(tdir, exist_ok=True)
        state_path = os.path.join(tdir, "gang_state.pkl")
        for name, value in flow._artifacts.items():
            # Same contract as the datastore: device tensors never ship by
            # pickle into the gang subprocesses — only Checkpoint handles.
            if not isinstance(value, store.Checkpoint):
                store.reject_device_arrays(name, value)
        with open(state_path, "wb") as f:
            pickle.dump(
                {"artifacts": flow._artifacts, "module": self._flow_module()}, f
            )
        port = _free_port()
        # Elastic gang (ISSUE 7): with TPUFLOW_ELASTIC=1 a member loss no
        # longer kills the survivors — the supervisor announces a mesh
        # re-form through this shared membership dir (cleared per launch:
        # a previous attempt's plan must not leak into this world).
        elastic = (
            knobs.raw("TPUFLOW_ELASTIC") == "1" and num_parallel > 1
        )
        membership_dir = None
        if elastic:
            import shutil

            membership_dir = os.path.join(tdir, "membership")
            shutil.rmtree(membership_dir, ignore_errors=True)
            os.makedirs(membership_dir, exist_ok=True)
        import tpuflow

        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(tpuflow.__file__)))

        def launch_member(
            i: int, *, rejoin: bool = False
        ) -> tuple[subprocess.Popen, Any]:
            # Stale heartbeats from a previous attempt (or a lost member's
            # final stamp) would read as an instant stall — clear before
            # every launch.
            hb_path = os.path.join(tdir, f"heartbeat_{i}")
            try:
                os.unlink(hb_path)
            except FileNotFoundError:
                pass
            env = dict(os.environ)
            env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
            env.update(
                TPUFLOW_NUM_PROCESSES=str(num_parallel),
                TPUFLOW_PROCESS_ID=str(i),
                TPUFLOW_COORDINATOR=f"127.0.0.1:{port}",
                TPUFLOW_GANG_TIMEOUT=str(timeout),
                TPUFLOW_FORCE_CPU=env_force_cpu(),
                TPUFLOW_ATTEMPT=str(attempt),
                TPUFLOW_HEARTBEAT_FILE=hb_path,
            )
            if membership_dir is not None:
                env["TPUFLOW_MEMBERSHIP_DIR"] = membership_dir
            if rejoin:
                # Requeued capacity: the member skips the gen-0 rendezvous
                # and instead requests inclusion in the next (grow)
                # generation. Same TPUFLOW_ATTEMPT as the gang launch so
                # the goodput ledger keeps ONE attempt lane (an in-place
                # resize must not read as a requeue gap).
                env["TPUFLOW_GANG_REJOIN"] = "1"
            if "TPUFLOW_PREEMPT_GRACE_S" not in env:
                # The supervisor SIGKILLs TPUFLOW_KILL_GRACE_S after
                # its SIGTERM — tell members their real termination
                # grace so the drain's emergency-save decision
                # (preempt.emergency_save_advised) counts down from
                # the budget that actually applies here. Deployed,
                # the pod spec sets TPUFLOW_PREEMPT_GRACE_S from
                # terminationGracePeriodSeconds instead.
                env["TPUFLOW_PREEMPT_GRACE_S"] = knobs.raw(
                    "TPUFLOW_KILL_GRACE_S", "5"
                )
            if getattr(self, "_obs_dir", None):
                # Each member records its own events.p<i>.jsonl in the
                # run's obs dir; the end-of-run merge unions them.
                env["TPUFLOW_OBS_DIR"] = self._obs_dir
                env["TPUFLOW_OBS_PROC"] = str(i)
            cmd = [
                sys.executable,
                "-m",
                "tpuflow.flow.gang_exec",
                self._flow_module(),
                self.flow_cls.__name__,
                step_name,
                str(run_id),
                str(task_id + i),
                state_path,
            ]
            log = open(
                os.path.join(tdir, f"gang_{i}.log"), "a" if rejoin else "w"
            )
            try:
                p = subprocess.Popen(
                    cmd, env=env, stdout=log, stderr=subprocess.STDOUT,
                    cwd=os.getcwd(),
                )
            except BaseException:
                log.close()
                raise
            return (p, log)

        procs: list[tuple[subprocess.Popen, Any]] = []
        launched = False
        try:
            for i in range(num_parallel):
                procs.append(launch_member(i))
            launched = True
        finally:
            if not launched:
                # A mid-loop launch failure must not leak already-spawned
                # members or their open log files.
                for p, log in procs:
                    try:
                        p.kill()
                        p.wait(timeout=10)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
                    log.close()
        with obs.span(
            "flow.gang", step=step_name, num_parallel=num_parallel
        ) as gang_span:
            failure = self._supervise_gang(
                procs, tdir, step_name,
                timeout=timeout, stall_timeout=stall_timeout,
                membership_dir=membership_dir,
                launch_member=launch_member if elastic else None,
                min_members=min_members,
            )
            gang_span.set(failed=failure is not None)
        if failure is not None:
            kind, member, detail = failure
            if kind == "preempt":
                raise StepPreempted(
                    f"gang step {step_name!r} preempted (member {member} "
                    f"exited with requeue code {REQUEUE_EXIT_CODE})"
                )
            logs = []
            for i in range(num_parallel):
                lp = os.path.join(tdir, f"gang_{i}.log")
                if os.path.exists(lp):
                    with open(lp) as f:
                        tail = f.read()[-2000:]
                    logs.append(f"--- gang member {i} ---\n{tail}")
            raise StepFailed(
                f"gang step {step_name!r} failed ({detail}):\n"
                + "\n".join(logs)
            )
        # Load head artifacts back into the in-process flow to continue.
        head_artifacts = store.load_artifacts(
            self.flow_name, run_id, step_name, task_id
        )
        for k, v in head_artifacts.items():
            setattr(flow, k, v)
        # Recover the head's self.next(...) transition.
        next_path = os.path.join(tdir, "next.json")
        if os.path.exists(next_path):
            with open(next_path) as f:
                target = json.load(f)["target"]
            flow.next(getattr(flow, target))
        inputs = [_GangInput(head_artifacts)]
        for i in range(1, num_parallel):
            arts = store.load_artifacts(
                self.flow_name, run_id, step_name, task_id + i
            )
            inputs.append(_GangInput(arts))
        return inputs

    def _supervise_gang(
        self,
        procs: list,
        tdir: str,
        step_name: str,
        *,
        timeout: float,
        stall_timeout: float | None,
        membership_dir: str | None = None,
        launch_member=None,
        min_members: int | None = None,
    ):
        """Poll all gang members until they all exit cleanly or one fails.

        Replaces the old sequential ``p.wait()`` join, whose worst case was
        every surviving peer hanging in a dead collective until the flat
        ``timeout + 600`` deadline. Here the first non-zero exit (or a
        heartbeat stall) kills the survivors promptly — SIGTERM (so they
        can drain a checkpoint) escalating to SIGKILL after
        ``TPUFLOW_KILL_GRACE_S``.

        Elastic mode (ISSUE 7, ``membership_dir`` + ``launch_member``
        given): a non-coordinator member loss no longer fails the step —
        the supervisor converts it into a mesh re-form at step-fence
        granularity: ``flow.member_lost`` is recorded, a shrink generation
        is announced through the membership dir, and the survivors drain,
        re-rendezvous and continue. When the lost capacity is requeue-
        eligible (crash or preemption, not a ``member_lost`` fault) the
        member is relaunched and, once it requests inclusion, a grow
        generation re-adds it. Falls back to the classic requeue-the-world
        verdict when the coordinator (member 0) dies, the survivors would
        drop below the min-members floor, a re-form misses its deadline,
        or the resize budget is spent. While a re-form is in flight the
        heartbeat-stall judgment is suspended — quiesce/rendezvous
        legitimately stops step fences, so the re-form deadline (not
        ``TPUFLOW_STALL_TIMEOUT_S``) governs, and ``flow.heartbeat_stall``
        never fingers a draining survivor.

        Returns ``None`` on success or ``(kind, member, detail)`` where
        kind ∈ {"member_failed", "heartbeat_stall", "timeout", "preempt",
        "reform_timeout"}.
        """
        if stall_timeout is None:
            stall_timeout = float(
                knobs.raw("TPUFLOW_STALL_TIMEOUT_S", "600")
            )
        deadline = time.monotonic() + timeout + 600.0
        n = len(procs)
        rcs: list[int | None] = [None] * n
        failure = None
        elastic = membership_dir is not None and launch_member is not None
        roster: set[int] = set(range(n))
        generation = 0
        resizes = 0
        forming: dict | None = None  # in-flight re-form bookkeeping
        formed_at = time.monotonic()
        pending_rejoin: list[int] = []
        awaiting_join: set[int] = set()
        if elastic:
            from tpuflow.dist import membership as _ms
            from tpuflow.testing import faults as _faults

            floor = (
                int(min_members)
                if min_members
                else int(knobs.raw("TPUFLOW_GANG_MIN_MEMBERS", "2"))
            )
            reform_timeout = float(
                knobs.raw("TPUFLOW_REFORM_TIMEOUT_S", "120")
            )
            max_resizes = int(knobs.raw("TPUFLOW_MAX_RESIZES", "8"))
            try:
                # ``member_lost`` faults model PERMANENT capacity loss:
                # their requeue is suppressed so shrink is exercised
                # (``member_exit``'s relaunch exercises re-grow).
                suppressed = {
                    f.rank for f in _faults.matching("member_lost")
                }
            except ValueError:
                suppressed = set()

        def _announce(reason: str) -> None:
            nonlocal forming, generation, resizes
            generation += 1
            resizes += 1
            plan = _ms.Generation(
                generation=generation,
                roster=tuple(sorted(roster)),
                coordinator=f"127.0.0.1:{_free_port()}",
                reason=reason,
                deadline=time.time() + reform_timeout,
            )
            _ms.announce(membership_dir, plan)
            forming = {
                "plan": plan,
                "t0": time.monotonic(),
                "ts": time.time(),
                "from": len(roster) + (1 if reason == "shrink" else -1),
            }
            print(
                f"[tpuflow] gang {reason}: generation {generation} over "
                f"members {sorted(roster)} (deadline "
                f"{reform_timeout:.0f}s)"
            )

        def _elastic_loss(i: int, rc: int) -> None:
            """One roster member exited non-zero: shrink if eligible,
            else fall back to the classic requeue-the-world verdict."""
            nonlocal failure
            survivors = {
                j for j in roster if j != i and rcs[j] is None
            }
            finished_ok = {
                j for j in roster if j != i and rcs[j] == 0
            }
            eligible = (
                i != 0  # the coordinator hosts every generation's service
                and forming is None
                and resizes < max_resizes
                and len(survivors | finished_ok) >= floor
            )
            if not eligible:
                if rc == REQUEUE_EXIT_CODE:
                    failure = ("preempt", i, "requeue")
                    obs.event("flow.preempt", step=step_name, member=i)
                else:
                    failure = (
                        "member_failed", i,
                        f"member {i} exited {rc} (elastic fallback: "
                        f"{'coordinator' if i == 0 else 'floor/budget/in-flight'})",
                    )
                    attrs = {
                        "step": step_name,
                        "member": i,
                        "rc": rc,
                        "log_tail": self._log_tail(tdir, i),
                    }
                    flight = self._member_flight(i)
                    if flight:
                        attrs["flight"] = flight
                    obs.event("flow.member_failed", **attrs)
                return
            roster.discard(i)
            attrs = {
                "step": step_name,
                "member": i,
                "rc": rc,
                "survivors": len(roster),
                "log_tail": self._log_tail(tdir, i),
            }
            flight = self._member_flight(i)
            if flight:
                attrs["flight"] = flight
            obs.event("flow.member_lost", **attrs)
            _announce("shrink")
            if i not in suppressed:
                # Requeued capacity returns: crash and preemption both
                # come back (a preempted pod is rescheduled); a
                # member_lost fault stays gone.
                pending_rejoin.append(i)

        try:
            while True:
                for i, (p, log) in enumerate(procs):
                    if rcs[i] is not None:
                        continue
                    rc = p.poll()
                    if rc is None:
                        continue
                    rcs[i] = rc
                    log.close()
                    if elastic and i in awaiting_join:
                        # The relaunched member died before it could even
                        # request to rejoin: stop waiting for it (the
                        # shrunk gang is already healthy without it).
                        awaiting_join.discard(i)
                        continue
                    if rc == 0 or failure is not None:
                        continue
                    if elastic and i in _ms.done_members(membership_dir):
                        # Post-completion teardown crash of a re-formed
                        # member (leaked old-generation runtimes make
                        # interpreter teardown racy): the step body
                        # finished and its artifacts committed — forgive.
                        rcs[i] = 0
                        continue
                    if elastic and i in roster:
                        _elastic_loss(i, rc)
                    elif elastic:
                        pass  # already counted out of the roster
                    elif rc == REQUEUE_EXIT_CODE:
                        failure = ("preempt", i, "requeue")
                        obs.event(
                            "flow.preempt", step=step_name, member=i
                        )
                    else:
                        failure = (
                            "member_failed", i, f"member {i} exited {rc}"
                        )
                        attrs = {
                            "step": step_name,
                            "member": i,
                            "rc": rc,
                            "log_tail": self._log_tail(tdir, i),
                        }
                        # Crash forensics (ISSUE 6): the dying member
                        # dumped its flight ring before exiting
                        # (unhandled exception, SIGTERM, injected
                        # death) — reference the structured artifact
                        # beside the log tail.
                        flight = self._member_flight(i)
                        if flight:
                            attrs["flight"] = flight
                        obs.event("flow.member_failed", **attrs)
                if failure is not None:
                    break
                if elastic:
                    if forming is not None:
                        plan = forming["plan"]
                        if roster <= _ms.joined_members(
                            membership_dir, plan.generation
                        ):
                            dur = time.monotonic() - forming["t0"]
                            rec = obs.recorder()
                            if rec is not None:
                                rec.record(
                                    "span", "flow.gang_resize",
                                    ts=forming["ts"], dur_s=dur,
                                    step=step_name,
                                    generation=plan.generation,
                                    reason=plan.reason,
                                    from_members=forming["from"],
                                    to_members=len(roster),
                                )
                            # Reset the stall clock: a member's first
                            # post-reform fence may trail a long restore
                            # + recompile; never-stamped members are
                            # never judged.
                            for j in roster:
                                try:
                                    os.unlink(
                                        os.path.join(tdir, f"heartbeat_{j}")
                                    )
                                except OSError:
                                    pass
                            print(
                                f"[tpuflow] gang generation "
                                f"{plan.generation} formed "
                                f"({plan.reason} → {len(roster)} members, "
                                f"{dur:.1f}s)"
                            )
                            forming = None
                            formed_at = time.monotonic()
                        elif time.time() > plan.deadline:
                            failure = (
                                "reform_timeout", None,
                                f"generation {plan.generation} "
                                f"({plan.reason}) missed its "
                                f"{reform_timeout:.0f}s re-form deadline; "
                                "falling back to requeue-the-world",
                            )
                            break
                    if forming is None and pending_rejoin and (
                        # Hold the relaunch until every survivor passed a
                        # step fence in the NEW generation (their
                        # heartbeat files — cleared at formation — exist
                        # again): a grow fence arriving before the shrunk
                        # gang banked any progress makes everyone replay
                        # from scratch, where a deterministic crasher
                        # fires again. Non-stamping step bodies get a
                        # bounded hold instead.
                        all(
                            os.path.exists(
                                os.path.join(tdir, f"heartbeat_{j}")
                            )
                            for j in roster
                            if rcs[j] is None
                        )
                        or time.monotonic() - formed_at
                        > float(
                            knobs.raw("TPUFLOW_REJOIN_HOLD_S", "10")
                        )
                    ):
                        m = pending_rejoin.pop(0)
                        procs[m] = launch_member(m, rejoin=True)
                        rcs[m] = None
                        awaiting_join.add(m)
                    if forming is None and awaiting_join:
                        ready = _ms.join_requests(
                            membership_dir
                        ) & awaiting_join
                        if ready:
                            m = min(ready)
                            awaiting_join.discard(m)
                            _ms.clear_join_request(membership_dir, m)
                            roster.add(m)
                            _announce("grow")
                    if forming is None and all(
                        rcs[j] is not None for j in roster
                    ):
                        break  # every current-roster member finished
                elif all(rc is not None for rc in rcs):
                    break
                reforming = elastic and forming is not None
                if stall_timeout and stall_timeout > 0 and not reforming:
                    # Judge only members that ever stamped: arbitrary step
                    # bodies owe no heartbeats. The member with the OLDEST
                    # stamp is the culprit — its peers went silent later,
                    # blocked in collectives waiting for it. Suspended
                    # while a re-form is in flight: quiesce/rendezvous
                    # stops step fences by design, and the re-form
                    # deadline already bounds that window.
                    now = time.time()
                    stalled: list[tuple[float, int]] = []
                    for i, (p, _log) in enumerate(procs):
                        if rcs[i] is not None or (
                            elastic and i not in roster
                        ):
                            continue
                        try:
                            age = now - os.path.getmtime(
                                os.path.join(tdir, f"heartbeat_{i}")
                            )
                        except OSError:
                            continue
                        if age > stall_timeout:
                            stalled.append((age, i))
                    if stalled:
                        age, culprit = max(stalled)
                        # Heartbeats stamp the member's current step
                        # (ISSUE 6 satellite): report WHERE it stalled,
                        # not just how stale the stamp is.
                        last_step = self._heartbeat_step(tdir, culprit)
                        at = (
                            f" at step {last_step}"
                            if last_step is not None
                            else ""
                        )
                        failure = (
                            "heartbeat_stall", culprit,
                            f"member {culprit} heartbeat stalled "
                            f"{age:.1f}s (> {stall_timeout:.0f}s){at}",
                        )
                        obs.event(
                            "flow.heartbeat_stall", step=step_name,
                            member=culprit, age_s=round(age, 2),
                            last_step=(
                                last_step if last_step is not None else -1
                            ),
                            log_tail=self._log_tail(tdir, culprit),
                        )
                        break
                if time.monotonic() > deadline:
                    failure = (
                        "timeout", None,
                        f"gang deadline exceeded ({timeout:.0f}s + 600s)",
                    )
                    break
                time.sleep(_GANG_POLL_S)
        finally:
            if failure is not None or any(rc is None for rc in rcs):
                # Failure, or success with stragglers (e.g. a relaunched
                # member still waiting for a grow plan the finished gang
                # will never form): reap everything still running.
                self._kill_survivors(procs, rcs)
            for _p, log in procs:
                log.close()  # idempotent
        if failure is None and elastic and resizes:
            print(
                f"[tpuflow] elastic gang step {step_name!r} completed "
                f"after {resizes} resize(s), final generation {generation}"
            )
        if failure is not None and failure[0] == "reform_timeout":
            # The fallback verdict: surface as a plain member failure so
            # @retry requeues the world exactly as with elasticity off.
            return ("member_failed", failure[1], failure[2])
        return failure

    @staticmethod
    def _log_tail(tdir: str, member: int, limit: int = 500) -> str:
        try:
            with open(os.path.join(tdir, f"gang_{member}.log")) as f:
                return f.read()[-limit:]
        except OSError:
            return ""

    def _member_flight(self, member: int) -> str | None:
        """Path of the failed member's flight-recorder dump, if the
        member managed to write one before dying (its crash handlers run
        pre-exit, the supervisor polls post-exit — no race)."""
        obs_dir = getattr(self, "_obs_dir", None)
        if not obs_dir:
            return None
        from tpuflow.obs import flight as flight_mod

        path = flight_mod.flight_path(obs_dir, member)
        return path if os.path.exists(path) else None

    @staticmethod
    def _heartbeat_step(tdir: str, member: int) -> int | None:
        """Last step number the member stamped into its heartbeat file
        (``utils.heartbeat.beat(step=...)``), or None for a step-less /
        absent stamp."""
        try:
            with open(os.path.join(tdir, f"heartbeat_{member}")) as f:
                raw = f.read().strip()
            return int(raw) if raw else None
        except (OSError, ValueError):
            return None

    @staticmethod
    def _kill_survivors(procs: list, rcs: list) -> None:
        """SIGTERM surviving members (their preemption handler drains a
        final checkpoint), escalate to SIGKILL after the grace window."""
        grace = float(knobs.raw("TPUFLOW_KILL_GRACE_S", "5"))
        live = [i for i, rc in enumerate(rcs) if rc is None]
        for i in live:
            try:
                procs[i][0].send_signal(signal.SIGTERM)
            except OSError:
                pass
        t_end = time.monotonic() + grace
        while live and time.monotonic() < t_end:
            live = [i for i in live if procs[i][0].poll() is None]
            if live:
                time.sleep(_GANG_POLL_S)
        for i in live:
            try:
                procs[i][0].kill()
            except OSError:
                pass
        for i, rc in enumerate(rcs):
            if rc is None:
                try:
                    rcs[i] = procs[i][0].wait(timeout=10)
                except (OSError, subprocess.TimeoutExpired):
                    rcs[i] = -9

    def _flow_module(self) -> str:
        mod = inspect.getmodule(self.flow_cls)
        path = getattr(mod, "__file__", None)
        if path is None:
            raise RuntimeError("flow class must live in an importable file")
        return os.path.abspath(path)


def _takes_inputs(fn) -> bool:
    params = list(inspect.signature(fn).parameters)
    return len(params) >= 2 and params[1] not in ("args", "kwargs")


def _jsonable(v):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return str(v)


def env_force_cpu() -> str:
    """Gang subprocesses run on CPU when explicitly requested
    (TPUFLOW_FORCE_CPU=1) or when the parent itself runs on CPU."""
    explicit = knobs.raw("TPUFLOW_FORCE_CPU")
    if explicit is not None:
        return explicit
    import jax

    try:
        return "1" if jax.default_backend() == "cpu" else "0"
    except Exception:
        return "0"


# --------------------------------------------------------------------- CLI
def main(flow_cls: type[FlowSpec], argv: list[str] | None = None):
    argv = list(sys.argv[1:] if argv is None else argv)
    runner = FlowRunner(flow_cls)
    if not argv or argv[0] in ("-h", "--help", "show"):
        _show(flow_cls)
        return None
    cmd, rest = argv[0], argv[1:]
    if cmd in ("run", "trigger"):
        # Don't let a hung accelerator tunnel stall the whole run: probe the
        # default platform and fall back to virtual CPU devices if needed.
        from tpuflow.dist import (
            ensure_healthy_platform,
            maybe_enable_compile_cache,
        )

        ensure_healthy_platform()
        # Persistent XLA compile cache: retry attempts, resumes, and the
        # triggered eval flow reload compiled executables instead of
        # re-paying the 20-40 s TPU compile.
        maybe_enable_compile_cache()
    if cmd == "run":
        params, triggered = _parse_params(flow_cls, rest)
        return runner.run(params, triggered=triggered)
    if cmd == "deploy":
        # Materialize the decorator records (@kubernetes/@pypi/@tpu/
        # @schedule) into runnable k8s manifests — the deployer step the
        # reference delegates to `argo-workflows create` (README.md:27-45).
        from tpuflow.flow.deploy import materialize

        out_dir = None
        if "--manifest-dir" in rest:
            i = rest.index("--manifest-dir")
            if i + 1 >= len(rest):
                raise SystemExit("--manifest-dir requires a directory argument")
            out_dir = rest[i + 1]
        if out_dir is None:
            out_dir = os.path.join(
                store.home(), "deployments", flow_cls.__name__
            )
        manifests = materialize(flow_cls, out_dir)
        record = {
            "flow": flow_cls.__name__,
            "schedule": getattr(flow_cls, "__schedule__", None),
            "trigger_on_finish": getattr(flow_cls, "__trigger_on_finish__", None),
            "manifests": manifests,
            "deployed": time.time(),
        }
        path = store.write_deployment(flow_cls.__name__, record)
        print(f"[tpuflow] deployed {flow_cls.__name__}: {record} → {path}")
        for m in manifests:
            print(f"[tpuflow]   manifest: {m}")
        return path
    if cmd == "trigger":
        params, _ = _parse_params(flow_cls, rest)
        return runner.run(params, triggered=True)
    raise SystemExit(f"unknown command {cmd!r}; use run|show|deploy|trigger")


def _parse_params(flow_cls, rest: list[str]):
    specs = flow_cls.parameters()
    by_cli = {}
    for attr, p in specs.items():
        by_cli[p.name.replace("_", "-")] = (attr, p)
        by_cli[p.name] = (attr, p)
    params = {attr: p.default for attr, p in specs.items()}
    triggered = False
    i = 0
    while i < len(rest):
        arg = rest[i]
        if arg == "--triggered":
            triggered = True
            i += 1
            continue
        if not arg.startswith("--"):
            raise SystemExit(f"unexpected argument {arg!r}")
        key = arg[2:]
        if key not in by_cli:
            raise SystemExit(
                f"unknown parameter --{key}; known: "
                + ", ".join(sorted(c for c in by_cli if "-" in c or "_" not in c))
            )
        if i + 1 >= len(rest):
            raise SystemExit(f"--{key} requires a value")
        attr, p = by_cli[key]
        params[attr] = p.parse(rest[i + 1])
        i += 2
    missing = [p.name for a, p in specs.items() if p.required and params[a] is None]
    if missing:
        raise SystemExit(f"missing required parameters: {missing}")
    return params, triggered


def _show(flow_cls) -> None:
    print(f"Flow {flow_cls.__name__}")
    doc = (flow_cls.__doc__ or "").strip()
    if doc:
        print(f"  {doc.splitlines()[0]}")
    print("Steps:")
    for name, fn in flow_cls.steps().items():
        tags = []
        if getattr(fn, "__retry_times__", 0):
            tags.append(f"retry×{fn.__retry_times__}")
        if getattr(fn, "__gang__", None):
            tags.append("gang")
        if getattr(fn, "__card__", None):
            tags.append("card")
        print(f"  {name}{(' [' + ', '.join(tags) + ']') if tags else ''}")
    print("Parameters:")
    for attr, p in flow_cls.parameters().items():
        print(f"  --{p.name.replace('_', '-')} (default {p.default!r}) {p.help}")
