"""FlowSpec base class, @step, Parameter, and the ``current`` singleton.

The user-facing authoring surface, shaped like Metaflow's as the reference
uses it (train_flow.py:1-14,20-39; eval_flow.py:1-38): subclass ``FlowSpec``,
mark methods ``@step``, chain with ``self.next(...)`` (optionally
``num_parallel=N`` for gang steps), declare CLI ``Parameter``s as class
attributes, assign ``self.<name>`` for persisted artifacts, and read
``current.*`` for runtime context (run id, storage path, trigger)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable


class Parameter:
    """CLI-exposed flow parameter (↔ metaflow.Parameter,
    train_flow.py:23-35). ``type`` is inferred from ``default`` if omitted."""

    def __init__(
        self,
        name: str,
        *,
        default: Any = None,
        help: str = "",
        type: type | None = None,
        required: bool = False,
    ):
        self.name = name
        self.default = default
        self.help = help
        self.required = required
        if type is not None:
            self.type = type
        elif default is not None:
            self.type = builtins_type(default)
        else:
            self.type = str

    def parse(self, raw: str) -> Any:
        if self.type is bool:
            return raw.lower() in ("1", "true", "yes", "on")
        return self.type(raw)


def builtins_type(v: Any) -> type:
    for t in (bool, int, float, str):
        if isinstance(v, t):
            return t
    return str


def step(fn: Callable) -> Callable:
    """Mark a method as a flow step (↔ @step, train_flow.py:36-95)."""
    fn.__is_step__ = True
    return fn


@dataclasses.dataclass
class _Transition:
    target: str
    num_parallel: int = 1


class _Trigger:
    """``current.trigger`` — set when a run was event-triggered
    (↔ current.trigger.run, eval_flow.py:42)."""

    def __init__(self, run):
        self.run = run


class _Current:
    """Runtime context singleton (↔ metaflow.current; exposes
    ``tpu_storage_path`` the way @metaflow_ray exposes ``ray_storage_path``,
    train_flow.py:65)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.flow_name: str | None = None
        self.run_id: str | None = None
        self.step_name: str | None = None
        self.task_id: int | None = None
        self.tpu_storage_path: str | None = None
        self.trigger: _Trigger | None = None
        self.card = None  # CardBuffer when the step has @card
        self.gang_index: int = 0
        self.gang_size: int = 1

    @property
    def pathspec(self) -> str:
        return f"{self.flow_name}/{self.run_id}/{self.step_name}/{self.task_id}"


current = _Current()


class FlowSpec:
    """Base class for flows. Subclasses define @step methods; execution is
    driven by tpuflow.flow.runner via the generated CLI (``main()``)."""

    def __init__(self):
        self.__dict__["_artifacts"] = {}
        self.__dict__["_next"] = None

    # Artifact capture: plain attribute assignment persists (↔ self.result =
    # ..., train_flow.py:77).
    def __setattr__(self, name: str, value: Any):
        object.__setattr__(self, name, value)
        if not name.startswith("_"):
            self._artifacts[name] = value

    def next(self, target: Callable, *, num_parallel: int = 1) -> None:
        """Declare the next step (↔ self.next(self.train, num_parallel=2),
        train_flow.py:39)."""
        if self._next is not None:
            raise RuntimeError("self.next() called twice in one step")
        name = getattr(target, "__name__", None)
        if name is None or not hasattr(type(self), name):
            raise ValueError(f"next() target must be a step method, got {target!r}")
        object.__setattr__(self, "_next", _Transition(name, num_parallel))

    # ------------------------------------------------------------ class info
    @classmethod
    def parameters(cls) -> dict[str, Parameter]:
        out = {}
        for klass in reversed(cls.__mro__):
            for k, v in vars(klass).items():
                if isinstance(v, Parameter):
                    out[k] = v
        return out

    @classmethod
    def steps(cls) -> dict[str, Callable]:
        out = {}
        for klass in reversed(cls.__mro__):
            for k, v in vars(klass).items():
                if callable(v) and getattr(v, "__is_step__", False):
                    out[k] = v
        return out

    @classmethod
    def main(cls, argv: list[str] | None = None):
        """CLI entry point: ``python flow.py run|show|deploy|trigger ...``."""
        from tpuflow.flow.runner import main as runner_main

        return runner_main(cls, argv)
