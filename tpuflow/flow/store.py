"""Flow datastore: run directories, artifact persistence, run metadata.

Replaces the Metaflow datastore as the reference exercises it: step artifacts
(``self.result = ...`` at train_flow.py:77,87) persisted per task and readable
across processes/flows via the client API (train_flow.py:69-73,
eval_flow.py:45-49). Checkpoint/Result artifacts are stored as JSON
*references* (path + metadata) — never pickled tensors (SURVEY.md §7
hard-part 3); plain JSON types stay JSON; numpy arrays go to .npy; anything
else falls back to pickle.

Layout under ``$TPUFLOW_HOME`` (default ``~/.tpuflow``)::

    flows/<FlowName>/<run_id>/run.json
    flows/<FlowName>/<run_id>/<step>/<task_id>/artifacts.json (+ blobs)
    flows/<FlowName>/<run_id>/tpu_storage/          # checkpoint area (D8)
    events/<flow_name>.jsonl                        # trigger records (D10)
    deployments/<FlowName>.json                     # schedule records (D10)
"""

from __future__ import annotations

import fcntl
import json
import os
import pickle
import time
from typing import Any

import numpy as np

from tpuflow.ckpt import Checkpoint
from tpuflow.utils import FileLock
from tpuflow.utils import knobs


def home() -> str:
    return os.path.abspath(
        knobs.raw("TPUFLOW_HOME", os.path.expanduser("~/.tpuflow"))
    )


def flow_dir(flow: str) -> str:
    return os.path.join(home(), "flows", flow)


def run_dir(flow: str, run_id: str | int) -> str:
    return os.path.join(flow_dir(flow), str(run_id))


def task_dir(flow: str, run_id: str | int, step: str, task_id: int) -> str:
    return os.path.join(run_dir(flow, run_id), step, str(task_id))


def new_run_id(flow: str) -> int:
    """Monotonic per-flow run ids, atomic under concurrent launches."""
    d = flow_dir(flow)
    os.makedirs(d, exist_ok=True)
    with FileLock(os.path.join(d, ".id.lock")):
        path = os.path.join(d, "latest_run_id")
        last = 0
        if os.path.exists(path):
            with open(path) as f:
                last = int(f.read().strip() or 0)
        run_id = last + 1
        with open(path, "w") as f:
            f.write(str(run_id))
    return run_id


def latest_run_id(flow: str) -> int | None:
    path = os.path.join(flow_dir(flow), "latest_run_id")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return int(f.read().strip())


# ------------------------------------------------------------------ metadata
def write_run_meta(flow: str, run_id, meta: dict) -> None:
    d = run_dir(flow, run_id)
    os.makedirs(d, exist_ok=True)
    # Atomic replace: the client reads run.json concurrently (namespace
    # check, latest-successful scans) and must never see a truncated file.
    path = os.path.join(d, "run.json")
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1, default=str)
    os.replace(tmp, path)


def read_run_meta(flow: str, run_id) -> dict:
    with open(os.path.join(run_dir(flow, run_id), "run.json")) as f:
        return json.load(f)


# ----------------------------------------------------------------- artifacts
def reject_device_arrays(name: str, value: Any) -> None:
    """Enforce the never-pickled-tensors artifact contract for jax.Arrays.

    A ``jax.Array`` inside an artifact would silently ship device tensors
    through pickle (cross-process in the gang launcher, cross-run in the
    datastore). Device state travels as ``Checkpoint`` handles — path +
    metadata — the way the reference moves it (train_flow.py:77 →
    eval_flow.py:45-49), so reject the tensor loudly instead.
    """
    import sys

    jax = sys.modules.get("jax")
    if jax is None:  # no jax imported → no jax.Arrays can exist
        return
    for leaf in jax.tree_util.tree_leaves(value):
        if isinstance(leaf, jax.Array) and not isinstance(leaf, np.ndarray):
            raise TypeError(
                f"artifact {name!r} contains a jax.Array "
                f"({getattr(leaf, 'shape', ())}, "
                f"{getattr(leaf, 'dtype', '?')}): device tensors are never "
                "pickled into artifacts — save them through the "
                "CheckpointManager and store the Checkpoint handle, or "
                "convert to numpy explicitly if the value is small host data"
            )


def _encode(name: str, value: Any, blob_dir: str) -> dict:
    from tpuflow.train.trainer import Result

    if isinstance(value, Checkpoint):
        return {"__type__": "checkpoint", **value.to_json()}
    if isinstance(value, Result):
        return {"__type__": "result", "value": value.to_json()}
    reject_device_arrays(name, value)
    if isinstance(value, np.ndarray):
        fname = f"{name}.npy"
        np.save(os.path.join(blob_dir, fname), value)
        return {"__type__": "ndarray", "file": fname}
    try:
        json.dumps(value)
        return {"__type__": "json", "value": value}
    except (TypeError, ValueError):
        fname = f"{name}.pkl"
        with open(os.path.join(blob_dir, fname), "wb") as f:
            pickle.dump(value, f)
        return {"__type__": "pickle", "file": fname}


def _decode(entry: dict, blob_dir: str) -> Any:
    from tpuflow.train.trainer import Result

    t = entry["__type__"]
    if t == "checkpoint":
        return Checkpoint.from_json(entry)
    if t == "result":
        return Result.from_json(entry["value"])
    if t == "ndarray":
        return np.load(os.path.join(blob_dir, entry["file"]))
    if t == "json":
        return entry["value"]
    if t == "pickle":
        with open(os.path.join(blob_dir, entry["file"]), "rb") as f:
            return pickle.load(f)
    raise ValueError(f"unknown artifact type {t!r}")


def save_artifacts(
    flow: str, run_id, step: str, task_id: int, artifacts: dict[str, Any]
) -> None:
    d = task_dir(flow, run_id, step, task_id)
    os.makedirs(d, exist_ok=True)
    encoded = {k: _encode(k, v, d) for k, v in artifacts.items()}
    with open(os.path.join(d, "artifacts.json"), "w") as f:
        json.dump(encoded, f, indent=1)
    # Commit marker, written strictly AFTER artifacts.json and its blobs:
    # a task that died mid-save leaves an unmarked dir, which the
    # store-sourced artifact scan (gang_exec._store_artifacts) ignores —
    # a failed attempt's partial artifacts are never resurrected. The
    # launch attempt (TPUFLOW_ATTEMPT, stamped by the gang launcher) rides
    # along for diagnosis of which attempt produced the bytes.
    marker = {
        "attempt": int(knobs.raw("TPUFLOW_ATTEMPT", "0") or 0),
        "ts": time.time(),
    }
    tmp = os.path.join(d, "artifacts.ok.tmp")
    with open(tmp, "w") as f:
        json.dump(marker, f)
    os.replace(tmp, os.path.join(d, "artifacts.ok"))


def load_artifacts(flow: str, run_id, step: str, task_id: int) -> dict[str, Any]:
    d = task_dir(flow, run_id, step, task_id)
    path = os.path.join(d, "artifacts.json")
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        encoded = json.load(f)
    return {k: _decode(v, d) for k, v in encoded.items()}


# -------------------------------------------------------------------- events
def append_event(event: dict) -> None:
    d = os.path.join(home(), "events")
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{event['flow']}.jsonl")
    line = json.dumps({**event, "ts": time.time()})
    # O_APPEND + flock: concurrent flows may finish simultaneously.
    fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_APPEND)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        os.write(fd, (line + "\n").encode())
    finally:
        os.close(fd)


def read_events(flow: str) -> list[dict]:
    path = os.path.join(home(), "events", f"{flow}.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def write_deployment(flow: str, record: dict) -> str:
    d = os.path.join(home(), "deployments")
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{flow}.json")
    with open(path, "w") as f:
        json.dump(record, f, indent=1)
    return path


def read_deployment(flow: str) -> dict | None:
    path = os.path.join(home(), "deployments", f"{flow}.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)
