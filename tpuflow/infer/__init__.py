"""Batch inference engine (replaces Ray Data map_batches actor inference)
plus autoregressive KV-cache generation for the LM family."""

from tpuflow.infer.beam import beam_search
from tpuflow.infer.engine import (
    BatchPredictor,
    GenerationPredictor,
    map_batches,
)
from tpuflow.infer.generate import generate, pad_ragged, render_tokens
from tpuflow.infer.quant import (
    QuantDecision,
    QuantizedModel,
    dequantize_params,
    maybe_quantize,
    quant_decision,
    quantize_model,
    quantize_params,
    teacher_forced_agreement,
)
from tpuflow.infer.score import best_of_n, sequence_logprob
from tpuflow.infer.serve import ServeEngine, ServeRequest, serve_forever
from tpuflow.infer.speculative import speculative_generate

__all__ = [
    "BatchPredictor",
    "GenerationPredictor",
    "ServeEngine",
    "ServeRequest",
    "serve_forever",
    "QuantDecision",
    "QuantizedModel",
    "beam_search",
    "best_of_n",
    "dequantize_params",
    "generate",
    "map_batches",
    "maybe_quantize",
    "pad_ragged",
    "quant_decision",
    "quantize_model",
    "quantize_params",
    "render_tokens",
    "sequence_logprob",
    "speculative_generate",
    "teacher_forced_agreement",
]
