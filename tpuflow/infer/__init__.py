"""Batch inference engine (replaces Ray Data map_batches actor inference)
plus autoregressive KV-cache generation for the LM family."""

from tpuflow.infer.beam import beam_search
from tpuflow.infer.engine import (
    BatchPredictor,
    GenerationPredictor,
    map_batches,
)
from tpuflow.infer.generate import generate, pad_ragged, render_tokens
from tpuflow.infer.quant import (
    QuantizedModel,
    dequantize_params,
    quantize_model,
    quantize_params,
)
from tpuflow.infer.score import best_of_n, sequence_logprob
from tpuflow.infer.speculative import speculative_generate

__all__ = [
    "BatchPredictor",
    "GenerationPredictor",
    "QuantizedModel",
    "beam_search",
    "best_of_n",
    "dequantize_params",
    "generate",
    "map_batches",
    "pad_ragged",
    "quantize_model",
    "quantize_params",
    "render_tokens",
    "sequence_logprob",
    "speculative_generate",
]
