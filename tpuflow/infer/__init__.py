"""Batch inference engine (replaces Ray Data map_batches actor inference)."""

from tpuflow.infer.engine import BatchPredictor, map_batches

__all__ = ["BatchPredictor", "map_batches"]
