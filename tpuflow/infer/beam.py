"""Beam-search decoding: deterministic width-K search over the KV cache.

The deterministic sibling of ``best_of_n`` (sampling + rerank): at every
step each batch row keeps its K highest-scoring continuations. TPU shape:
beams ride the batch dimension (B*K rows through the same one-program
cached decode as ``generate``), the per-step beam reorder is a gather on
the cache's batch axis, and the whole search — prefill, cache tiling,
scan of (forward, top-k, reorder) steps, backtrack — compiles to ONE XLA
program. The reference has no generation path at all (its predictor is a
single classifier forward, my_ray_module.py:275-284); this completes the
LM inference surface next to sampling (generate), scoring
(sequence_logprob), and reranking (best_of_n).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG = -1e30


def _cache_batch_axis(model) -> int:
    """Axis of the batch dimension in cache leaves: 0 normally, 1 under
    ``scan_layers`` (nn.scan stacks a leading layer axis onto every cache
    variable — a shape heuristic would silently tile the LAYER axis
    whenever n_layer happened to equal the batch size)."""
    return 1 if getattr(model.config, "scan_layers", False) else 0


def _tile_cache(cache, k: int, batch: int, axis: int):
    """Repeat cache leaves K-fold along the batch axis (B -> B*K); leaves
    without that axis (scalar/per-layer indices) pass through untouched."""
    return jax.tree_util.tree_map(
        lambda c: jnp.repeat(c, k, axis=axis)
        if c.ndim > axis and c.shape[axis] == batch
        else c,
        cache,
    )


def _gather_beams(cache, flat_parent, rows: int, axis: int):
    """Reorder cache rows to the chosen parents (beam switch)."""
    return jax.tree_util.tree_map(
        lambda c: jnp.take(c, flat_parent, axis=axis)
        if c.ndim > axis and c.shape[axis] == rows
        else c,
        cache,
    )


@functools.partial(
    jax.jit,
    static_argnums=(0,),
    static_argnames=("beam_size", "max_new_tokens", "eos_id", "pad_id",
                     "prefill_chunk"),
)
def _beam_jit(
    model,
    params,
    prompt,
    pad_lens=None,
    *,
    beam_size: int,
    max_new_tokens: int,
    eos_id: int | None,
    pad_id: int,
    length_penalty: float = 1.0,
    prefill_chunk: int | None = None,
):
    B, T = prompt.shape
    K = beam_size

    # Prefill ONCE at width B (one shot or chunked — generate's memory
    # knob), then tile the cache K-fold — K x cheaper than prefilling
    # B*K identical prompts.
    from tpuflow.infer.generate import chunked_prefill

    logits, prefill_cache = chunked_prefill(
        model, params, prompt, prefill_chunk, pad_lens=pad_lens
    )
    axis = _cache_batch_axis(model)
    cache = _tile_cache(prefill_cache, K, B, axis)
    tiled_pad_lens = (
        jnp.repeat(pad_lens, K, axis=0) if pad_lens is not None else None
    )

    logprobs = jax.nn.log_softmax(logits[:, -1, :].astype(jnp.float32))
    V = logprobs.shape[-1]
    # Step 0: the top-K first tokens seed the beams.
    scores, tok0 = jax.lax.top_k(logprobs, K)          # (B, K)
    tok0 = tok0.astype(jnp.int32)
    done = (tok0 == eos_id) if eos_id is not None else jnp.zeros((B, K), bool)
    lengths = jnp.ones((B, K), jnp.int32)

    def step(carry, _):
        cache, tok, scores, done, lengths = carry
        logits, vars_out = model.apply(
            {"params": params, "cache": cache},
            tok.reshape(B * K)[:, None],
            decode=True,
            mutable=["cache"],
            pad_lens=tiled_pad_lens,
        )
        cache = vars_out["cache"]
        lp = jax.nn.log_softmax(
            logits[:, -1, :].astype(jnp.float32)
        ).reshape(B, K, V)
        # Finished beams extend ONLY with pad at zero cost — they keep
        # their score and stay comparable against live beams.
        if eos_id is not None:
            frozen = jnp.full((V,), _NEG).at[pad_id].set(0.0)
            lp = jnp.where(done[..., None], frozen[None, None, :], lp)
        total = scores[..., None] + lp                  # (B, K, V)
        flat = total.reshape(B, K * V)
        scores, idx = jax.lax.top_k(flat, K)            # (B, K)
        parent = (idx // V).astype(jnp.int32)
        token = (idx % V).astype(jnp.int32)
        flat_parent = (
            jnp.arange(B, dtype=jnp.int32)[:, None] * K + parent
        ).reshape(-1)
        cache = _gather_beams(cache, flat_parent, B * K, axis)
        done = jnp.take_along_axis(done, parent, axis=1)
        lengths = jnp.take_along_axis(lengths, parent, axis=1) + jnp.where(
            done, 0, 1
        )
        if eos_id is not None:
            done = done | (token == eos_id)
        token = jnp.where(done & (token != eos_id), pad_id, token)
        return (cache, token, scores, done, lengths), (parent, token)

    if max_new_tokens > 1:
        (cache, tok, scores, done, lengths), (parents, tokens) = jax.lax.scan(
            step,
            (cache, tok0, scores, done, lengths),
            None,
            length=max_new_tokens - 1,
        )
        # Backtrack: follow each surviving beam's parent chain from the
        # last step to the first (reverse scan), then prepend step 0.
        def back(beam_idx, y):
            parent, token = y
            t = jnp.take_along_axis(token, beam_idx, axis=1)
            return jnp.take_along_axis(parent, beam_idx, axis=1), t

        root, toks_rev = jax.lax.scan(
            back,
            jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32), (B, K)),
            (parents, tokens),
            reverse=True,
        )
        first = jnp.take_along_axis(tok0, root, axis=1)  # (B, K)
        seqs = jnp.concatenate(
            [first[None], toks_rev], axis=0
        )  # (M, B, K)
        seqs = jnp.moveaxis(seqs, 0, 2)  # (B, K, M)
    else:
        seqs = tok0[..., None]

    # Rank by length-normalized score (GNMT-style penalty; 1.0 = plain
    # mean-free total logprob over real tokens).
    norm = jnp.power(lengths.astype(jnp.float32), length_penalty)
    ranked = scores / jnp.maximum(norm, 1.0)
    best = jnp.argmax(ranked, axis=1)
    rows = jnp.arange(B)
    return (
        seqs[rows, best],            # (B, max_new_tokens)
        ranked[rows, best],          # (B,)
        seqs,                        # (B, K, M) all beams
        ranked,                      # (B, K)
    )


def beam_search(
    model,
    params,
    prompt,
    *,
    beam_size: int,
    max_new_tokens: int,
    eos_id: int | None = None,
    pad_id: int = 0,
    length_penalty: float = 1.0,
    prompt_lens=None,
    return_all: bool = False,
    prefill_chunk: int | None = None,
):
    """Deterministic beam-search continuation of ``prompt`` (B, T) int32.

    Returns ``(tokens (B, max_new_tokens), scores (B,))`` — the best beam
    per row under a GNMT-style length penalty (``scores`` are total token
    logprob / length**penalty; eos-frozen tails contribute nothing) — or,
    with ``return_all``, ``(tokens, scores, all_tokens (B, K, M),
    all_scores (B, K))``. ``beam_size=1`` equals greedy decoding exactly.
    Ragged prompts ride ``prompt_lens`` exactly as in ``generate``, and
    ``prefill_chunk`` streams long prompts into the cache in fixed
    slices (the same memory bound as ``generate``'s knob).
    """
    from tpuflow.infer.generate import (
        check_cache_capacity,
        normalize_prefill_chunk,
        prompt_lens_to_pad_lens,
    )

    prompt = jnp.asarray(prompt, jnp.int32)
    B, T = prompt.shape
    if beam_size < 1:
        raise ValueError(f"beam_size must be >= 1, got {beam_size}")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if length_penalty < 0:
        raise ValueError(
            f"length_penalty must be >= 0, got {length_penalty} (negative "
            "penalties would be silently neutralized by the norm clamp)"
        )
    check_cache_capacity(model, T, max_new_tokens)
    prefill_chunk = normalize_prefill_chunk(prefill_chunk, T)
    pad_lens = prompt_lens_to_pad_lens(prompt_lens, B, T)
    best, best_scores, all_seqs, all_scores = _beam_jit(
        model,
        params,
        prompt,
        pad_lens,
        beam_size=beam_size,
        max_new_tokens=max_new_tokens,
        eos_id=eos_id,
        pad_id=pad_id,
        length_penalty=length_penalty,
        prefill_chunk=prefill_chunk,
    )
    if return_all:
        return best, best_scores, all_seqs, all_scores
    return best, best_scores
