"""Batched inference over row datasets with a stateful jitted predictor.

Replaces the reference's Ray Data pipeline (eval_flow.py:78-91 +
my_ray_module.py:266-284): ``ray.data.from_items(rows).map_batches(
TorchPredictor(checkpoint), batch_size=512, concurrency=1, num_gpus=1)`` —
a stateful actor that loads weights once, then streams batches through
``inference_mode`` forward + argmax.

TPU shape: ``BatchPredictor`` loads weights once (from a flow Checkpoint
handle) and jits the forward; ``map_batches`` feeds fixed-size batches —
padding the ragged tail and trimming after — so XLA compiles exactly one
program (SURVEY.md §7 hard-part 5); the batch is sharded over the mesh's
data axis, which is the actor-pool parallelism of the original expressed as
SPMD. Returns per-row dicts, so downstream assembly (the eval flow's
dataframe join, eval_flow.py:91) is index-aligned with the input rows.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import numpy as np

from tpuflow import dist
from tpuflow.ckpt import Checkpoint, restore_from_handle


class BatchPredictor:
    """Stateful predictor: weights loaded once, jitted forward per batch.

    ↔ TorchPredictor (my_ray_module.py:266-284): ``__init__`` loads best
    weights from the checkpoint; ``__call__`` squeezes accidental
    ``(1,B,...)`` leading dims, runs a no-grad forward, and returns
    ``{"logits": float32, "predicted_values": argmax}``.
    """

    def __init__(self, model, params, *, mesh=None):
        self.model = model
        self.params = params
        self.mesh = mesh if mesh is not None else dist.make_mesh()
        self._forward = jax.jit(
            lambda params, x: model.apply({"params": params}, x, train=False)
        )

    @classmethod
    def from_checkpoint(
        cls,
        checkpoint: Checkpoint,
        model,
        *,
        sample_input=None,
        mesh=None,
        zero_copy: bool = False,
    ) -> "BatchPredictor":
        """Load weights once at construction (↔ my_ray_module.py:268-273,
        which restores best_model.pt in TorchPredictor.__init__).

        When ``sample_input`` is given, params are restored against an
        abstract tree derived from the model (replicated on the current
        mesh), so a checkpoint written on any training topology loads on the
        inference topology.

        ``zero_copy=True`` makes the weights alias the mapped shard files
        (predictor startup skips the full read copy; pages stream in on
        first use). Only safe when no other process may still be writing or
        recycling the producing run's checkpoint directory — i.e. the run
        is finished (see raw.restore_raw); the eval flow enables it after
        checking the producing run succeeded.
        """
        mesh = mesh if mesh is not None else dist.make_mesh()
        abstract = None
        if sample_input is not None:
            shapes = jax.eval_shape(
                model.init, jax.random.PRNGKey(0), sample_input
            )["params"]
            sharding = dist.replicated(mesh)
            abstract = jax.tree_util.tree_map(
                lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sharding),
                shapes,
            )
        params = restore_from_handle(
            checkpoint, weights_only=True, abstract_state=abstract,
            zero_copy=zero_copy,
        )
        return cls(model, params, mesh=mesh)

    def __call__(self, batch: dict) -> dict:
        x = np.asarray(batch["features"])
        # Squeeze an accidental leading batch-of-batches dim (parity:
        # my_ray_module.py:276-278 squeezes (1,B,1,28,28)).
        while x.ndim > 0 and x.shape[0] == 1 and x.ndim > 3:
            x = x[0]
        placed = dist.shard_batch({"x": x}, self.mesh)
        logits = self._forward(self.params, placed["x"])
        logits = np.asarray(logits, dtype=np.float32)
        return {
            "logits": logits,
            "predicted_values": logits.argmax(axis=-1),
        }


def map_batches(
    rows: Sequence[dict],
    predictor: Callable[[dict], dict],
    *,
    batch_size: int = 512,
) -> list[dict]:
    """Run ``predictor`` over ``rows`` in fixed-size batches; return one output
    row per input row, in order (↔ ds.map_batches(...).take_all(),
    eval_flow.py:85-90).

    The final ragged batch is padded up to ``batch_size`` by repeating its
    last row, then the outputs are trimmed — the jitted forward sees a single
    static shape.
    """
    rows = list(rows)
    if not rows:
        return []
    keys = rows[0].keys()
    out_rows: list[dict] = []
    for start in range(0, len(rows), batch_size):
        chunk = rows[start : start + batch_size]
        n = len(chunk)
        if n < batch_size:
            chunk = chunk + [chunk[-1]] * (batch_size - n)
        batch = {k: np.stack([np.asarray(r[k]) for r in chunk]) for k in keys}
        out = predictor(batch)
        for i in range(n):
            out_rows.append({k: np.asarray(v)[i] for k, v in out.items()})
    return out_rows
