"""Batched inference over row datasets with a stateful jitted predictor.

Replaces the reference's Ray Data pipeline (eval_flow.py:78-91 +
my_ray_module.py:266-284): ``ray.data.from_items(rows).map_batches(
TorchPredictor(checkpoint), batch_size=512, concurrency=1, num_gpus=1)`` —
a stateful actor that loads weights once, then streams batches through
``inference_mode`` forward + argmax.

TPU shape: ``BatchPredictor`` loads weights once (from a flow Checkpoint
handle) and jits the forward; ``map_batches`` feeds fixed-size batches —
padding the ragged tail and trimming after — so XLA compiles exactly one
program (SURVEY.md §7 hard-part 5); the batch is sharded over the mesh's
data axis, which is the actor-pool parallelism of the original expressed as
SPMD. Returns per-row dicts, so downstream assembly (the eval flow's
dataframe join, eval_flow.py:91) is index-aligned with the input rows.
"""

from __future__ import annotations

import os
from typing import Callable, Sequence

import jax
import numpy as np

from tpuflow import dist, obs
from tpuflow.ckpt import Checkpoint, restore_from_handle
from tpuflow.utils import knobs


class BatchPredictor:
    """Stateful predictor: weights loaded once, jitted forward per batch.

    ↔ TorchPredictor (my_ray_module.py:266-284): ``__init__`` loads best
    weights from the checkpoint; ``__call__`` squeezes accidental
    ``(1,B,...)`` leading dims, runs a no-grad forward, and returns
    ``{"logits": float32, "predicted_values": argmax}``.
    """

    def __init__(self, model, params, *, batch_stats=None, mesh=None):
        self.model = model
        self.params = params
        self.batch_stats = batch_stats or None
        self.mesh = mesh if mesh is not None else dist.make_mesh()

        def fwd(params, batch_stats, x):
            variables = {"params": params}
            if batch_stats is not None:
                # BatchNorm models infer with their RUNNING statistics
                # (train=False selects them); without the collection the
                # apply would fail — see from_checkpoint's subtree restore.
                variables["batch_stats"] = batch_stats
            return model.apply(variables, x, train=False)

        self._forward = jax.jit(fwd)

    @classmethod
    def from_checkpoint(
        cls,
        checkpoint: Checkpoint,
        model,
        *,
        sample_input=None,
        mesh=None,
        zero_copy: bool = False,
    ) -> "BatchPredictor":
        """Load weights once at construction (↔ my_ray_module.py:268-273,
        which restores best_model.pt in TorchPredictor.__init__).

        When ``sample_input`` is given, params are restored against an
        abstract tree derived from the model (replicated on the current
        mesh), so a checkpoint written on any training topology loads on the
        inference topology.

        ``zero_copy=True`` makes the weights alias the mapped shard files
        (predictor startup skips the full read copy; pages stream in on
        first use). Only safe when no other process may still be writing or
        recycling the producing run's checkpoint directory — i.e. the run
        is finished (see raw.restore_raw); the eval flow enables it after
        checking the producing run succeeded.
        """
        mesh = mesh if mesh is not None else dist.make_mesh()
        abstract = None
        abstract_stats = None
        var_shapes = None
        if sample_input is not None:
            var_shapes = jax.eval_shape(
                model.init, jax.random.PRNGKey(0), sample_input
            )
            sharding = dist.replicated(mesh)

            def _abs(s):
                return jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sharding)

            abstract = jax.tree_util.tree_map(_abs, var_shapes["params"])
            if var_shapes.get("batch_stats"):
                abstract_stats = jax.tree_util.tree_map(
                    _abs, var_shapes["batch_stats"]
                )
        params = restore_from_handle(
            checkpoint, weights_only=True, abstract_state=abstract,
            zero_copy=zero_copy,
        )
        # BatchNorm running statistics live beside the weights in the
        # checkpoint (my_tpu_module._state_tree); restore them when the
        # model has the collection. A KeyError = the checkpoint carries no
        # batch_stats subtree: fatal when the model is KNOWN to need it
        # (inference without running stats would fail later, worse-labeled,
        # inside model.apply), tolerated only when no sample_input told us
        # the model's collections. Other errors (format, corruption)
        # propagate untouched.
        batch_stats = None
        if var_shapes is None or var_shapes.get("batch_stats"):
            try:
                batch_stats = restore_from_handle(
                    checkpoint, subtree=("batch_stats",),
                    abstract_state=abstract_stats, zero_copy=zero_copy,
                )
            except KeyError:
                if var_shapes is not None:
                    raise KeyError(
                        "model has a batch_stats collection (BatchNorm) but "
                        f"checkpoint {checkpoint.path} carries no "
                        "batch_stats subtree — it cannot serve inference"
                    ) from None
        return cls(model, params, batch_stats=batch_stats, mesh=mesh)

    def __call__(self, batch: dict) -> dict:
        x = np.asarray(batch["features"])
        # Squeeze an accidental leading batch-of-batches dim (parity:
        # my_ray_module.py:276-278 squeezes (1,B,1,28,28)).
        while x.ndim > 0 and x.shape[0] == 1 and x.ndim > 3:
            x = x[0]
        with obs.span("infer.predict", rows=int(x.shape[0])):
            placed = dist.shard_batch({"x": x}, self.mesh)
            logits = self._forward(self.params, self.batch_stats, placed["x"])
            # np.asarray materializes the result, so the span closes on an
            # honest wall time.
            logits = np.asarray(logits, dtype=np.float32)
        return {
            "logits": logits,
            "predicted_values": logits.argmax(axis=-1),
        }


class GenerationPredictor:
    """Stateful LM generation actor for ``map_batches``: weights loaded
    once, each batch of (possibly ragged) prompt rows decodes in ONE
    KV-cache program (tpuflow.infer.generate with ``prompt_lens``).

    The LM-family completion of the engine parity: the reference's
    ``map_batches`` takes ragged rows (eval_flow.py:85-90) because Ray
    moves Python objects; under XLA the raggedness is absorbed here by
    left-pad + mask, token-exactly (pinned against per-row dense calls).

    ``pad_to`` fixes the padded prompt width across batches so XLA
    compiles one program for the whole stream; default pads each batch to
    its own max length (one compile per distinct width).
    """

    def __init__(
        self,
        model,
        params,
        *,
        max_new_tokens: int,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        eos_id: int | None = None,
        pad_id: int = 0,
        pad_to: int | None = None,
        rng=None,
        quantize: str | None = None,
        speculative: bool = False,
        draft_len: int = 8,
        ngram: int = 3,
        prefill_chunk: int | None = None,
    ):
        self.quant_decision = None
        if quantize is not None:
            # Explicit modes are FORCED — 'int8' (weight-only at rest; a
            # memory-capacity ask the throughput gate must not override)
            # and 'int8-native' (fused-native W8A8: dynamic activation
            # quantization + int8 MXU matmuls + int8 LM head through
            # tpuflow.ops.int8_matmul; 'int8-mxu' is the pre-ISSUE-9
            # spelling of the same path). 'auto' delegates to the
            # measured policy (quant_decision): weight-only only above
            # the size threshold where it pays (0.76x vs fp at 124M/b8
            # on chip, r4), fp otherwise. The verdict lands on
            # ``self.quant_decision`` either way; the wrapper is a
            # drop-in static model, everything below is unchanged —
            # including the shared-ServeEngine route, which decodes the
            # quantized model through the same persistent slot programs.
            from tpuflow.infer.quant import (
                maybe_quantize,
                quant_decision,
                quantize_model,
            )

            modes = {
                "int8": "weight",
                "int8-mxu": "mxu",
                "int8-native": "mxu",
            }
            if quantize == "auto":
                model, params, self.quant_decision = maybe_quantize(
                    model, params, mode="weight"
                )
            elif quantize in modes:
                # Advisory verdict on the ORIGINAL float tree (after
                # quantization the byte count would be meaningless), then
                # quantize unconditionally — the user asked.
                self.quant_decision = quant_decision(
                    params, mode=modes[quantize]
                )
                model, params = quantize_model(
                    model, params, mode=modes[quantize]
                )
            else:
                raise ValueError(
                    f"unknown quantize mode {quantize!r}; supported: "
                    f"{sorted(modes) + ['auto']}"
                )
        self.model = model
        self.params = params
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self.pad_id = pad_id
        self.pad_to = pad_to
        # Speculative (prompt-lookup) decoding for the engine surface:
        # greedy-only by construction — stochastic sampling would need
        # acceptance-rejection the drafter doesn't implement, so an
        # incompatible ask fails loudly here rather than silently
        # degrading per batch.
        if speculative and temperature != 0.0:
            raise ValueError(
                "speculative=True requires temperature=0.0 (greedy): "
                "prompt-lookup speculation is token-exact greedy decoding"
            )
        if speculative and pad_to is not None:
            # pad_to left-pads narrower batches, and the speculative path
            # is dense-only — every padded batch would silently fall back
            # to plain generate, so the combination is refused outright.
            raise ValueError(
                "speculative=True is incompatible with pad_to: padded "
                "batches are LEFT-padded and speculation is dense-only"
            )
        if speculative and draft_len < 1:
            raise ValueError(f"draft_len must be >= 1, got {draft_len}")
        if speculative and ngram < 2:
            raise ValueError(f"ngram must be >= 2, got {ngram}")
        self.speculative = speculative
        self.draft_len = draft_len
        self.ngram = ngram
        # Continuous-batching route (ISSUE 8): from the SECOND batch on,
        # greedy non-speculative streams decode through one shared
        # ServeEngine — per-request bucketed prefill into a persistent
        # slot-based decode program — so a stream of varying batch shapes
        # stops paying one compile per shape. The first batch keeps the
        # legacy path (a single batch gains nothing from engine warmup).
        # TPUFLOW_SERVE=0 opts out; pad_to keeps the legacy single-program
        # contract it already guarantees; sampling/speculation are
        # engine-incompatible (greedy-exactness is the serving contract).
        self._serve_engine = None
        self._batches_seen = 0
        # Long-prompt memory bound, passed through to every decode entry
        # point (generate and the speculative fast path alike). Same
        # fail-loudly-at-construction contract as the knobs above.
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}"
            )
        self.prefill_chunk = prefill_chunk
        # Advanced per __call__ (split): batches sample independently; the
        # same construction-time seed still reproduces the whole stream.
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)

    @classmethod
    def from_checkpoint(
        cls, checkpoint: Checkpoint, model, *, subtree=None,
        zero_copy: bool = False, **kw,
    ) -> "GenerationPredictor":
        """Weights-only restore at construction (↔ the stateful-actor
        load-once semantics, my_ray_module.py:268-273); ``subtree``
        selects e.g. ``("ema_params",)``."""
        params = restore_from_handle(
            checkpoint, weights_only=True, subtree=subtree,
            zero_copy=zero_copy,
        )
        return cls(model, params, **kw)

    def _serve_batch(self, prompt, lens) -> "np.ndarray | None":
        """Decode one (possibly LEFT-padded) batch through the shared
        continuous-batching engine: each row becomes a request, outputs
        re-assemble into the exact ``generate()`` contract — eos emitted,
        remaining positions frozen to ``pad_id`` (greedy engine tokens are
        bit-identical to the legacy path, pinned by tests/test_serve.py).
        Returns None when a row doesn't fit the engine's bucket capacity
        (bucket pads eat cache columns the dense batch wouldn't) — the
        caller falls back to the legacy per-batch program."""
        from tpuflow.infer.serve import ServeEngine

        if self._serve_engine is None:
            # quant=False explicitly: the predictor already applied its
            # own quantize= policy to model/params, so the engine must
            # not ALSO arm per-request int8 from TPUFLOW_SERVE_QUANT —
            # it would double-quantize (and refuse the wrapped model).
            engine = ServeEngine(
                self.model,
                self.params,
                prefill_chunk=self.prefill_chunk,
                pad_id=self.pad_id,
                quant=False,
            )
            engine.warmup()
            self._serve_engine = engine
        engine = self._serve_engine
        B, W = prompt.shape
        rows = [
            np.asarray(prompt[i, W - (W if lens is None else int(lens[i])):])
            for i in range(B)
        ]
        try:
            for row in rows:
                engine.bucket_for(row.size, self.max_new_tokens)
        except ValueError:
            return None
        with obs.span(
            "infer.generate_batch", rows=B,
            new_tokens=self.max_new_tokens, speculative=False, serve=True,
        ):
            outs = engine.generate_many(
                rows, max_new_tokens=self.max_new_tokens,
                eos_id=self.eos_id,
            )
            full = np.full((B, self.max_new_tokens), self.pad_id, np.int32)
            for i, toks in enumerate(outs):
                full[i, : toks.size] = toks
        return full

    def __call__(self, batch: dict) -> dict:
        from tpuflow.infer.generate import generate, pad_ragged

        tokens = batch["tokens"]
        if isinstance(tokens, np.ndarray) and tokens.ndim == 2:
            # A batch whose rows HAPPEN to be equal-length still honors
            # pad_to below (lens = full width per row), so the stream-wide
            # single-program contract holds for it too.
            prompt = tokens.astype(np.int32)
            lens = None
        else:
            prompt, lens = pad_ragged(tokens, pad_id=self.pad_id)
        if self.pad_to is not None:
            if prompt.shape[1] > self.pad_to:
                raise ValueError(
                    f"a prompt of length {prompt.shape[1]} exceeds "
                    f"pad_to={self.pad_to}"
                )
            extra = self.pad_to - prompt.shape[1]
            if extra:
                if lens is None:
                    lens = np.full(
                        (prompt.shape[0],), prompt.shape[1], np.int32
                    )
                prompt = np.concatenate(
                    [np.full((prompt.shape[0], extra), self.pad_id, np.int32),
                     prompt],
                    axis=1,
                )
        self._rng, sub = jax.random.split(self._rng)
        if lens is not None and bool((lens == prompt.shape[1]).all()):
            # Rows that HAPPEN to be equal-length arrived as lists: no row
            # was actually padded, so drop the lens and take the dense
            # program (faster attention masks; enables speculation).
            lens = None
        self._batches_seen += 1
        if (
            self._batches_seen > 1
            and self.temperature == 0.0
            and not self.speculative
            and self.pad_to is None
            and knobs.raw("TPUFLOW_SERVE", "1") != "0"
        ):
            out = self._serve_batch(prompt, lens)
            if out is not None:
                return {"generated": out}
        if (
            self.speculative
            and lens is None
            and prompt.shape[1] >= self.ngram - 1
            # The uniform advance can overshoot by draft_len+1 — the spec
            # path needs that slack in n_ctx where plain generate doesn't.
            and prompt.shape[1] + self.max_new_tokens + self.draft_len + 1
            <= getattr(self.model.config, "n_ctx", 1 << 30)
        ):
            # Dense equal-length greedy batch: the speculative fast path
            # (token-exact vs generate — decode numerics are
            # width-independent, GPT2Config.decode_dtype). Ragged batches
            # and sub-ngram prompts fall through to plain generate, which
            # produces the identical token stream.
            from tpuflow.infer.speculative import speculative_generate

            obs_on = obs.enabled()
            with obs.span(
                "infer.generate_batch", rows=int(prompt.shape[0]),
                new_tokens=self.max_new_tokens, speculative=True,
            ):
                out = speculative_generate(
                    self.model,
                    self.params,
                    prompt,
                    max_new_tokens=self.max_new_tokens,
                    draft_len=self.draft_len,
                    ngram=self.ngram,
                    eos_id=self.eos_id,
                    pad_id=self.pad_id,
                    prefill_chunk=self.prefill_chunk,
                    # Telemetry wants the realized acceptance rate; the
                    # extra jit variant (with_stats is a static arg) is
                    # only ever compiled when obs is on.
                    return_stats=obs_on,
                )
                if obs_on:
                    out, stats = out
                    n_fwd = int(stats["n_forwards"])
                    n_com = int(stats["n_committed"])
                    obs.counter("infer.spec.forwards", n_fwd)
                    obs.counter("infer.spec.committed", n_com)
                    if n_fwd:
                        obs.gauge("infer.spec.acceptance", n_com / n_fwd)
                out = np.asarray(out, np.int32)
            return {"generated": out}
        with obs.span(
            "infer.generate_batch", rows=int(prompt.shape[0]),
            new_tokens=self.max_new_tokens, speculative=False,
        ):
            out = generate(
                self.model,
                self.params,
                prompt,
                prompt_lens=lens,
                max_new_tokens=self.max_new_tokens,
                temperature=self.temperature,
                top_k=self.top_k,
                top_p=self.top_p,
                eos_id=self.eos_id,
                pad_id=self.pad_id,
                rng=sub,
                prefill_chunk=self.prefill_chunk,
            )
            out = np.asarray(out, np.int32)
        return {"generated": out}


def _collate(vals: list) -> object:
    """Stack same-shape row values into one array; keep ragged values as a
    list (a ragged-aware predictor — GenerationPredictor — left-pads)."""
    arrays = [np.asarray(v) for v in vals]
    if len({a.shape for a in arrays}) == 1:
        return np.stack(arrays)
    return arrays


def map_batches(
    rows: Sequence[dict],
    predictor: Callable[[dict], dict],
    *,
    batch_size: int = 512,
    prefetch: bool = True,
) -> list[dict]:
    """Run ``predictor`` over ``rows`` in fixed-size batches; return one output
    row per input row, in order (↔ ds.map_batches(...).take_all(),
    eval_flow.py:85-90).

    The final ragged batch is padded up to ``batch_size`` by repeating its
    last row, then the outputs are trimmed — the jitted forward sees a single
    static shape. Rows whose values differ in shape (ragged token prompts)
    are passed to the predictor as lists instead of stacked arrays.

    ``prefetch`` double-buffers batch assembly on a background thread: the
    host-side stack/pad of batch N+1 overlaps the device execution of
    batch N (the actor-pool pipelining of the original, expressed as one
    producer thread; jitted predictors release the GIL while the device
    runs).
    """
    rows = list(rows)
    if not rows:
        return []
    keys = rows[0].keys()

    def make_batch(start: int):
        chunk = rows[start : start + batch_size]
        n = len(chunk)
        if n < batch_size:
            chunk = chunk + [chunk[-1]] * (batch_size - n)
        return n, {k: _collate([r[k] for r in chunk]) for k in keys}

    starts = range(0, len(rows), batch_size)
    out_rows: list[dict] = []
    if prefetch and len(starts) > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=1) as ex:
            pending = ex.submit(make_batch, starts[0])
            for i, _ in enumerate(starts):
                n, batch = pending.result()
                if i + 1 < len(starts):
                    pending = ex.submit(make_batch, starts[i + 1])
                out = predictor(batch)
                for r in range(n):
                    out_rows.append(
                        {k: np.asarray(v)[r] for k, v in out.items()}
                    )
        return out_rows
    for start in starts:
        n, batch = make_batch(start)
        out = predictor(batch)
        for i in range(n):
            out_rows.append({k: np.asarray(v)[i] for k, v in out.items()})
    return out_rows
