"""HTTP faces of the front-door router (ISSUE 17).

Two small servers and the forwarder that connects them:

- ``ReplicaGateway`` runs BESIDE a ServeEngine in each replica process:
  ``POST /generate`` submits into the engine's continuous-batching
  queue (under the step-loop's lock) and holds the connection until
  the request finishes. It is idempotent by request id — a duplicate
  of an in-flight id attaches to the existing handle instead of
  submitting twice, and a duplicate of a finished id replays the
  cached answer — which is what makes the router's re-dispatch safe
  when a retry races a slow original. A draining or drained replica
  answers 503 with a reason the router treats as "go elsewhere".
- ``FrontDoor`` is the client-facing ingress: ``POST /generate`` runs
  ``Router.route`` (admission → pick → forward → bounded retry) and
  maps its outcomes onto HTTP — 200 with the replica's answer,
  503 on ``FleetBusy`` (queue timeout / retries exhausted), 400 on a
  malformed request. ``GET /status`` serves ``router_*`` stats (the
  alert engine's reroute_spike feed).
- ``http_forward`` is the Router's default ``forward_fn``: one POST to
  the replica row's ``generate_url`` with a hard timeout, raising on
  anything but a 200 — the router's retry loop is built on exactly
  that contract.

Everything is stdlib http.server + urllib: jax-free, import-safe on a
CPU-only host, and the same code path the in-process chaos harness
drives in tests.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any
from urllib import error as urlerror
from urllib import request as urlrequest

import numpy as np

from tpuflow.infer.router import FleetBusy, Router
from tpuflow.obs import trace as _reqtrace
from tpuflow.utils import knobs

_RESULT_CACHE_MAX = 2048


def _read_json(handler: BaseHTTPRequestHandler) -> dict | None:
    try:
        n = int(handler.headers.get("Content-Length") or 0)
        body = handler.rfile.read(n) if n > 0 else b""
        obj = json.loads(body.decode("utf-8") or "{}")
        return obj if isinstance(obj, dict) else None
    except (ValueError, OSError):
        return None


def _send_json(
    handler: BaseHTTPRequestHandler, code: int, payload: dict
) -> None:
    body = json.dumps(payload).encode("utf-8")
    try:
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
    except (BrokenPipeError, ConnectionResetError, OSError):
        pass  # client gave up; the engine-side work is unaffected


# ------------------------------------------------------ replica gateway
class ReplicaGateway:
    """The replica-side /generate endpoint over a live ServeEngine.

    ``lock`` must be the SAME lock the replica's step loop holds while
    stepping — submit and step interleave safely through it. The
    gateway never steps the engine itself; it submits and polls the
    handle, so a stalled step loop shows up to the router as a forward
    timeout, not a crash.
    """

    def __init__(
        self,
        engine: Any,
        *,
        lock: threading.RLock | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        hold_timeout_s: float = 60.0,
        poll_s: float = 0.005,
        on_complete=None,
    ):
        self.engine = engine
        self.lock = lock if lock is not None else threading.RLock()
        self.hold_timeout_s = float(hold_timeout_s)
        self.poll_s = float(poll_s)
        # Called (under the lock) with each finished handle — the
        # replica's chance to feed its ledger (TTFT histogram,
        # completion counter) without the gateway knowing about obs.
        self.on_complete = on_complete
        self.draining = False
        # Set by a chaos kill (or a dying process): every held and new
        # request answers 503 immediately so the router's re-dispatch
        # fires at once instead of waiting out the forward timeout.
        self.aborted = False
        self._handles: dict[str, Any] = {}
        self._results: OrderedDict[str, dict] = OrderedDict()
        gateway = self

        class _Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 (http.server API)
                if self.path != "/generate":
                    _send_json(self, 404, {"error": "not found"})
                    return
                body = _read_json(self)
                if body is None:
                    _send_json(self, 400, {"error": "bad json"})
                    return
                try:
                    code, payload = gateway.handle_generate(
                        body,
                        traceparent=self.headers.get("traceparent"),
                    )
                except Exception as e:  # noqa: BLE001 — a raised
                    # forward is "try another replica" to the router;
                    # an explicit 500 beats a severed connection.
                    code, payload = 500, {
                        "error": f"{type(e).__name__}: {e}"
                    }
                _send_json(self, code, payload)

            def log_message(self, *args):  # silence request spam
                pass

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="tpuflow-replica-gateway",
            daemon=True,
        )
        self._thread.start()
        h, p = self._server.server_address[:2]
        self.url = f"http://{h}:{p}/generate"

    # ------------------------------------------------------- handling
    def handle_generate(
        self, body: dict, traceparent: str | None = None
    ) -> tuple[int, dict]:
        """Replica hop of the end-to-end trace (ISSUE 18): rebuild the
        context from the propagated ``traceparent`` header, record the
        gateway hold (and any error outcome) as spans parented to the
        forward attempt that carried the request, and flush to this
        replica's trace JSONL. Untraced requests skip all of it on one
        ``is not None`` check."""
        rid = str(body.get("id") or "")
        ctx = _reqtrace.from_traceparent(traceparent, rid)
        t0 = time.time()
        code, payload = self._handle_generate(body, rid, ctx)
        if ctx is not None:
            if code != 200:
                # Tail sampling: killed / draining / hold-timeout /
                # malformed outcomes always record.
                ctx.escalate("error")
            ctx.add_span(
                "gateway.hold",
                ts=t0,
                dur_s=time.time() - t0,
                parent=ctx.root_id,
                status=code,
            )
            _reqtrace.flush(ctx)
        return code, payload

    def _handle_generate(
        self, body: dict, rid: str, ctx: Any = None
    ) -> tuple[int, dict]:
        prompt = body.get("prompt")
        if not rid or not isinstance(prompt, list) or not prompt:
            return 400, {"error": "need id and non-empty prompt"}
        if body.get("phase") == "prefill":
            return self._handle_prefill(body, rid, prompt)
        with self.lock:
            done = self._results.get(rid)
            if done is not None:
                return 200, dict(done)  # idempotent replay
            handle = self._handles.get(rid)
            if handle is not None and ctx is not None:
                # Dedupe-attach: a router re-dispatch raced the slow
                # original — the span marks which attempt attached.
                ctx.add_span(
                    "gateway.attach",
                    ts=time.time(),
                    parent=ctx.root_id,
                    attached=True,
                )
            if handle is None:
                if self.aborted:
                    return 503, {"error": "killed"}
                if self.draining:
                    return 503, {"error": "draining"}
                eos = body.get("eos_id")
                try:
                    # trace= rides only for traced requests so fake
                    # engines without the kwarg keep working untraced.
                    kw = {} if ctx is None else {"trace": ctx}
                    # kv_key likewise rides only when the router
                    # shipped a prefill (ISSUE 19): engines without
                    # the kwarg keep working on plain forwards.
                    if body.get("kv_key"):
                        kw["kv_key"] = str(body["kv_key"])
                    handle = self.engine.submit(
                        np.asarray(prompt, np.int32),
                        max_new_tokens=int(
                            body.get("max_new_tokens") or 1
                        ),
                        eos_id=None if eos is None else int(eos),
                        **kw,
                    )
                except (TypeError, ValueError) as e:
                    # TypeError covers non-castable fields (a list
                    # max_new_tokens) — still the client's fault, 400.
                    return 400, {"error": str(e)}
                self._handles[rid] = handle
        deadline = time.monotonic() + self.hold_timeout_s
        while True:
            with self.lock:
                if self.aborted:
                    self._handles.pop(rid, None)
                    return 503, {"error": "killed"}
                if handle.state == "done":
                    payload = {
                        "id": rid,
                        "tokens": [int(t) for t in handle.tokens],
                        "finish_reason": handle.finish_reason,
                    }
                    if self.on_complete is not None:
                        try:
                            self.on_complete(handle)
                        except Exception:  # noqa: BLE001 — obs only
                            pass
                    self._handles.pop(rid, None)
                    self._results[rid] = payload
                    while len(self._results) > _RESULT_CACHE_MAX:
                        self._results.popitem(last=False)
                    return 200, dict(payload)
                if getattr(handle, "drained", False):
                    # SIGTERM landed before this request started: the
                    # router re-dispatches it to a live replica.
                    self._handles.pop(rid, None)
                    return 503, {"error": "drained"}
            if time.monotonic() >= deadline:
                return 503, {"error": "hold timeout"}
            time.sleep(self.poll_s)

    def _handle_prefill(
        self, body: dict, rid: str, prompt: list
    ) -> tuple[int, dict]:
        """Disaggregated ship hop (ISSUE 19): run a chunked prefill on
        THIS replica, commit the KV pages as a tiny checkpoint, answer
        the store key. Any failure — no kv store, a mid-ship kill, a
        commit error — is an explicit 503: the router counts a
        ship-fallback and the decode replica prefills locally, so the
        client's answer never depends on this hop succeeding."""
        with self.lock:
            done = self._results.get(rid)
            if done is not None:
                return 200, dict(done)  # idempotent replay
            if self.aborted:
                return 503, {"error": "killed"}
            if self.draining:
                return 503, {"error": "draining"}
            ship = getattr(self.engine, "ship", None)
            if ship is None:
                return 503, {"error": "replica cannot ship"}
            try:
                key = ship(
                    np.asarray(prompt, np.int32),
                    quantize=bool(body.get("quantize")),
                )
            except (TypeError, ValueError) as e:
                return 400, {"error": str(e)}
            except Exception as e:  # noqa: BLE001 — ship is optional;
                # "try another path" beats a severed connection.
                return 503, {"error": f"{type(e).__name__}: {e}"}
            payload = {"id": rid, "kv_key": str(key)}
            self._results[rid] = payload
            while len(self._results) > _RESULT_CACHE_MAX:
                self._results.popitem(last=False)
            return 200, dict(payload)

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)


# ------------------------------------------------------------ CLI entry
def main(argv: list[str] | None = None) -> int:
    """``python -m tpuflow.infer.frontdoor [target]`` — the ingress the
    router_deployment manifest launches: discover replicas (arg >
    TPUFLOW_ROUTER_TARGET > the fleet discovery knobs), poll them, and
    serve /generate on TPUFLOW_ROUTER_HOST:TPUFLOW_ROUTER_PORT until
    SIGINT/SIGTERM."""
    import signal

    from tpuflow.obs import fleet as _fleet

    args = list(argv) if argv is not None else None
    target = None
    if args:
        target = args[0]
    if target is None:
        target = knobs.raw("TPUFLOW_ROUTER_TARGET") or None
    observatory = _fleet.FleetObservatory(target)
    # The observatory sweep runs on the poller's thread; the router
    # only ever reads its cached snapshot (the "cheap snapshot_fn"
    # contract — a slow /status must not stall routing).
    poller = _fleet.FleetPoller(observatory)
    router = Router(poller.snapshot, http_forward)
    door = FrontDoor(router)
    print(f"[frontdoor] serving {door.url}/generate", flush=True)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            pass  # non-main thread (tests)
    try:
        while not stop.is_set():
            router.refresh()
            stop.wait(0.5)
    finally:
        door.close()
        poller.close()
    return 0


# ---------------------------------------------------------- forwarding
def http_forward(row: dict, request: dict, timeout_s: float) -> dict:
    """One forward attempt to a replica row's ``generate_url``.

    Raises on ANY failure — no URL in the row, connection refused,
    timeout, non-200, undecodable body — because the Router's retry
    loop treats "raise" as "try another replica". A 200 body is the
    client's response, verbatim.
    """
    url = row.get("generate_url")
    if not url:
        raise RuntimeError(
            f"replica {row.get('id')!r} exports no generate_url"
        )
    # The in-process TraceContext never rides the wire: strip it from
    # the body and propagate as a W3C traceparent header, whose span id
    # the Router set to THIS forward attempt's span.
    ctx = request.get("_trace_ctx")
    headers = {"Content-Type": "application/json"}
    if ctx is None:
        payload = request
    else:
        payload = {
            k: v for k, v in request.items() if k != "_trace_ctx"
        }
        headers["traceparent"] = ctx.to_traceparent()
    data = json.dumps(payload).encode("utf-8")
    req = urlrequest.Request(
        url, data=data, headers=headers, method="POST",
    )
    try:
        with urlrequest.urlopen(req, timeout=timeout_s) as resp:
            body = resp.read()
    except urlerror.HTTPError as e:
        detail = ""
        try:
            detail = e.read().decode("utf-8", "replace")[:200]
        except OSError:
            pass
        raise RuntimeError(
            f"replica {row.get('id')!r} answered {e.code}: {detail}"
        ) from e
    out = json.loads(body.decode("utf-8"))
    if not isinstance(out, dict):
        raise RuntimeError("replica answered a non-object body")
    return out


# ----------------------------------------------------------- front door
class FrontDoor:
    """Client-facing ingress: POST /generate → Router.route, with the
    router's explicit outcomes mapped onto HTTP codes. GET /status
    serves ``router_*`` stats; GET /healthz answers 200 while up."""

    def __init__(
        self,
        router: Router,
        *,
        host: str | None = None,
        port: int | None = None,
    ):
        if host is None:
            host = knobs.get_str("TPUFLOW_ROUTER_HOST")
        if port is None:
            port = knobs.get_int("TPUFLOW_ROUTER_PORT")
        self.router = router
        door = self

        class _Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 (http.server API)
                if self.path != "/generate":
                    _send_json(self, 404, {"error": "not found"})
                    return
                body = _read_json(self)
                if body is None:
                    _send_json(self, 400, {"error": "bad json"})
                    return
                # End-to-end tracing (ISSUE 18): mint the trace at
                # ingress; the context rides the body in-process (the
                # forwarder strips it and speaks traceparent on the
                # wire) and the ingress span is the client-observed
                # wall the critical path reconciles against.
                ctx = _reqtrace.maybe_mint(body.get("id"))
                if ctx is not None:
                    body["_trace_ctx"] = ctx
                t0 = time.time()
                try:
                    code, out = 200, door.router.route(body)
                except FleetBusy as e:
                    code, out = 503, {"error": str(e)}
                except (TypeError, ValueError) as e:
                    if ctx is not None:
                        ctx.escalate("error")
                    code, out = 400, {"error": str(e)}
                except Exception as e:  # noqa: BLE001 — the "every
                    # request ends answered or told" contract: an
                    # unexpected failure is a 500 JSON answer, never a
                    # severed connection.
                    if ctx is not None:
                        ctx.escalate("error")
                    code, out = 500, {
                        "error": f"{type(e).__name__}: {e}"
                    }
                if ctx is not None:
                    ctx.add_span(
                        "router.ingress",
                        span_id=ctx.root_id,
                        ts=t0,
                        dur_s=time.time() - t0,
                        status=code,
                    )
                    _reqtrace.flush(ctx, writer="frontdoor")
                _send_json(self, code, out)

            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path == "/status":
                    _send_json(self, 200, door.router.stats())
                elif self.path == "/healthz":
                    _send_json(self, 200, {"ok": True})
                else:
                    _send_json(self, 404, {"error": "not found"})

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((host, int(port)), _Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="tpuflow-frontdoor",
            daemon=True,
        )
        self._thread.start()
        h, p = self._server.server_address[:2]
        self.url = f"http://{h}:{p}"

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)


if __name__ == "__main__":
    import sys

    raise SystemExit(main(sys.argv[1:]))
