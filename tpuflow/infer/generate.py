"""Autoregressive generation for the GPT-2 family: KV-cache decode under jit.

The reference's inference story is one classifier forward per batch
(my_ray_module.py:275-284); an LM family needs token-by-token sampling. This
is the TPU-native shape of that loop:

- **Prefill** runs the whole prompt through the model once in decode mode,
  filling every block's fixed-size KV cache (one compile, MXU-batched).
- **Decode** loops single-token steps — cache, current token, rng, and
  done-mask ride the carry, so the entire generation is ONE jitted XLA
  program: no per-token Python dispatch, no dynamic shapes, no host↔device
  chatter until the final tokens come back. Without an eos it is a
  ``lax.scan`` (static trip count); with ``eos_id`` it is a
  ``lax.while_loop`` that exits as soon as every row has finished (the
  output buffer stays statically shaped, unreached positions hold
  ``pad_id``).
- Sampling is temperature / top-k / top-p categorical (greedy at
  temperature=0),
  with an EOS done-mask that freezes finished rows to ``pad_id``.

Works on any backend; on a sharded mesh the batch axis shards over 'data'
and the cache inherits the activations' sharding through GSPMD.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpuflow import obs


def _sample(
    logits,
    rng,
    temperature,
    top_p,
    *,
    greedy: bool,
    top_k: int | None,
    use_top_p: bool,
):
    """(B, V) logits → (B,) sampled token ids.

    ``greedy`` (the temperature == 0 case), ``top_k``, and whether nucleus
    filtering applies change the program shape and are static;
    ``temperature`` and the ``top_p`` value are traced operands so sweeping
    either does not recompile the generation program. With both filters
    set, top-k applies first, then the nucleus filter over what remains.
    """
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    if use_top_p:
        # Nucleus: keep the smallest prefix of the sorted distribution with
        # cumulative probability >= top_p (the first token always survives).
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = cum - probs < top_p  # prefix BEFORE this token is < top_p
        # Threshold = smallest kept logit per row.
        cutoff = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnums=(0,),
    static_argnames=(
        "max_new_tokens", "greedy", "top_k", "use_top_p", "eos_id", "pad_id",
        "prefill_chunk",
    ),
)
def _generate_jit(
    model,
    params,
    prompt,
    rng,
    temperature,
    top_p,
    pad_lens=None,
    *,
    max_new_tokens: int,
    greedy: bool,
    top_k: int | None,
    use_top_p: bool,
    eos_id: int | None,
    pad_id: int,
    prefill_chunk: int | None = None,
):
    # pad_lens None-vs-array is itself a jit specialization boundary (pytree
    # structure), so dense batches compile the fast T x T prefill path.
    B, T = prompt.shape

    logits, cache = chunked_prefill(
        model, params, prompt, prefill_chunk, pad_lens=pad_lens
    )
    rng, sub = jax.random.split(rng)
    # Left-padding puts every row's last REAL token in the last column, so
    # logits[:, -1] is the right next-token distribution for dense and
    # ragged batches alike.
    tok = _sample(
        logits[:, -1, :], sub, temperature, top_p,
        greedy=greedy, top_k=top_k, use_top_p=use_top_p,
    )
    # EOS semantics: the eos token itself IS emitted (so callers can trim at
    # it); only positions after it are frozen to pad_id.
    done = (
        tok == eos_id if eos_id is not None else jnp.zeros((B,), bool)
    )

    def decode_one(cache, tok, rng, done):
        logits, vars_out = model.apply(
            {"params": params, "cache": cache},
            tok[:, None],
            decode=True,
            mutable=["cache"],
            pad_lens=pad_lens,
        )
        rng, sub = jax.random.split(rng)
        sampled = _sample(
            logits[:, -1, :], sub, temperature, top_p,
            greedy=greedy, top_k=top_k, use_top_p=use_top_p,
        )
        nxt = jnp.where(done, pad_id, sampled)
        if eos_id is not None:
            done = done | (sampled == eos_id)
        return vars_out["cache"], nxt, rng, done

    if max_new_tokens == 1:
        return tok[:, None]

    if eos_id is None:
        def step(carry, _):
            cache, tok, rng, done = carry
            new_cache, nxt, rng, done = decode_one(cache, tok, rng, done)
            return (new_cache, nxt, rng, done), tok

        (_, last, _, _), toks = jax.lax.scan(
            step, (cache, tok, rng, done), None, length=max_new_tokens - 1
        )
        return jnp.concatenate([toks.T, last[:, None]], axis=1)

    # With an eos the trip count is data-dependent: a while_loop exits as
    # soon as EVERY row has finished, instead of burning the full
    # max_new_tokens steps (output identical — unreached positions stay
    # pad_id, exactly what the frozen rows would have emitted).
    out0 = jnp.full((B, max_new_tokens), pad_id, jnp.int32)
    out0 = jax.lax.dynamic_update_slice(out0, tok[:, None], (0, 0))

    def cond(state):
        i, _, _, _, _, done = state
        return (i < max_new_tokens) & ~jnp.all(done)

    def body(state):
        i, out, cache, tok, rng, done = state
        cache, nxt, rng, done = decode_one(cache, tok, rng, done)
        out = jax.lax.dynamic_update_slice(out, nxt[:, None], (0, i))
        return i + 1, out, cache, nxt, rng, done

    _, out, _, _, _, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(1), out0, cache, tok, rng, done)
    )
    return out


def render_tokens(ids, *, byte_level: bool = False) -> str:
    """Human-readable rendering of generated token ids: byte-level corpora
    decode to text (out-of-range ids show as the replacement character,
    never silently dropped); token corpora print the ids."""
    ids = [int(t) for t in ids]
    if byte_level:
        return "".join(
            chr(t) if 0 <= t < 256 else "\N{REPLACEMENT CHARACTER}"
            for t in ids
        )
    return " ".join(str(t) for t in ids)


def normalize_prefill_chunk(prefill_chunk, T: int):
    """One validator shared by every inference entry point (generate /
    beam / speculative) so the chunk contract can't drift: widths < 1
    fail loudly OUTSIDE jit; no-op widths (>= T) normalize to None so
    the jit cache holds one program, not duplicates keyed on a width
    that changes nothing."""
    if prefill_chunk is not None and prefill_chunk < 1:
        raise ValueError(f"prefill_chunk must be >= 1, got {prefill_chunk}")
    if prefill_chunk is not None and prefill_chunk >= T:
        return None
    return prefill_chunk


def chunked_prefill(model, params, prompt, prefill_chunk, *, pad_lens=None):
    """Fill a fresh KV cache from ``prompt``, one pass (``prefill_chunk``
    None or >= T) or in fixed-size slices — chunking bounds the largest
    attention-score tensor to (B, H, chunk, n_ctx) instead of
    (B, H, T, T) for long prompts, at a static chunk count (at most two
    distinct widths compile). Chunks after the first hit the warm cache
    at start > 0, which the model computes exactly (masked full-cache
    attention behind the lax.cond in Block._cached_attention). Shared by
    ``generate`` and ``speculative_generate`` (call INSIDE jit); returns
    ``(last_chunk_logits, cache)``."""
    T = prompt.shape[1]
    if prefill_chunk is None or prefill_chunk >= T:
        logits, vars_out = model.apply(
            {"params": params}, prompt, decode=True, mutable=["cache"],
            pad_lens=pad_lens, prefill=True,
        )
        return logits, vars_out["cache"]
    cache = None
    for start in range(0, T, prefill_chunk):
        chunk = prompt[:, start:start + prefill_chunk]
        variables = (
            {"params": params}
            if cache is None
            else {"params": params, "cache": cache}
        )
        logits, vars_out = model.apply(
            variables, chunk, decode=True, mutable=["cache"],
            pad_lens=pad_lens, prefill=True,
        )
        cache = vars_out["cache"]
    return logits, cache


def after_first_true(flags):
    """(…, T) bool → True at positions STRICTLY after the first True along
    the last axis. The one eos-freeze mask shared by scoring, speculative
    decoding, and rerank — the token-exactness contract between them
    depends on all three using identical semantics."""
    f = flags.astype(jnp.int32)
    return (jnp.cumsum(f, axis=-1) - f) > 0


def check_cache_capacity(model, width: int, max_new_tokens: int) -> None:
    """Shared n_ctx guard for every decode entry point: prompt + new
    tokens must fit the model's fixed KV-cache size."""
    n_ctx = model.config.n_ctx
    if width + max_new_tokens > n_ctx:
        raise ValueError(
            f"prompt length {width} + max_new_tokens {max_new_tokens} "
            f"exceeds the model's n_ctx={n_ctx} (the KV cache size)"
        )


def prompt_lens_to_pad_lens(prompt_lens, batch: int, width: int):
    """Validate a ``prompt_lens`` (B,) array against a LEFT-padded batch of
    ``width`` columns and return the pad-count tensor the model consumes
    (``None`` passes through). One validator shared by every inference
    entry point (generate / beam_search / sequence_logprob) so the
    contract can't drift between them."""
    if prompt_lens is None:
        return None
    import numpy as np

    lens = np.asarray(prompt_lens, np.int32)
    if lens.shape != (batch,):
        raise ValueError(
            f"prompt_lens shape {lens.shape} != (batch,) = ({batch},)"
        )
    if (lens < 1).any() or (lens > width).any():
        raise ValueError(
            f"prompt_lens must be in [1, {width}], got "
            f"[{lens.min()}, {lens.max()}]"
        )
    return jnp.asarray(width - lens, jnp.int32)


def pad_ragged(prompts, *, pad_id: int = 0):
    """LEFT-pad a list of variable-length token sequences to one (B, Tmax)
    int32 array. Returns ``(prompt, prompt_lens)`` — pass both to
    ``generate(..., prompt_lens=...)`` or ``sequence_logprob(...,
    prompt_lens=...)``. Left-padding keeps every row's last real token in
    the final column, which is what the single uniform decode loop needs
    (no per-row gather at the prompt boundary)."""
    import numpy as np

    seqs = [np.asarray(p, np.int32).reshape(-1) for p in prompts]
    if not seqs:
        raise ValueError("prompts is empty")
    lens = np.array([len(s) for s in seqs], np.int32)
    if (lens == 0).any():
        raise ValueError("every prompt must have at least one token")
    T = int(lens.max())
    out = np.full((len(seqs), T), pad_id, np.int32)
    for i, s in enumerate(seqs):
        out[i, T - len(s):] = s
    return out, lens


def generate(
    model,
    params,
    prompt,
    *,
    max_new_tokens: int,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    eos_id: int | None = None,
    pad_id: int = 0,
    rng=None,
    prompt_lens=None,
    prefill_chunk: int | None = None,
):
    """Sample ``max_new_tokens`` continuations of ``prompt`` (B, T) int32.

    Returns (B, max_new_tokens) int32. ``T + max_new_tokens`` must fit the
    model's ``n_ctx`` (the fixed cache size). ``temperature=0`` is greedy
    decoding; any other temperature is a traced operand (sweeping it reuses
    the compiled program); ``top_k`` and ``top_p`` nucleus filtering compose
    (top-k first). With ``eos_id`` set, the eos token itself is emitted and
    the row's remaining positions are frozen to ``pad_id``.

    Ragged batches: pass ``prompt_lens`` (B,) with a LEFT-padded ``prompt``
    (see ``pad_ragged``) — pad columns are masked out of attention and
    positions are row-shifted, so mixed-length batches decode token-exactly
    vs per-row dense calls (parity bar: the reference's engine takes ragged
    rows, reference eval_flow.py:85-90).

    ``prefill_chunk`` streams the prompt into the cache in fixed-size
    slices (long-context prefill: peak attention memory drops from
    O(T^2) to O(chunk x n_ctx) per layer, exactness unchanged — chunks
    after the first run masked full-cache attention).
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    B, T = prompt.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(
            f"top_p must be in (0, 1], got {top_p} (<= 0 would mask every "
            "token)"
        )
    check_cache_capacity(model, T, max_new_tokens)
    prefill_chunk = normalize_prefill_chunk(prefill_chunk, T)
    pad_lens = prompt_lens_to_pad_lens(prompt_lens, B, T)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    rec = obs.recorder()
    if rec is not None:
        import time

        t0, ts0 = time.monotonic(), time.time()
    out = _generate_jit(
        model,
        params,
        prompt,
        rng,
        jnp.asarray(temperature, jnp.float32),
        jnp.asarray(1.0 if top_p is None else top_p, jnp.float32),
        pad_lens,
        max_new_tokens=max_new_tokens,
        greedy=temperature == 0.0,
        top_k=top_k,
        use_top_p=top_p is not None,
        eos_id=eos_id,
        pad_id=pad_id,
        prefill_chunk=prefill_chunk,
    )
    if rec is not None:
        # Fenced decode latency + tokens/s (telemetry-on only: the fence
        # trades the async-dispatch overlap for an honest wall time; with
        # obs off the call returns the in-flight arrays untouched).
        import time

        from tpuflow.infer.quant import QuantizedModel

        out = jax.block_until_ready(out)
        dur = time.monotonic() - t0
        n = B * max_new_tokens
        # The numeric path is part of the measurement's identity: a
        # tokens/s record that doesn't say fp vs int8 (and which int8
        # mode) can't be compared across runs — the bench's sub-legs
        # and the serving telemetry both key on it (ISSUE 9).
        quant = (
            model.mode if isinstance(model, QuantizedModel) else "fp"
        )
        rec.record(
            "span", "infer.generate", ts=ts0, dur_s=dur, batch=B,
            prompt_len=T, new_tokens=max_new_tokens,
            tokens_per_s=n / dur if dur > 0 else 0.0,
            quant=quant,
        )
    return out
