"""Tiered KV-page store: committed page sets as tiny checkpoints
(ISSUE 19).

PR 11 proved paged KV content is pad-invariant — page ``j`` of a prompt
is a pure function of the prompt prefix through that page. That makes a
request's KV pages a *shippable artifact*: a prefill-role engine can run
chunked prefill once, extract the pages + the sha1 prefix-digest chain,
and commit them as a :class:`KVPageSet`; a decode-role engine imports
the set and admits the request already-prefilled, bit-equal to a solo
``generate()`` (tests/test_serve_disagg.py). The same machinery is the
spill path of the tiered prefix cache: pages evicted from the HBM pool
drop to host DRAM (:class:`HostTier`) and node-local disk (a
:class:`KVStore` keyed by digest), and a lower-tier prefix hit promotes
pages back instead of recomputing prefill (:class:`TierCache`).

Commit protocol — the ckpt manager's atomic-commit/crc-manifest idiom
(``tpuflow/ckpt/manager.py`` / ``raw.py``), applied to one blob + one
manifest per page set:

1. the ``.npz`` blob is staged at ``<key>.npz.tmp`` and published by one
   ``os.replace``;
2. the JSON manifest (digest chain, geometry, the blob's crc32) is
   staged and renamed LAST — the manifest IS the commit marker.

A crash at any point leaves either nothing visible or a blob without a
manifest; ``load`` requires both plus a crc match, so torn or corrupted
sets never load (they return ``None`` — the caller's local-prefill
fallback, never an exception on the serving path). ``ckpt/manager.py``
shares :func:`atomic_write_bytes` / :func:`atomic_write_json` for its
own marker writes, so the two commit paths cannot drift.

Import discipline: stdlib + numpy + ``tpuflow.utils.knobs`` only — no
jax, so the unit tests (tests/test_kv_store.py) and the router run this
with zero compiles.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import io
import json
import os
import zlib

import numpy as np

BLOB_SUFFIX = ".npz"
MANIFEST_SUFFIX = ".json"
STAGE_SUFFIX = ".tmp"
FORMAT_NAME = "tpuflow-kvpages-v1"
SCHEMA = 1

_PAGE_PREFIX = "page::"


# ------------------------------------------------------- commit helpers
def atomic_write_bytes(path: str, data: bytes) -> None:
    """Stage ``data`` at ``path + '.tmp'``, fsync, publish with one
    ``os.replace`` — the write is all-or-nothing; a crash leaves only an
    invisible ``.tmp`` the next :func:`gc_stage_leftovers` reclaims.
    Shared with the checkpoint manager's marker writes."""
    tmp = path + STAGE_SUFFIX
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_write_json(path: str, obj) -> None:
    """JSON variant of :func:`atomic_write_bytes` (the commit-marker
    write: manifest/meta files become visible atomically or not at
    all)."""
    atomic_write_bytes(path, json.dumps(obj).encode("utf-8"))


def gc_stage_leftovers(root: str) -> int:
    """Remove ``*.tmp`` staging leftovers under ``root`` (a previous
    process died mid-commit; its set was never visible). Returns the
    count removed."""
    n = 0
    try:
        names = os.listdir(root)
    except OSError:
        return 0
    for name in names:
        if name.endswith(STAGE_SUFFIX):
            try:
                os.remove(os.path.join(root, name))
                n += 1
            except OSError:
                pass
    return n


# ------------------------------------------------------- digest chains
def chain_digests(prompt, page_size: int) -> list[bytes]:
    """sha1 prefix-digest chain over every FULLY-covered page: entry
    ``j`` keys the whole prompt prefix through page ``j`` (causal
    attention makes page content a pure function of that prefix) —
    byte-identical to ``PagePool.prefix_digests`` and the router's
    affinity keys."""
    p = np.asarray(prompt, np.int32).reshape(-1)
    ps = int(page_size)
    return [
        hashlib.sha1(p[: (j + 1) * ps].tobytes()).digest()
        for j in range(p.size // ps)
    ]


def chain_match(a: list[bytes], b: list[bytes]) -> int:
    """Longest common PREFIX of two digest chains (suffix resume: how
    many committed pages a longer prompt can import)."""
    m = 0
    for x, y in zip(a, b):
        if x != y:
            break
        m += 1
    return m


def prompt_key(prompt) -> str:
    """Store key of a prompt's page set: sha1 hex over the full token
    bytes (int32) — what the router forwards as ``kv_key``."""
    p = np.asarray(prompt, np.int32).reshape(-1)
    return hashlib.sha1(p.tobytes()).hexdigest()


# ----------------------------------------------------------- page sets
@dataclasses.dataclass
class KVPageSet:
    """One request's committed KV pages: the shippable artifact.

    ``pages`` maps each cache-leaf key (the engine's flattened pytree
    path) to a page-major array ``(k, ..., page_size, H, D)`` holding
    the first ``k = ceil(n_tokens / page_size)`` logical pages —
    including the partial tail page (private to the request: decode
    writes land there). ``digests`` covers only the FULL pages (the
    shareable ones). ``tok0`` is the prefill's first greedy token, so a
    decode-side import of the exact prompt admits with zero prefill."""

    page_size: int
    n_tokens: int
    prompt: np.ndarray  # (L,) int32
    digests: list[bytes]
    pages: dict[str, np.ndarray]
    tok0: int | None = None
    meta: dict = dataclasses.field(default_factory=dict)

    @property
    def key(self) -> str:
        return prompt_key(self.prompt)

    @property
    def n_pages(self) -> int:
        for arr in self.pages.values():
            return int(arr.shape[0])
        return 0

    def page_bundle(self, j: int) -> dict[str, np.ndarray]:
        """Page ``j`` as a per-leaf bundle (the tier/promotion unit)."""
        return {k: np.asarray(v[j]) for k, v in self.pages.items()}


class KVStore:
    """Directory of committed page sets, one blob + one manifest per
    key. All operations are torn-safe: ``load`` never returns a partial
    or corrupted set, and never raises on the serving path."""

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        gc_stage_leftovers(self.root)

    # internal ----------------------------------------------------------
    def _blob(self, key: str) -> str:
        return os.path.join(self.root, key + BLOB_SUFFIX)

    def _manifest(self, key: str) -> str:
        return os.path.join(self.root, key + MANIFEST_SUFFIX)

    # low-level (tier pages ride this without a prompt) -----------------
    def commit_arrays(
        self, key: str, arrays: dict[str, np.ndarray], extra: dict
    ) -> str:
        """Commit named arrays under ``key``: blob first, manifest (the
        commit marker, carrying the blob crc32) last."""
        buf = io.BytesIO()
        np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
        data = buf.getvalue()
        atomic_write_bytes(self._blob(key), data)
        manifest = {
            "schema": SCHEMA,
            "format": FORMAT_NAME,
            "crc32": zlib.crc32(data),
            "blob_bytes": len(data),
            **extra,
        }
        atomic_write_json(self._manifest(key), manifest)
        return key

    def load_arrays(
        self, key: str
    ) -> tuple[dict[str, np.ndarray], dict] | None:
        """(arrays, manifest) — or ``None`` for missing / torn (blob
        without manifest or vice versa) / crc-mismatched / malformed
        sets. Never raises."""
        try:
            with open(self._manifest(key)) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return None
        try:
            with open(self._blob(key), "rb") as f:
                data = f.read()
        except OSError:
            return None
        if (
            len(data) != manifest.get("blob_bytes")
            or zlib.crc32(data) != manifest.get("crc32")
        ):
            return None
        try:
            with np.load(io.BytesIO(data), allow_pickle=False) as z:
                arrays = {k: z[k] for k in z.files}
        except Exception:  # noqa: BLE001 — torn-set tolerance by contract
            return None
        return arrays, manifest

    # page-set surface --------------------------------------------------
    def commit(self, pset: KVPageSet) -> str:
        """Commit a page set under its prompt key; returns the key."""
        arrays = {"prompt": np.asarray(pset.prompt, np.int32)}
        for name, arr in pset.pages.items():
            arrays[_PAGE_PREFIX + name] = arr
        extra = {
            "page_size": int(pset.page_size),
            "n_tokens": int(pset.n_tokens),
            "tok0": None if pset.tok0 is None else int(pset.tok0),
            "digests": [d.hex() for d in pset.digests],
            "meta": dict(pset.meta),
        }
        return self.commit_arrays(pset.key, arrays, extra)

    def load(self, key: str) -> KVPageSet | None:
        got = self.load_arrays(key)
        if got is None:
            return None
        arrays, manifest = got
        if "prompt" not in arrays:
            return None
        try:
            digests = [bytes.fromhex(h) for h in manifest["digests"]]
            tok0 = manifest["tok0"]
            return KVPageSet(
                page_size=int(manifest["page_size"]),
                n_tokens=int(manifest["n_tokens"]),
                prompt=np.asarray(arrays["prompt"], np.int32),
                digests=digests,
                pages={
                    k[len(_PAGE_PREFIX):]: v
                    for k, v in arrays.items()
                    if k.startswith(_PAGE_PREFIX)
                },
                tok0=None if tok0 is None else int(tok0),
                meta=dict(manifest.get("meta") or {}),
            )
        except (KeyError, ValueError, TypeError):
            return None

    def contains(self, key: str) -> bool:
        return os.path.exists(self._manifest(key)) and os.path.exists(
            self._blob(key)
        )

    def keys(self) -> list[str]:
        out = []
        for name in sorted(os.listdir(self.root)):
            if name.endswith(MANIFEST_SUFFIX):
                key = name[: -len(MANIFEST_SUFFIX)]
                if os.path.exists(self._blob(key)):
                    out.append(key)
        return out

    def delete(self, key: str) -> None:
        # Manifest first: a crash between the two unlinks must leave a
        # torn (never-loading) set, not a manifest pointing at nothing
        # that later pairs with a recreated blob.
        for path in (self._manifest(key), self._blob(key)):
            try:
                os.remove(path)
            except OSError:
                pass

    def nbytes(self) -> int:
        total = 0
        for key in self.keys():
            try:
                total += os.path.getsize(self._blob(key))
            except OSError:
                pass
        return total

    def trim_to_bytes(self, max_bytes: int) -> list[str]:
        """LRU-trim (manifest mtime) the store under ``max_bytes``;
        returns the evicted keys."""
        entries = []
        for key in self.keys():
            try:
                entries.append((
                    os.path.getmtime(self._manifest(key)),
                    os.path.getsize(self._blob(key)),
                    key,
                ))
            except OSError:
                continue
        total = sum(e[1] for e in entries)
        evicted = []
        for _, size, key in sorted(entries):
            if total <= max_bytes:
                break
            self.delete(key)
            total -= size
            evicted.append(key)
        return evicted


# ---------------------------------------------------------------- tiers
def _bundle_bytes(bundle: dict[str, np.ndarray]) -> int:
    return sum(int(v.nbytes) for v in bundle.values())


class HostTier:
    """Host-DRAM page tier: digest → per-leaf page bundle, LRU within a
    byte budget. ``put`` returns the bundles evicted to make room (the
    cascade the disk tier absorbs)."""

    def __init__(self, budget_bytes: int):
        self.budget_bytes = int(budget_bytes)
        self._data: collections.OrderedDict[
            bytes, dict[str, np.ndarray]
        ] = collections.OrderedDict()
        self.used_bytes = 0

    def __contains__(self, digest: bytes) -> bool:
        return digest in self._data

    @property
    def count(self) -> int:
        return len(self._data)

    def put(
        self, digest: bytes, bundle: dict[str, np.ndarray]
    ) -> list[tuple[bytes, dict[str, np.ndarray]]]:
        nb = _bundle_bytes(bundle)
        evicted: list[tuple[bytes, dict[str, np.ndarray]]] = []
        if nb > self.budget_bytes:
            return [(digest, bundle)]  # never fits: cascade straight down
        old = self._data.pop(digest, None)
        if old is not None:
            self.used_bytes -= _bundle_bytes(old)
        while self._data and self.used_bytes + nb > self.budget_bytes:
            d, b = self._data.popitem(last=False)  # LRU-first
            self.used_bytes -= _bundle_bytes(b)
            evicted.append((d, b))
        self._data[digest] = bundle
        self.used_bytes += nb
        return evicted

    def get(
        self, digest: bytes, *, pop: bool = False
    ) -> dict[str, np.ndarray] | None:
        bundle = self._data.get(digest)
        if bundle is None:
            return None
        if pop:
            del self._data[digest]
            self.used_bytes -= _bundle_bytes(bundle)
        else:
            self._data.move_to_end(digest)
        return bundle

    def drop(self, digest: bytes) -> None:
        self.get(digest, pop=True)


class TierCache:
    """The HBM pool's lower tiers: host DRAM first, node-local disk
    below it, with one bounded digest→tier index on top (the ISSUE 19
    bugfix: an evicted prefix used to be indistinguishable from
    never-cached). Spill order is HBM → host → disk; host-budget
    overflow cascades LRU bundles down to disk. A disk dir alone (no
    host budget) spills straight to disk — and is rescanned at
    construction, which is what lets a hot prefix survive an engine
    restart."""

    def __init__(
        self,
        *,
        host_bytes: int = 0,
        disk_dir: str | None = None,
        index_max: int = 4096,
        disk_max_bytes: int = 0,
    ):
        self.host = HostTier(host_bytes) if host_bytes > 0 else None
        self.disk = KVStore(disk_dir) if disk_dir else None
        self.index_max = max(int(index_max), 1)
        self.disk_max_bytes = int(disk_max_bytes)
        self._index: collections.OrderedDict[bytes, str] = (
            collections.OrderedDict()
        )
        self.spills_host = 0
        self.spills_disk = 0
        self.hits_host = 0
        self.hits_disk = 0
        if self.disk is not None:
            for key in self.disk.keys():
                try:
                    d = bytes.fromhex(key)
                except ValueError:
                    continue
                self._index[d] = "disk"
            self._trim_index()

    @property
    def armed(self) -> bool:
        return self.host is not None or self.disk is not None

    @property
    def pages_host(self) -> int:
        return 0 if self.host is None else self.host.count

    @property
    def pages_disk(self) -> int:
        return sum(1 for t in self._index.values() if t == "disk")

    def _trim_index(self) -> None:
        while len(self._index) > self.index_max:
            d, tier = self._index.popitem(last=False)
            if tier == "host" and self.host is not None:
                # Host bundles are only findable through the index;
                # reclaim the DRAM. Disk files stay (a restart rescan
                # re-finds them) — the index stays bounded either way.
                self.host.drop(d)

    def _to_disk(self, digest: bytes, bundle) -> bool:
        if self.disk is None:
            return False
        key = digest.hex()
        if not self.disk.contains(key):
            # Page content is a pure function of the digest — an
            # existing entry is already the right bytes.
            self.disk.commit_arrays(key, bundle, {"kind": "tier_page"})
            if self.disk_max_bytes > 0:
                self.disk.trim_to_bytes(self.disk_max_bytes)
        self.spills_disk += 1
        return True

    def spill(
        self, digest: bytes, bundle: dict[str, np.ndarray]
    ) -> str | None:
        """Absorb one HBM-evicted page. Returns the tier it landed in
        (``"host"`` / ``"disk"``) or ``None`` when no tier could take
        it."""
        if self.host is not None:
            for d, b in self.host.put(digest, bundle):
                if d == digest:
                    break  # over-budget bundle: fall through to disk
                if self._to_disk(d, b):
                    self._index[d] = "disk"
                    self._index.move_to_end(d)
                else:
                    self._index.pop(d, None)
            else:
                self._index[digest] = "host"
                self._index.move_to_end(digest)
                self.spills_host += 1
                self._trim_index()
                return "host"
        if self._to_disk(digest, bundle):
            self._index[digest] = "disk"
            self._index.move_to_end(digest)
            self._trim_index()
            return "disk"
        self._index.pop(digest, None)
        return None

    def locate(self, digest: bytes) -> str | None:
        """Which tier (if any) holds ``digest`` — index-only, no IO."""
        tier = self._index.get(digest)
        if tier is not None:
            self._index.move_to_end(digest)
        return tier

    def fetch(
        self, digest: bytes
    ) -> tuple[dict[str, np.ndarray], str] | None:
        """(bundle, tier) for a promotion, or ``None`` (an indexed disk
        entry may still be torn/corrupt on read — the caller falls back
        to prefill). A host hit frees the DRAM copy (the page is going
        back to HBM); a disk hit keeps the file for restart survival."""
        tier = self._index.get(digest)
        if tier == "host" and self.host is not None:
            bundle = self.host.get(digest, pop=True)
            if bundle is not None:
                del self._index[digest]
                self.hits_host += 1
                return bundle, "host"
            self._index.pop(digest, None)
            return None
        if tier == "disk" and self.disk is not None:
            got = self.disk.load_arrays(digest.hex())
            if got is not None:
                self._index.move_to_end(digest)
                self.hits_disk += 1
                return got[0], "disk"
            self.disk.delete(digest.hex())
            self._index.pop(digest, None)
        return None
