"""Weight-only int8 quantization for the decode path.

Autoregressive decode is HBM-bandwidth-bound: every emitted token
streams the full weight set through the chip (the bench's decode leg is
the memory-side complement of its MFU leg). Storing weights as int8
with per-channel scales cuts that stream 4x vs f32 (2x vs bf16) — a
direct decode-throughput lever on TPU, where the MXU natively consumes
low-precision operands.

Design (TPU/XLA-first):

- **Quantize once, outside jit**: ``quantize_params`` walks the param
  pytree and replaces big floating matrices with ``QuantLeaf(q, scale)``
  — int8 values + a per-channel f32 scale (symmetric, max-abs / 127,
  reduced over every axis but the last; biases, norms, and small leaves
  stay exact).
- **Dequantize inside the compiled program**: ``QuantizedModel`` wraps
  any Flax model and rebuilds float weights *inside* ``apply`` — i.e.
  inside the caller's jit trace — as ``q.astype(dtype) * scale``. At
  rest (and across host→device transfer) only int8 bytes exist.
  CAVEAT, measured on-chip (r4, TPU_EVIDENCE.json decode.int8 = 0.76x
  vs fp at 124M/b8): XLA fusions do not cross dot boundaries, so the
  dequantized weights CAN materialize as a per-step bf16 buffer —
  convert+scale+write+read on top of the matmul — making weight-only
  int8 a *memory capacity* feature (half/quarter-sized resident
  weights, cheap transfer), not a decode-throughput feature, at small
  model sizes. A throughput win needs either much larger models (where
  the resident-set halving keeps weights HBM-side at all) or a true
  int8-operand MXU matmul (dynamic activation quantization), which is
  future work.
- **Zero integration surface**: the wrapper exposes ``apply`` and
  ``config`` — exactly what ``generate`` / ``beam_search`` /
  ``speculative_generate`` / ``score`` use — and is hashable, so it
  rides the same ``static_argnums`` slot the raw model does. Every
  decode feature (ragged prompts, chunked prefill, eos freezing, KV
  cache) works unchanged.

No parity counterpart in the reference (its engine serves f32 torch
modules); this is a TPU-first capability on top of the D12 engine.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class QuantLeaf(NamedTuple):
    """int8 values + broadcastable per-channel scale (a pytree node:
    checkpoints, device_put, and shardings see two ordinary arrays)."""

    q: Any      # int8, original shape
    scale: Any  # float, broadcastable to q (reduced axes kept as size 1)


def _is_quant(x) -> bool:
    return isinstance(x, QuantLeaf)


def quantize_params(
    params,
    *,
    min_size: int = 4096,
    scale_dtype=jnp.float32,
):
    """Replace large floating leaves (ndim >= 2, size >= ``min_size``)
    with ``QuantLeaf``s. Symmetric per-channel quantization, max-abs/127:
    a 2-D ``(in, out)`` kernel reduces the in axis (per-output-channel);
    3-D+ kernels reduce only the MIDDLE axes, keeping per-layer scales
    for scan-stacked weights and per-in-channel scales for
    ``(in, heads, head_dim)`` layouts. Small leaves (biases, LayerNorm,
    scalars) pass through exact."""

    def one(leaf):
        x = jnp.asarray(leaf)
        if (
            x.ndim < 2
            or x.size < min_size
            or not jnp.issubdtype(x.dtype, jnp.floating)
        ):
            return leaf
        # 2-D (in, out): reduce the in axis — per-output-channel scales.
        # 3-D+ kernels keep BOTH the leading and trailing axes: under
        # scan_layers the leading axis is the layer stack (one hot layer
        # must not inflate every other layer's scale and collapse its
        # int8 resolution). Guard: the scale tensor must stay a
        # negligible fraction of the int8 bytes — a head-split layout
        # like (in, heads, head_dim) would otherwise make shape[0] *
        # shape[-1] scales eat the compression the module exists for.
        # The fallback reduces everything BUT the leading axis: the
        # leading slice is the one whose independence matters (the layer
        # of a scan stack), and dequantize_params rebuilds full floats
        # inside jit before the matmul, so coarser scales cost only
        # resolution, never exactness. Reducing the leading axis away
        # instead would re-create the hot-layer bleed this layout exists
        # to prevent (caught in review, r4).
        axes = (
            tuple(range(x.ndim - 1)) if x.ndim == 2
            else tuple(range(1, x.ndim - 1))
        )
        itemsize = np.dtype(scale_dtype).itemsize
        n_scales = x.size // math.prod(x.shape[a] for a in axes)
        if n_scales * itemsize > x.size // 16:
            # 2-D: collapse to one per-tensor scale; 3-D+: one scale per
            # leading slice (per layer of a scan stack).
            axes = (
                tuple(range(x.ndim)) if x.ndim == 2
                else tuple(range(1, x.ndim))
            )
        amax = jnp.max(jnp.abs(x.astype(scale_dtype)), axis=axes,
                       keepdims=True)
        scale = jnp.where(amax > 0, amax, 1.0) / 127.0
        q = jnp.clip(jnp.round(x.astype(scale_dtype) / scale), -127, 127)
        return QuantLeaf(q.astype(jnp.int8), scale.astype(scale_dtype))

    return jax.tree_util.tree_map(one, params)


def dequantize_params(qparams, dtype=None):
    """Rebuild float leaves from ``QuantLeaf``s. Call INSIDE jit (e.g.
    via ``QuantizedModel.apply``) so XLA fuses the convert+scale into
    the consuming matmul and only int8 crosses HBM."""

    def one(leaf):
        if not _is_quant(leaf):
            return leaf
        out_dtype = dtype or leaf.scale.dtype
        return (leaf.q.astype(out_dtype) * leaf.scale.astype(out_dtype))

    return jax.tree_util.tree_map(one, qparams, is_leaf=_is_quant)


def quantized_nbytes(qparams) -> int:
    """Device bytes of a (possibly partially) quantized tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(qparams):
        total += leaf.nbytes
    return total


@dataclasses.dataclass(frozen=True)
class QuantizedModel:
    """Hashable shim exposing the two surfaces the decode stack uses
    (``apply`` + ``config``), dequantizing inside the traced apply.

    Use: ``qm, qp = quantize_model(model, params)`` then pass
    ``(qm, qp)`` anywhere ``(model, params)`` went."""

    model: Any
    dtype: Any = None  # compute dtype for dequantized weights

    def apply(self, variables, *args, **kwargs):
        variables = dict(variables)
        variables["params"] = dequantize_params(
            variables["params"], self.dtype
        )
        return self.model.apply(variables, *args, **kwargs)

    @property
    def config(self):
        return self.model.config


def quantize_model(model, params, *, min_size: int = 4096, dtype=None):
    """One-call form: returns ``(QuantizedModel, qparams)`` ready for
    ``generate(qm, qp, ...)`` / ``BatchPredictor`` / beam / speculative."""
    return (
        QuantizedModel(model, dtype),
        quantize_params(params, min_size=min_size),
    )
