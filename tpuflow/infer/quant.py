"""int8 quantization for the decode path: weight-only and fused native.

Autoregressive decode is HBM-bandwidth-bound: every emitted token
streams the full weight set through the chip (the bench's decode leg is
the memory-side complement of its MFU leg). Storing weights as int8
with per-channel scales cuts that stream 4x vs f32 (2x vs bf16) — a
direct decode-throughput lever on TPU, where the MXU natively consumes
low-precision operands.

Two modes, one wrapper:

- ``mode='weight'`` (alias ``weight_only``) — **quantize once, outside
  jit** (``quantize_params``: big floating matrices become
  ``QuantLeaf(q, scale)``), **dequantize inside the compiled program**
  (``QuantizedModel.apply`` rebuilds floats inside the caller's jit
  trace). At rest only int8 bytes exist. CAVEAT, measured on-chip (r4,
  TPU_EVIDENCE.json decode.int8 = 0.76x vs fp at 124M/b8): XLA fusions
  do not cross dot boundaries, so the dequantized weights CAN
  materialize as a per-step bf16 buffer — convert+scale+write+read on
  top of the matmul — making weight-only int8 a *memory capacity*
  feature (half/quarter-sized resident weights, cheap transfer), not a
  decode-throughput feature, at small model sizes.
- ``mode='mxu'`` (alias ``fused_native``) — the **native int8 compute
  path** that 0.76x number motivated (ROADMAP item 4): Dense kernels
  AND the LM head stay int8 *through the matmul*. Activations are
  dynamically quantized per row at the matmul boundary, the contraction
  runs int8 x int8 -> int32 on the MXU, and the combined
  ``act_scale (x) weight_scale`` dequant folds into the epilogue — one
  fused op (``tpuflow.ops.int8_matmul``: Pallas fused
  quantize-matmul-dequant kernel where the shape profits, XLA int8
  ``dot_general`` everywhere else, bit-identical numerics between the
  two). No dequantized weight copy ever materializes. The LM head rides
  a ``wte_q`` sibling leaf (per-vocab-row scales) that
  ``QuantizedModel.apply`` hands the model as the ``quant`` collection
  — the param tree stays a derived VIEW of the fp checkpoint, never a
  fork of it (checkpoints keep restoring unchanged).

**Zero integration surface** either way: the wrapper exposes ``apply``
and ``config`` — exactly what ``generate`` / ``beam_search`` /
``speculative_generate`` / ``score`` / ``ServeEngine`` use — and is
hashable, so it rides the same ``static_argnums`` slot the raw model
does. Every decode feature (ragged prompts, chunked prefill, eos
freezing, KV cache, serving slots) works unchanged.

No parity counterpart in the reference (its engine serves f32 torch
modules); this is a TPU-first capability on top of the D12 engine.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class QuantLeaf(NamedTuple):
    """int8 values + broadcastable per-channel scale (a pytree node:
    checkpoints, device_put, and shardings see two ordinary arrays)."""

    q: Any      # int8, original shape
    scale: Any  # float, broadcastable to q (reduced axes kept as size 1)


def _is_quant(x) -> bool:
    return isinstance(x, QuantLeaf)


def quantize_params(
    params,
    *,
    min_size: int = 4096,
    scale_dtype=jnp.float32,
):
    """Replace large floating leaves (ndim >= 2, size >= ``min_size``)
    with ``QuantLeaf``s. Symmetric per-channel quantization, max-abs/127:
    a 2-D ``(in, out)`` kernel reduces the in axis (per-output-channel);
    3-D+ kernels reduce only the MIDDLE axes, keeping per-layer scales
    for scan-stacked weights and per-in-channel scales for
    ``(in, heads, head_dim)`` layouts. Small leaves (biases, LayerNorm,
    scalars) pass through exact."""

    def one(leaf):
        x = jnp.asarray(leaf)
        if (
            x.ndim < 2
            or x.size < min_size
            or not jnp.issubdtype(x.dtype, jnp.floating)
        ):
            return leaf
        # 2-D (in, out): reduce the in axis — per-output-channel scales.
        # 3-D+ kernels keep BOTH the leading and trailing axes: under
        # scan_layers the leading axis is the layer stack (one hot layer
        # must not inflate every other layer's scale and collapse its
        # int8 resolution). Guard: the scale tensor must stay a
        # negligible fraction of the int8 bytes — a head-split layout
        # like (in, heads, head_dim) would otherwise make shape[0] *
        # shape[-1] scales eat the compression the module exists for.
        # The fallback reduces everything BUT the leading axis: the
        # leading slice is the one whose independence matters (the layer
        # of a scan stack), and dequantize_params rebuilds full floats
        # inside jit before the matmul, so coarser scales cost only
        # resolution, never exactness. Reducing the leading axis away
        # instead would re-create the hot-layer bleed this layout exists
        # to prevent (caught in review, r4).
        axes = (
            tuple(range(x.ndim - 1)) if x.ndim == 2
            else tuple(range(1, x.ndim - 1))
        )
        itemsize = np.dtype(scale_dtype).itemsize
        n_scales = x.size // math.prod(x.shape[a] for a in axes)
        if n_scales * itemsize > x.size // 16:
            # 2-D: collapse to one per-tensor scale; 3-D+: one scale per
            # leading slice (per layer of a scan stack).
            axes = (
                tuple(range(x.ndim)) if x.ndim == 2
                else tuple(range(1, x.ndim))
            )
        amax = jnp.max(jnp.abs(x.astype(scale_dtype)), axis=axes,
                       keepdims=True)
        scale = jnp.where(amax > 0, amax, 1.0) / 127.0
        q = jnp.clip(jnp.round(x.astype(scale_dtype) / scale), -127, 127)
        return QuantLeaf(q.astype(jnp.int8), scale.astype(scale_dtype))

    return jax.tree_util.tree_map(one, params)


def dequantize_params(qparams, dtype=None):
    """Rebuild float leaves from ``QuantLeaf``s. Call INSIDE jit (e.g.
    via ``QuantizedModel.apply``) so XLA fuses the convert+scale into
    the consuming matmul and only int8 crosses HBM."""

    def one(leaf):
        if not _is_quant(leaf):
            return leaf
        out_dtype = dtype or leaf.scale.dtype
        return (leaf.q.astype(out_dtype) * leaf.scale.astype(out_dtype))

    return jax.tree_util.tree_map(one, qparams, is_leaf=_is_quant)


def quantized_nbytes(qparams) -> int:
    """Device bytes of a (possibly partially) quantized tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(qparams):
        total += leaf.nbytes
    return total


def _int8_dense_interceptor(next_fun, args, kwargs, context):
    """Flax method interceptor implementing W8A8 Dense: when the bound
    kernel is a ``QuantLeaf``, route the matmul through the shared fused
    op (``tpuflow.ops.int8_matmul``) — dynamic per-row activation
    quantization, int8 x int8 -> int32 on the MXU (the contraction the
    chip executes natively at 2x its bf16 rate on v5e), and the combined
    ``act_scale (x) weight_scale`` dequant folded into the epilogue.
    Weights never materialize as a bf16 buffer (the r4-measured failure
    mode of the dequantize-into-matmul path: convert+scale+write+read
    cost 0.76x vs fp at 124M/b8). The op dispatches to its Pallas fused
    kernel or the XLA int8 ``dot_general`` per shape
    (``TPUFLOW_INT8_MATMUL`` / ``resolve_int8_impl``) — the two are
    bit-identical, so the choice never shifts tokens."""
    import flax.linen as nn

    from tpuflow.ops.int8_matmul import int8_matmul

    mod = context.module
    if (
        context.method_name != "__call__"
        or not mod.has_variable("params", "kernel")
    ):
        return next_fun(*args, **kwargs)
    kernel = mod.get_variable("params", "kernel")
    if not _is_quant(kernel):
        return next_fun(*args, **kwargs)
    if not isinstance(mod, nn.Dense):
        # ``_quantize_dense_kernels`` selects by leaf NAME; a non-Dense
        # module with a big 'kernel' (e.g. a 1-D nn.Conv) would otherwise
        # receive the QuantLeaf and crash deep inside its float ops.
        raise TypeError(
            f"mxu-mode int8 supports nn.Dense kernels only, but "
            f"{type(mod).__name__} at {'/'.join(context.module.path)} "
            "was given a quantized kernel — exclude it via min_size or "
            "use mode='weight'"
        )
    (x,) = args
    out = int8_matmul(
        x, kernel.q, kernel.scale, out_dtype=jnp.float32
    )
    if mod.use_bias:
        out = out + mod.get_variable("params", "bias").astype(jnp.float32)
    return out.astype(mod.dtype or x.dtype)


@dataclasses.dataclass(frozen=True)
class QuantizedModel:
    """Hashable shim exposing the two surfaces the decode stack uses
    (``apply`` + ``config``). Two modes:

    - ``mode='weight'`` (alias ``weight_only``): every large leaf is
      int8 at rest; float weights are rebuilt inside the traced apply
      (memory-capacity feature).
    - ``mode='mxu'`` (alias ``fused_native``): Dense kernels stay int8
      *through the matmul* — activations are dynamically quantized
      per-row and the contraction runs int8 x int8 -> int32 on the MXU
      (W8A8) via ``tpuflow.ops.int8_matmul``. A ``wte_q`` sibling leaf
      (when the model has a big tied ``wte``) carries the int8 LM head
      with per-vocab-row scales; apply hands it to the model as the
      ``quant`` collection, so the ``params`` tree the model sees keeps
      the exact fp structure it was initialized with. Non-Dense leaves
      (embedding gather, norms) are exact floats.

    Use: ``qm, qp = quantize_model(model, params)`` then pass
    ``(qm, qp)`` anywhere ``(model, params)`` went."""

    model: Any
    dtype: Any = None  # compute dtype for dequantized weights
    mode: str = "weight"
    # Pin of the int8 matmul implementation ('xla' | 'pallas'; None =
    # per-shape auto dispatch). Part of this hashable static arg, so two
    # wrappers pinned differently compile separate programs — the
    # fused-kernel-vs-interceptor numerics tests key on exactly that.
    int8_impl: str | None = None

    def apply(self, variables, *args, **kwargs):
        import flax.linen as nn

        from tpuflow.ops.int8_matmul import impl_override

        if self.mode == "mxu":
            import collections.abc

            params = variables.get("params", {})
            if isinstance(params, collections.abc.Mapping) and (
                "wte_q" in params
            ):
                # The quantized LM head travels inside the qparams tree
                # (one tree to device_put / shard / pass around) but the
                # model consumes it as its own collection — the params
                # structure the module tree declares stays untouched.
                variables = dict(variables)
                params = dict(params)
                variables["quant"] = {"wte_q": params.pop("wte_q")}
                variables["params"] = params
            with impl_override(self.int8_impl):
                with nn.intercept_methods(_int8_dense_interceptor):
                    return self.model.apply(variables, *args, **kwargs)
        variables = dict(variables)
        variables["params"] = dequantize_params(
            variables["params"], self.dtype
        )
        return self.model.apply(variables, *args, **kwargs)

    @property
    def config(self):
        return self.model.config


# Mode aliases: the bench's sub-leg names (weight_only / fused_native)
# resolve to the same two internal modes, so callers can speak either
# vocabulary (ISSUE 9: the bench records sub-legs under the alias names).
_MODE_ALIASES = {
    "weight": "weight",
    "weight_only": "weight",
    "mxu": "mxu",
    "native": "mxu",
    "fused_native": "mxu",
}


def canonical_mode(mode: str) -> str:
    """'weight' | 'mxu' from any accepted spelling; loud on unknowns."""
    try:
        return _MODE_ALIASES[mode]
    except (KeyError, TypeError):
        raise ValueError(
            f"unknown quantization mode {mode!r}; supported: "
            f"{sorted(_MODE_ALIASES)}"
        ) from None


def _quantize_dense_kernels(params, *, min_size: int, head: bool = True):
    """Quantize ONLY Dense-consumed ``kernel`` leaves (2-D, or 3-D
    scan-stacked — ``nn.scan`` slices the QuantLeaf's q and scale along
    the layer axis together), plus — when ``head`` and the tree has a
    big top-level ``wte`` — an int8 LM-head view ``wte_q`` with
    PER-VOCAB-ROW scales (the head contracts ``wte``'s last axis, so
    per-out-channel there means per vocab row, not the per-column
    layout ``quantize_params`` would pick). ``wte`` itself stays exact
    float: the embedding gather reads it directly. Everything else
    stays exact float too: the mxu interceptor handles Dense calls
    only, so a quantized non-Dense leaf would flow into ordinary float
    ops as a NamedTuple and fail."""

    def one(path, leaf):
        names = [str(getattr(p, "key", getattr(p, "name", ""))) for p in path]
        x = jnp.asarray(leaf)
        if (
            not names
            or names[-1] != "kernel"
            or x.ndim not in (2, 3)
            or x.size < min_size
            or not jnp.issubdtype(x.dtype, jnp.floating)
        ):
            return leaf
        # quantize_params tree_maps; on a bare array that is one leaf, so
        # the QuantLeaf comes back directly.
        return quantize_params(x, min_size=min_size)

    out = jax.tree_util.tree_map_with_path(one, params)
    if head:
        try:
            wte = jnp.asarray(params["wte"])
        except (KeyError, TypeError, IndexError):
            wte = None
        if (
            wte is not None
            and wte.ndim == 2
            and wte.size >= min_size
            and jnp.issubdtype(wte.dtype, jnp.floating)
        ):
            from tpuflow.ops.int8_matmul import quantize_rows

            q, scale = quantize_rows(wte)
            out = dict(out)
            out["wte_q"] = QuantLeaf(q, scale)
    return out


def quantize_model(
    model, params, *, min_size: int = 4096, dtype=None,
    mode: str = "weight", head: bool = True, int8_impl: str | None = None,
):
    """One-call form: returns ``(QuantizedModel, qparams)`` ready for
    ``generate(qm, qp, ...)`` / ``BatchPredictor`` / beam / speculative
    / ``ServeEngine``.

    ``mode='weight'`` (alias ``weight_only``) quantizes every large leaf
    and dequantizes inside jit; ``mode='mxu'`` (alias ``fused_native``)
    quantizes Dense kernels + the LM head (``head=False`` opts the head
    out) and keeps them int8 through the matmul (dynamic activation
    quantization, W8A8 — ``tpuflow.ops.int8_matmul``). ``int8_impl``
    pins the op's implementation ('xla' | 'pallas') for every matmul
    this wrapper traces; default per-shape auto dispatch."""
    mode = canonical_mode(mode)
    if mode == "mxu":
        return (
            QuantizedModel(model, dtype, mode, int8_impl),
            _quantize_dense_kernels(params, min_size=min_size, head=head),
        )
    return (
        QuantizedModel(model, dtype, mode, int8_impl),
        quantize_params(params, min_size=min_size),
    )


# Measured on chip (r4, TPU_EVIDENCE.json decode.int8): weight-only int8
# decode at GPT-2-124M/b8 ran 0.76x vs fp — the dequantized weights
# materialize as a per-step bf16 buffer, so below this resident-set size
# the halved weight stream never pays for the convert+write+read. The
# threshold is the smallest size where the capacity argument (fit a
# model that otherwise wouldn't, e.g. >= ~1 GiB float weights against
# v5e's 16 GiB HBM alongside caches + programs) outweighs the measured
# throughput loss.
WEIGHT_QUANT_MIN_BYTES = 1 << 30


@dataclasses.dataclass(frozen=True)
class QuantDecision:
    """Auto-gate verdict: whether quantization should be applied, with
    the measured rationale benchmarks record verbatim."""

    apply: bool
    mode: str
    reason: str
    weight_bytes: int


def quant_decision(params, *, mode: str = "weight") -> QuantDecision:
    """Policy gate for ``quantize_model``: weight-only quantization is
    OFF below ``WEIGHT_QUANT_MIN_BYTES`` of float weights (measured
    throughput regression, see constant above); mxu (fused-native W8A8)
    mode is ungated — its int8 operands never materialize as floats, so
    it has no size floor (each bench records its measured speedup
    alongside the teacher-forced agreement)."""
    mode = canonical_mode(mode)
    nbytes = sum(
        leaf.nbytes
        for leaf in jax.tree_util.tree_leaves(params)
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)
    )
    if mode == "mxu":
        return QuantDecision(
            True, mode,
            "fused-native (mxu, W8A8) mode: int8 operands feed the MXU "
            "directly through the fused quantize-matmul-dequant path, no "
            "dequant materialization — ungated at any size",
            nbytes,
        )
    if nbytes < WEIGHT_QUANT_MIN_BYTES:
        return QuantDecision(
            False, mode,
            f"weight-only int8 gated OFF: float weights {nbytes / 2**20:.0f}"
            f" MiB < {WEIGHT_QUANT_MIN_BYTES / 2**20:.0f} MiB threshold — "
            "measured 0.76x vs fp at 124M/b8 on v5e (r4): the per-step "
            "bf16 dequant buffer costs more than the halved weight "
            "stream saves below this size",
            nbytes,
        )
    return QuantDecision(
        True, mode,
        f"weight-only int8 ON: float weights {nbytes / 2**20:.0f} MiB >= "
        "threshold — resident-set halving dominates the dequant overhead",
        nbytes,
    )


def maybe_quantize(model, params, *, mode: str = "weight", dtype=None):
    """Gated form of ``quantize_model``: consults ``quant_decision`` and
    returns ``(model, params, decision)`` — unchanged model/params when
    the gate says quantization loses at this size. The verdict is
    recorded on the telemetry stream (``quant.decision``) so a run's
    events say which numeric path its decode actually took."""
    decision = quant_decision(params, mode=mode)
    from tpuflow import obs

    obs.event(
        "quant.decision",
        apply=decision.apply,
        mode=decision.mode,
        weight_mib=round(decision.weight_bytes / 2**20, 1),
        reason=decision.reason,
    )
    if not decision.apply:
        return model, params, decision
    qm, qp = quantize_model(model, params, mode=mode, dtype=dtype)
    return qm, qp, decision


@functools.partial(jax.jit, static_argnums=(0, 3))
def _tf_predict_jit(model, params, tokens, prompt_len: int):
    logits = model.apply({"params": params}, tokens)
    return jnp.argmax(logits[:, prompt_len - 1 : -1], axis=-1)


def teacher_forced_predictions(model, params, tokens, prompt_len: int):
    """Argmax next-token predictions under teacher forcing: one jitted
    forward over ``tokens`` (B, T), returning predictions at positions
    ``prompt_len-1 .. T-2`` — those that predict continuation tokens.
    Callers comparing one reference against several candidates compute
    the reference once and reuse it."""
    tokens = jnp.asarray(tokens, jnp.int32)
    if prompt_len < 1:
        raise ValueError(f"prompt_len must be >= 1, got {prompt_len}")
    if tokens.shape[1] <= prompt_len:
        raise ValueError("tokens must extend past prompt_len")
    return _tf_predict_jit(model, params, tokens, prompt_len)


def teacher_forced_agreement(
    model_ref, params_ref, model_test, params_test, tokens, prompt_len: int
):
    """Per-step top-1 agreement under teacher forcing: ONE full forward
    of each model over the SAME token sequence, comparing argmax
    next-token predictions at every continuation position.

    This separates quantization fidelity from cascade artifacts: free-
    running greedy agreement conflates one early near-tie flip (after
    which the sequences legitimately part ways) with genuinely bad
    quantization, while teacher forcing scores every step against the
    same context (VERDICT r4 weak #3/#7). ``tokens`` (B, T) should be
    prompt + reference continuation. Returns the agreement fraction in
    [0, 1]."""
    pa = teacher_forced_predictions(model_ref, params_ref, tokens, prompt_len)
    pb = teacher_forced_predictions(
        model_test, params_test, tokens, prompt_len
    )
    return float(jnp.mean((pa == pb).astype(jnp.float32)))
