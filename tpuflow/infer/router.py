"""Front-door router (ISSUE 17): fault-tolerant admission over the fleet.

The single client-facing ingress for a serving fleet. Everything here is
host-pure and jax-free — the router never touches a device; it consumes
the PR 14 fleet observatory's snapshot dicts and forwards requests to
replica ``/generate`` endpoints through an injectable ``forward_fn``.

Four policies compose per request:

- **Admission by fleet token budget.** A request needs
  ``pages_needed(prompt, max_new)`` KV pages somewhere. It dispatches
  only to a replica whose reported ``serve_pages_free`` minus the pages
  the router has already charged to in-flight work covers the need;
  until one exists the request WAITS in the front door's queue
  (backpressure queues, never drops — bounded by
  ``TPUFLOW_ROUTER_QUEUE_TIMEOUT_S``, after which the client gets an
  explicit 503, counted in ``router.reject``).
- **Balance by health x queue trend.** Among eligible replicas the pick
  maximizes ``route_score = health * decay^queue_trend`` — the PR 14
  health score damped geometrically by consecutive queue-growth polls,
  so a replica falling behind its arrivals sheds new work before its
  health ever moves. Ties break toward fewer router-outstanding
  requests.
- **Prefix affinity.** Prompts hash to the same sha1 page-chain digests
  PagePool uses (``prefix_digests`` here is bit-equal to
  ``PagePool.prefix_digests`` — pinned in tests), and the router
  remembers which replica last served each chain. A request sharing a
  prefix routes to the replica already holding those pages: a
  fleet-wide prefix cache with zero page movement.
- **Failover.** Each forward carries a per-replica timeout; failures
  (timeout, refused, 5xx) back the replica off exponentially and
  re-dispatch the request — to a DIFFERENT replica when one is
  eligible (``router.reroute``). Requests are idempotent by id: the
  client sees exactly one answer even when a replica dies mid-decode
  and a duplicate retry races the original. Retry budget exhausted →
  503, never a hang.

Drain-awareness rides on the PR 13 serve ledger: a SIGTERM'd replica
flips ``serve_draining`` in its /status, the fleet row carries it, and
the router stops admitting there the next refresh (``router.drain``
emitted once per flip). Its queued-but-unstarted work comes back as
replica 503s and re-routes through the normal retry path.

``AutoscaleController`` is the minimal replacement loop: stale replicas
and sustained occupancy/SLO pressure produce dedup'd, cooldown-limited
actions whose launch command seeds the replacement's compile cache via
``tools/prewarm_cache.py`` before it takes traffic.
"""

from __future__ import annotations

import hashlib
import importlib
import pathlib
import sys
import threading
import time
from collections import OrderedDict
from typing import Any, Callable

import numpy as np

from tpuflow.utils import knobs

# The obs package re-exports the recorder() accessor under the same
# name as its submodule; resolve the MODULE so _rec.event/_rec.gauge
# exist regardless of package-init order.
_rec = importlib.import_module("tpuflow.obs.recorder")


class FleetBusy(RuntimeError):
    """Admission-queue timeout or retry-budget exhaustion.

    The router's ONLY loss mode, and it is explicit: the front door
    maps it to HTTP 503 so the client knows to back off and retry.
    Nothing is ever silently dropped.
    """


# --------------------------------------------------------- pure policy
def prefix_digests(prompt: Any, page_size: int) -> list[bytes]:
    """Chain keys for every fully-covered prompt page — bit-equal to
    ``PagePool.prefix_digests`` (same int32 cast, same sha1-over-chain
    construction), so the router's affinity map speaks the replicas'
    prefix-cache language without importing the engine."""
    ps = int(page_size)
    if ps <= 0:
        return []
    p = np.asarray(prompt, np.int32).reshape(-1)
    return [
        hashlib.sha1(p[: (j + 1) * ps].tobytes()).digest()
        for j in range(p.size // ps)
    ]


def pages_needed(prompt_len: int, max_new_tokens: int, page_size: int) -> int:
    """KV pages a request can grow to — the admission charge."""
    total = int(prompt_len) + int(max_new_tokens)
    return max(1, -(-total // max(int(page_size), 1)))


def route_score(
    health: float, queue_trend: int, trend_decay: float
) -> float:
    """Balance score: health damped geometrically per consecutive
    queue-growth poll. health<=0 or huge trend → 0 (never negative)."""
    h = max(float(health), 0.0)
    t = max(int(queue_trend), 0)
    d = min(max(float(trend_decay), 0.0), 1.0)
    return h * (d ** t)


def row_tier_pages(row: dict) -> int:
    """Host + disk spill-tier pages a fleet row reports (ISSUE 19) —
    the warmth signal the decode tie-break prefers. Rows without tier
    counts score 0."""
    total = 0
    for key in ("serve_pages_host", "serve_pages_disk"):
        v = row.get(key)
        if isinstance(v, (int, float)):
            total += int(v)
    return total


# Bounded internal maps: the affinity map holds the most recent chain
# digests (LRU), the done-cache the most recent responses (idempotent
# replay window). Both are memory bounds, not correctness bounds.
AFFINITY_MAP_MAX = 8192
DONE_CACHE_MAX = 2048
_BACKOFF_CAP_S = 2.0


class Router:
    """The front door's brain: admission, pick, forward, retry.

    ``snapshot_fn`` returns the fleet observatory's snapshot dict
    (``{"fleet": {...}, "replicas": [rows]}``) and should be cheap —
    the production wiring (``frontdoor.main``, the router bench) hands
    in ``FleetPoller.snapshot``, a cached background sweep. Either way
    the router never holds its lock across the call, so even a slow
    snapshot_fn degrades to stale routing, not blocked routing.
    ``forward_fn(row, request, timeout_s)`` performs one
    forward attempt and RAISES on any failure (timeout, refused,
    non-200); its return value is the client's response. Clock and
    sleep are injectable so the retry/backoff state machine unit-tests
    without real waiting.
    """

    def __init__(
        self,
        snapshot_fn: Callable[[], dict],
        forward_fn: Callable[[dict, dict, float], dict],
        *,
        page_size: int | None = None,
        timeout_s: float | None = None,
        retries: int | None = None,
        backoff_s: float | None = None,
        affinity: bool | None = None,
        hedge: bool | None = None,
        ship_min_tokens: int | None = None,
        min_health: float | None = None,
        trend_decay: float | None = None,
        queue_timeout_s: float | None = None,
        refresh_s: float = 0.05,
        wait_tick_s: float = 0.02,
        autoscale: "AutoscaleController | None" = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if page_size is None:
            page_size = knobs.get_int("TPUFLOW_SERVE_PAGE_SIZE")
        if timeout_s is None:
            timeout_s = knobs.get_float("TPUFLOW_ROUTER_TIMEOUT_S")
        if retries is None:
            retries = knobs.get_int("TPUFLOW_ROUTER_RETRIES")
        if backoff_s is None:
            backoff_s = knobs.get_float("TPUFLOW_ROUTER_BACKOFF_S")
        if affinity is None:
            affinity = knobs.get_bool("TPUFLOW_ROUTER_AFFINITY")
        if hedge is None:
            hedge = knobs.get_bool("TPUFLOW_ROUTER_HEDGE")
        if ship_min_tokens is None:
            ship_min_tokens = knobs.get_int(
                "TPUFLOW_KV_SHIP_MIN_TOKENS"
            )
        if min_health is None:
            min_health = knobs.get_float("TPUFLOW_ROUTER_MIN_HEALTH")
        if trend_decay is None:
            trend_decay = knobs.get_float("TPUFLOW_ROUTER_TREND_DECAY")
        if queue_timeout_s is None:
            queue_timeout_s = knobs.get_float(
                "TPUFLOW_ROUTER_QUEUE_TIMEOUT_S"
            )
        self._snapshot_fn = snapshot_fn
        self._forward = forward_fn
        self.page_size = int(page_size)
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.affinity = bool(affinity)
        self.hedge = bool(hedge)
        self.ship_min_tokens = int(ship_min_tokens)
        self.min_health = float(min_health)
        self.trend_decay = float(trend_decay)
        self.queue_timeout_s = float(queue_timeout_s)
        self.refresh_s = float(refresh_s)
        self.wait_tick_s = float(wait_tick_s)
        self._autoscale = autoscale
        self._clock = clock
        self._sleep = sleep
        self._cond = threading.Condition()
        self._rows: dict[str, dict] = {}
        self._refreshing = False
        self._last_refresh = float("-inf")
        self._last_budget = 0
        self._draining: set[str] = set()
        self._backoff_until: dict[str, float] = {}
        self._charged: dict[str, int] = {}
        self._outstanding: dict[str, int] = {}
        self._affinity_map: OrderedDict[bytes, str] = OrderedDict()
        self._done: OrderedDict[str, dict] = OrderedDict()
        self._inflight: dict[str, threading.Event] = {}
        self._waiting = 0
        self._counters = {
            "accepted": 0, "requests": 0, "rejected": 0, "retries": 0,
            "reroutes": 0, "affinity_hits": 0, "drains": 0,
            # Disaggregated serving (ISSUE 19): prefill hops shipped
            # to a role=="prefill" replica, and ship attempts that
            # fell back to the decode replica's local prefill.
            "ships": 0, "ship_fallbacks": 0,
            # Cumulative router-side admission wait (seconds, successful
            # picks only — deterministic for the alert oracle tests).
            # The ttft_router_dominance rule divides its window delta by
            # the router_requests delta for mean wait per request.
            "wait_s": 0.0,
        }

    # ------------------------------------------------------- snapshot
    def refresh(self, force: bool = False) -> None:
        """Pull the fleet snapshot (throttled by ``refresh_s``), detect
        drain flips, re-gauge the admission budget, feed the autoscale
        loop, and wake admission waiters."""
        with self._cond:
            self._refresh_locked(force=force)

    def _refresh_locked(self, force: bool = False) -> None:
        """Caller holds ``self._cond`` exactly once. The snapshot fetch
        itself runs with the lock RELEASED: even a cheap cached
        snapshot_fn must never head-of-line-block the admission
        waiters, retries, and completion bookkeeping that all tick this
        condition — and a slow one (an observatory sweep handed in
        directly) would otherwise freeze all routing exactly when the
        fleet is degraded. ``_refreshing`` keeps the fetch
        single-flight; everyone else routes on the cached view."""
        now = self._clock()
        if self._refreshing:
            return  # another thread is mid-fetch; use the cached view
        if not force and now - self._last_refresh < self.refresh_s:
            return
        self._refreshing = True
        self._last_refresh = now
        self._cond.release()
        try:
            snap = self._snapshot_fn() or {}
        except Exception:
            snap = None  # keep routing on the last good snapshot
        finally:
            self._cond.acquire()
            self._refreshing = False
        if snap is None:
            return
        rows = snap.get("replicas") or []
        self._rows = {
            str(r.get("id")): r for r in rows if r.get("id")
        }
        for rid, row in self._rows.items():
            d = bool(row.get("serve_draining"))
            if d and rid not in self._draining:
                self._draining.add(rid)
                self._counters["drains"] += 1
                _rec.event("router.drain", replica=rid)
            elif not d and rid in self._draining:
                self._draining.discard(rid)
        budget = 0
        for rid, row in self._rows.items():
            if self._routable(row, now) is None:
                continue
            # A prefill-role replica (ISSUE 19) takes ship hops, not
            # admissions — its pages are not decode budget.
            if row.get("serve_role") == "prefill":
                continue
            free = row.get("serve_pages_free")
            if isinstance(free, (int, float)):
                budget += max(
                    int(free) - self._charged.get(rid, 0), 0
                )
        self._last_budget = budget
        _rec.gauge("router.budget_pages", budget)
        if self._autoscale is not None:
            self._autoscale.consider(snap)
        self._cond.notify_all()

    def _routable(self, row: dict, now: float) -> float | None:
        """Health score if the replica may take NEW work, else None."""
        rid = str(row.get("id"))
        if row.get("stale") or row.get("serve_draining"):
            return None
        if self._backoff_until.get(rid, float("-inf")) > now:
            return None
        h = row.get("health")
        if not isinstance(h, (int, float)) or h < self.min_health:
            return None
        return float(h)

    def _pick_locked(
        self, need: int, digests: list[bytes], tried: set[str],
        now: float,
    ) -> tuple[str, dict, bool] | None:
        """(replica id, row, affinity-hit) or None when nothing can
        take ``need`` pages right now."""
        elig: list[tuple[str, dict, float]] = []
        for rid, row in self._rows.items():
            h = self._routable(row, now)
            if h is None:
                continue
            # Decode placement skips prefill-role rows (ISSUE 19):
            # those take the ship hop, never the request itself.
            if row.get("serve_role") == "prefill":
                continue
            free = row.get("serve_pages_free")
            if not isinstance(free, (int, float)):
                continue
            if int(free) - self._charged.get(rid, 0) < need:
                continue
            elig.append((rid, row, h))
        if not elig:
            return None
        # A replica that already failed this request is a last resort.
        pool = [e for e in elig if e[0] not in tried] or elig
        if self.affinity and digests:
            by_id = {e[0]: e for e in pool}
            for dg in reversed(digests):
                owner = self._affinity_map.get(dg)
                if owner in by_id:
                    rid, row, _h = by_id[owner]
                    return rid, row, True
        rid, row, _h = max(
            pool,
            key=lambda e: (
                route_score(
                    e[2], e[1].get("queue_trend", 0), self.trend_decay
                ),
                -self._outstanding.get(e[0], 0),
                # Warmer spill tiers break the remaining tie (ISSUE
                # 19): more host/disk pages means more promotable
                # prefixes, so equal-score picks land where a lower
                # tier might save a prefill. Tier-less fleets report
                # 0 everywhere — the ordering is unchanged.
                int(row_tier_pages(e[1])),
                e[0],
            ),
        )
        return rid, row, False

    def _pick_prefill_locked(self, now: float) -> dict | None:
        """Healthiest routable prefill-role row, or None. The ship hop
        is best-effort: no candidate simply means local prefill."""
        best: tuple[float, str, dict] | None = None
        for rid, row in self._rows.items():
            if row.get("serve_role") != "prefill":
                continue
            h = self._routable(row, now)
            if h is None:
                continue
            if best is None or (h, rid) > (best[0], best[1]):
                best = (h, rid, row)
        return None if best is None else best[2]

    def _maybe_ship(
        self, rid: str, prompt: Any, request: dict
    ) -> dict:
        """Disaggregated prefill hop (ISSUE 19). Prompts of at least
        ``ship_min_tokens`` take one best-effort forward to a
        role=="prefill" replica — ``{"phase": "prefill"}`` runs a
        chunked prefill there and commits the KV pages as a tiny
        checkpoint — and the decode forward carries the returned
        ``kv_key`` so the decode replica imports pages instead of
        recomputing them. EVERY failure mode (no prefill capacity, a
        dead replica mid-ship, a gateway without a kv store, a torn
        commit) degrades to the unmodified request: the decode replica
        prefills locally and the answer is unaffected — counted in
        ``router_ship_fallbacks`` so the degradation is observable."""
        if self.ship_min_tokens <= 0 or len(prompt) < self.ship_min_tokens:
            return request
        ctx = request.get("_trace_ctx")
        now = self._clock()
        with self._cond:
            self._refresh_locked()
            prow = self._pick_prefill_locked(now)
        t0 = self._clock()
        wall = time.time()
        key = None
        err = "no_prefill_replica"
        if prow is not None:
            ship_req = {
                "id": f"{rid}#prefill",
                "phase": "prefill",
                "prompt": [int(t) for t in prompt],
            }
            if request.get("quantize") is not None:
                ship_req["quantize"] = bool(request.get("quantize"))
            try:
                resp = self._forward(prow, ship_req, self.timeout_s)
                key = resp.get("kv_key") or None
                if key is None:
                    err = "no kv_key in prefill response"
            except Exception as e:  # noqa: BLE001 — ship is optional
                err = str(e)[:200]
        if ctx is not None:
            ctx.add_span(
                "router.ship",
                ts=wall,
                dur_s=self._clock() - t0,
                parent=ctx.root_id,
                ok=key is not None,
                **(
                    {"replica": str(prow.get("id"))}
                    if prow is not None else {}
                ),
                **({} if key is not None else {"error": err}),
            )
        if key is None:
            with self._cond:
                self._counters["ship_fallbacks"] += 1
            _rec.event(
                "router.ship_fallback",
                request=rid,
                reason=err,
            )
            return request
        with self._cond:
            self._counters["ships"] += 1
        _rec.event(
            "router.ship",
            request=rid,
            replica=str(prow.get("id")),
            key=str(key),
        )
        out = dict(request)
        out["kv_key"] = str(key)
        return out

    # ----------------------------------------------------------- route
    def route(self, request: dict) -> dict:
        """Admit, pick, forward — with bounded retry — one request.

        ``request`` needs ``id`` (idempotency key), ``prompt`` (token
        id list) and ``max_new_tokens``; everything else passes through
        to the replica. Returns the replica's response dict. Raises
        ``FleetBusy`` (503) on admission timeout or retry exhaustion,
        ``ValueError`` on a malformed request.
        """
        rid = str(request.get("id") or "")
        if not rid:
            raise ValueError("request needs a non-empty id")
        # Malformed requests surface as ValueError HERE, before the
        # accepted counter moves — the front door maps it to 400, and
        # nothing else (a TypeError from an int() cast, a numpy refusal
        # on a ragged prompt) can escape route() as a non-contract
        # exception or skew the zero-drop accounting.
        try:
            prompt = np.asarray(
                request.get("prompt"), np.int32
            ).reshape(-1)
        except (TypeError, ValueError, OverflowError) as e:
            raise ValueError(
                f"prompt must be a list of token ids ({e})"
            ) from e
        if prompt.size == 0:
            raise ValueError("request needs a non-empty prompt")
        try:
            max_new = int(request.get("max_new_tokens") or 1)
        except (TypeError, ValueError) as e:
            raise ValueError(
                "max_new_tokens must be an integer, got "
                f"{request.get('max_new_tokens')!r}"
            ) from e
        while True:
            with self._cond:
                done = self._done.get(rid)
                if done is not None:
                    return dict(done)  # idempotent replay
                ev = self._inflight.get(rid)
                if ev is None:
                    self._inflight[rid] = ev = threading.Event()
                    break
            # A duplicate of an in-flight id: wait for the original,
            # then replay its answer (or become the new original if it
            # failed — the client's retry deserves a fresh attempt).
            ev.wait(
                timeout=self.queue_timeout_s
                + (self.retries + 1) * (self.timeout_s + _BACKOFF_CAP_S)
            )
        try:
            resp = self._route_once(rid, prompt, max_new, request)
            with self._cond:
                self._done[rid] = resp
                while len(self._done) > DONE_CACHE_MAX:
                    self._done.popitem(last=False)
            return dict(resp)
        finally:
            with self._cond:
                self._inflight.pop(rid, None)
            ev.set()

    def _route_once(
        self, rid: str, prompt: Any, max_new: int, request: dict
    ) -> dict:
        need = pages_needed(len(prompt), max_new, self.page_size)
        digests = (
            prefix_digests(prompt, self.page_size)
            if self.affinity else []
        )
        with self._cond:
            self._counters["accepted"] += 1
        request = self._maybe_ship(rid, prompt, request)
        attempt = 0
        tried: set[str] = set()
        last_replica: str | None = None
        last_err = "no eligible replica"
        queued_at = self._clock()
        # End-to-end tracing (ISSUE 18): the FrontDoor parks the minted
        # TraceContext under "_trace_ctx" (http_forward strips it — the
        # wire carries only the traceparent header). Untraced callers
        # pay one dict.get per request.
        ctx = request.get("_trace_ctx")
        prev_span: str | None = None
        while True:
            # ---- admission: wait (bounded) for a placeable replica
            deadline = self._clock() + self.queue_timeout_s
            wait_t0 = self._clock()
            with self._cond:
                self._waiting += 1
                _rec.gauge("router.queue_depth", self._waiting)
                try:
                    while True:
                        self._refresh_locked()
                        now = self._clock()
                        picked = self._pick_locked(
                            need, digests, tried, now
                        )
                        if picked is not None:
                            break
                        if now >= deadline:
                            self._counters["rejected"] += 1
                            _rec.event(
                                "router.reject",
                                request=rid,
                                reason="queue_timeout",
                                attempts=attempt,
                                pages=need,
                                last_error=str(last_err)[:200],
                            )
                            if ctx is not None:
                                ctx.escalate("queue_timeout")
                                dur = now - wait_t0
                                ctx.add_span(
                                    "router.queue",
                                    ts=time.time() - dur,
                                    dur_s=dur,
                                    parent=ctx.root_id,
                                    attempt=attempt,
                                )
                                ctx.add_span(
                                    "router.reject",
                                    ts=time.time(),
                                    parent=prev_span or ctx.root_id,
                                    reason="queue_timeout",
                                    attempts=attempt,
                                )
                            raise FleetBusy(
                                f"no fleet budget for {need} pages "
                                f"within {self.queue_timeout_s:.1f}s "
                                f"({last_err})"
                            )
                        self._cond.wait(
                            timeout=min(
                                self.wait_tick_s, deadline - now
                            )
                        )
                finally:
                    self._waiting -= 1
                    _rec.gauge("router.queue_depth", self._waiting)
                replica_id, row, affine = picked
                self._counters["wait_s"] += max(now - wait_t0, 0.0)
                self._charged[replica_id] = (
                    self._charged.get(replica_id, 0) + need
                )
                self._outstanding[replica_id] = (
                    self._outstanding.get(replica_id, 0) + 1
                )
            if affine:
                with self._cond:
                    self._counters["affinity_hits"] += 1
            rerouted = attempt > 0 and replica_id != last_replica
            if rerouted:
                with self._cond:
                    self._counters["reroutes"] += 1
                _rec.event(
                    "router.reroute",
                    request=rid,
                    attempt=attempt,
                    replica=replica_id,
                    failed=last_replica,
                )
                if ctx is not None:
                    # A reroute is tail-sampled: never lost to the
                    # head sampler.
                    ctx.escalate("reroute")
            if ctx is not None:
                dur = self._clock() - wait_t0
                ctx.add_span(
                    "router.queue",
                    ts=time.time() - dur,
                    dur_s=dur,
                    parent=ctx.root_id,
                    attempt=attempt,
                )
            _rec.event(
                "router.admit",
                request=rid,
                replica=replica_id,
                pages=need,
                attempt=attempt,
                affinity=affine,
                queue_wait_s=round(self._clock() - queued_at, 4),
            )
            # ---- forward (no router lock held across the network)
            fwd_span = None
            if ctx is not None:
                # Pre-assign this attempt's span id and make it the
                # propagation span: the replica's hop parents to the
                # exact forward attempt that carried it, and each
                # attempt links causally to the prior one.
                fwd_span = ctx.new_span_id()
                ctx.span_id = fwd_span
            fwd_t0 = self._clock()
            fwd_wall = time.time()
            try:
                resp = self._forward(row, request, self.timeout_s)
            except Exception as e:
                last_err = e
                attempt += 1
                with self._cond:
                    self._charged[replica_id] -= need
                    self._outstanding[replica_id] -= 1
                    self._counters["retries"] += 1
                    self._backoff_until[replica_id] = (
                        self._clock() + min(
                            self.backoff_s * (2 ** (attempt - 1)),
                            _BACKOFF_CAP_S,
                        )
                    )
                    self._cond.notify_all()
                tried.add(replica_id)
                last_replica = replica_id
                if ctx is not None:
                    ctx.escalate("error")
                    will_sleep = not (self.hedge and attempt == 1)
                    ctx.add_span(
                        "router.forward",
                        span_id=fwd_span,
                        parent=prev_span or ctx.root_id,
                        ts=fwd_wall,
                        dur_s=self._clock() - fwd_t0,
                        attempt=attempt - 1,
                        replica=replica_id,
                        ok=False,
                        error=str(e)[:200],
                        backoff_s=(
                            min(
                                self.backoff_s * (2 ** (attempt - 1)),
                                _BACKOFF_CAP_S,
                            )
                            if will_sleep and attempt <= self.retries
                            else 0.0
                        ),
                    )
                    prev_span = fwd_span
                if attempt > self.retries:
                    with self._cond:
                        self._counters["rejected"] += 1
                    _rec.event(
                        "router.reject",
                        request=rid,
                        reason="retries_exhausted",
                        attempts=attempt,
                        error=str(e)[:200],
                    )
                    if ctx is not None:
                        ctx.add_span(
                            "router.reject",
                            ts=time.time(),
                            parent=prev_span or ctx.root_id,
                            reason="retries_exhausted",
                            attempts=attempt,
                        )
                    raise FleetBusy(
                        f"retry budget ({self.retries}) exhausted: {e}"
                    ) from e
                _rec.event(
                    "router.retry",
                    request=rid,
                    attempt=attempt,
                    replica=replica_id,
                    error=str(e)[:200],
                )
                if not (self.hedge and attempt == 1):
                    self._sleep(
                        min(
                            self.backoff_s * (2 ** (attempt - 1)),
                            _BACKOFF_CAP_S,
                        )
                    )
                continue
            # ---- success
            if ctx is not None:
                ctx.add_span(
                    "router.forward",
                    span_id=fwd_span,
                    parent=prev_span or ctx.root_id,
                    ts=fwd_wall,
                    dur_s=self._clock() - fwd_t0,
                    attempt=attempt,
                    replica=replica_id,
                    ok=True,
                    reroute=rerouted,
                )
            with self._cond:
                self._charged[replica_id] -= need
                self._outstanding[replica_id] -= 1
                self._counters["requests"] += 1
                for dg in digests:
                    self._affinity_map[dg] = replica_id
                    self._affinity_map.move_to_end(dg)
                while len(self._affinity_map) > AFFINITY_MAP_MAX:
                    self._affinity_map.popitem(last=False)
                self._cond.notify_all()
            return resp

    # ----------------------------------------------------------- stats
    def stats(self) -> dict[str, Any]:
        """``router_*`` counters for /status — the alert engine's
        reroute_spike rule and the chaos harness's zero-drop audit both
        read exactly these keys. ``router_dropped`` is accepted work
        that is neither answered, rejected, nor still in flight — the
        invariant the chaos bench asserts is 0."""
        with self._cond:
            c = dict(self._counters)
            inflight = len(self._inflight)
            return {
                "router_requests": c["requests"],
                "router_accepted": c["accepted"],
                "router_rejected": c["rejected"],
                "router_retries": c["retries"],
                "router_reroutes": c["reroutes"],
                "router_affinity_hits": c["affinity_hits"],
                "router_drains": c["drains"],
                "router_ships": c["ships"],
                "router_ship_fallbacks": c["ship_fallbacks"],
                "router_inflight": inflight,
                "router_queue_depth": self._waiting,
                "router_budget_pages": self._last_budget,
                "router_wait_s": round(c["wait_s"], 6),
                "router_dropped": max(
                    c["accepted"] - c["requests"] - c["rejected"]
                    - inflight,
                    0,
                ),
            }


# ----------------------------------------------------------- autoscale
def launch_command(action: str, replica_id: str) -> list[str]:
    """argv that seeds a replacement replica's compile cache before it
    takes traffic (the supervisor appends its serve/export flags). A
    replacement that skips this recompiles under live load — exactly
    the failure mode prewarming exists to prevent. The script path
    resolves relative to the package checkout (``tools/`` beside
    ``tpuflow/``), never the caller's cwd — autoscale launches fire
    from a router pod whose working directory is not the repo root."""
    script = (
        pathlib.Path(__file__).resolve().parents[2]
        / "tools" / "prewarm_cache.py"
    )
    return [
        sys.executable, str(script),
        "--no-train", "--allow-cpu",
    ]


class AutoscaleController:
    """Minimal replacement/scale-up loop over fleet snapshots.

    Stateless policy, stateful dedup: each (action, key) pair fires at
    most once per ``cooldown_s`` — replacements must not flap faster
    than pods can start. ``launch`` is injectable (tests capture
    actions; production hands them to a process/pod supervisor);
    without one the controller still records and emits
    ``router.replace`` so the decision trail exists either way.
    """

    def __init__(
        self,
        launch: Callable[[dict], None] | None = None,
        *,
        enabled: bool | None = None,
        occ_high: float | None = None,
        slo_rate_max: float | None = None,
        cooldown_s: float | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if enabled is None:
            enabled = knobs.get_bool("TPUFLOW_ROUTER_AUTOSCALE")
        if occ_high is None:
            occ_high = knobs.get_float("TPUFLOW_ROUTER_AUTOSCALE_OCC")
        if slo_rate_max is None:
            slo_rate_max = knobs.get_float(
                "TPUFLOW_ROUTER_AUTOSCALE_SLO"
            )
        if cooldown_s is None:
            cooldown_s = knobs.get_float(
                "TPUFLOW_ROUTER_AUTOSCALE_COOLDOWN_S"
            )
        self.enabled = bool(enabled)
        self.occ_high = float(occ_high)
        self.slo_rate_max = float(slo_rate_max)
        self.cooldown_s = float(cooldown_s)
        self._launch = launch
        self._clock = clock
        self._last_action: dict[str, float] = {}
        self._prev: tuple[float, float] | None = None
        self.actions: list[dict] = []

    def consider(self, snapshot: dict) -> list[dict]:
        """One policy sweep over a fleet snapshot; returns the actions
        THIS sweep caused (each also recorded on ``self.actions``)."""
        if not self.enabled:
            return []
        now = self._clock()
        out: list[dict] = []
        fleet = snapshot.get("fleet") or {}
        for row in snapshot.get("replicas") or []:
            if row.get("stale"):
                a = self._act(
                    "replace", str(row.get("id")), "stale", now
                )
                if a:
                    out.append(a)
        occ = fleet.get("slot_occupancy")
        if isinstance(occ, (int, float)) and occ > self.occ_high:
            a = self._act(
                "scale_up", "_fleet", f"occupancy {occ:.2f}", now
            )
            if a:
                out.append(a)
        req = fleet.get("requests")
        vio = fleet.get("slo_violations")
        if isinstance(req, (int, float)) and isinstance(
            vio, (int, float)
        ):
            if self._prev is not None:
                d_req = float(req) - self._prev[0]
                d_vio = float(vio) - self._prev[1]
                if d_req > 0 and d_vio / d_req > self.slo_rate_max:
                    a = self._act(
                        "scale_up", "_fleet",
                        f"slo_rate {d_vio / d_req:.3f}", now,
                    )
                    if a:
                        out.append(a)
            self._prev = (float(req), float(vio))
        return out

    def _act(
        self, action: str, key: str, reason: str, now: float
    ) -> dict | None:
        dedup = f"{action}:{key}"
        if (
            now - self._last_action.get(dedup, float("-inf"))
            < self.cooldown_s
        ):
            return None
        self._last_action[dedup] = now
        rec = {
            "action": action,
            "replica": key,
            "reason": reason,
            "command": launch_command(action, key),
        }
        _rec.event(
            "router.replace", action=action, replica=key, reason=reason
        )
        if self._launch is not None:
            try:
                self._launch(rec)
            except Exception as e:
                rec["error"] = str(e)[:200]
        self.actions.append(rec)
        return rec
