"""Per-sequence log-likelihood scoring for the LM family.

The third leg of LM inference next to batch classification and sampling:
``sequence_logprob`` returns each sequence's total (or mean) token
log-likelihood under the model — the primitive behind reranking,
best-of-n selection, and data filtering. One jitted forward per batch;
works on padded batches via a token mask.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def best_of_n(
    model,
    params,
    prompt,
    *,
    n: int,
    max_new_tokens: int,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    rng=None,
    per_token: bool = True,
):
    """Sample ``n`` continuations per prompt row and return the one the
    model itself scores highest.

    The standard rerank loop composed from the two inference primitives:
    ONE ``generate`` call over the (B*n)-row tiled prompt (each row draws
    independently), one ``sequence_logprob`` pass scoring only the
    continuation tokens (the prompt conditions but is masked out of the
    score — leading real context, so the mask semantics are exact), then an
    argmax per original row. Returns ``(tokens (B, max_new_tokens),
    logprob (B,))``. ``per_token=True`` compares length-normalized scores.
    Plain sampling only: there is no eos/pad handling here — every
    continuation token is scored (fixed-length candidates).
    """
    from tpuflow.infer.generate import generate

    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    prompt = jnp.asarray(prompt, jnp.int32)
    B, T = prompt.shape
    tiled = jnp.repeat(prompt, n, axis=0)
    conts = generate(
        model,
        params,
        tiled,
        max_new_tokens=max_new_tokens,
        temperature=temperature,
        top_k=top_k,
        top_p=top_p,
        rng=rng,
    )
    full = jnp.concatenate([tiled, conts], axis=1)
    mask = jnp.concatenate(
        [
            jnp.zeros((B * n, T), jnp.float32),
            jnp.ones((B * n, max_new_tokens), jnp.float32),
        ],
        axis=1,
    )
    scores = sequence_logprob(
        model, params, full, mask=mask, per_token=per_token
    ).reshape(B, n)
    best = jnp.argmax(scores, axis=-1)
    picked = conts.reshape(B, n, max_new_tokens)[jnp.arange(B), best]
    return picked, scores[jnp.arange(B), best]


@functools.partial(jax.jit, static_argnums=(0,), static_argnames=("per_token",))
def _score_jit(model, params, tokens, mask, *, per_token: bool):
    logits = model.apply({"params": params}, tokens[:, :-1])
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    targets = tokens[:, 1:]
    picked = jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    m = mask[:, 1:].astype(picked.dtype)
    total = jnp.sum(picked * m, axis=-1)
    if per_token:
        return total / jnp.maximum(jnp.sum(m, axis=-1), 1.0)
    return total


def sequence_logprob(model, params, tokens, *, mask=None, per_token=False):
    """log p(tokens[:, 1:] | prefixes) per sequence.

    ``tokens``: (B, T) int32. ``mask``: optional (B, T) {0,1} — position i
    contributes iff ``mask[i] == 1``. The mask gates CONTRIBUTIONS only,
    not attention: masked tokens still sit in the causal context, so it is
    exact for RIGHT-padded batches (trailing pad never precedes a scored
    token — pinned by test) but NOT for left-padded or interior-masked
    sequences; right-align ragged batches before scoring. The first token
    never contributes (it is only conditioned on). ``per_token=True``
    returns the mean instead of the sum (length-normalized scores for
    comparing sequences of different lengths). Returns (B,) float32.
    """
    tokens = jnp.asarray(tokens, jnp.int32)
    if mask is None:
        mask = jnp.ones(tokens.shape, jnp.float32)
    else:
        mask = jnp.asarray(mask, jnp.float32)
        if mask.shape != tokens.shape:
            raise ValueError(
                f"mask shape {mask.shape} != tokens shape {tokens.shape}"
            )
    return _score_jit(model, params, tokens, mask, per_token=per_token)
