"""Per-sequence log-likelihood scoring for the LM family.

The third leg of LM inference next to batch classification and sampling:
``sequence_logprob`` returns each sequence's total (or mean) token
log-likelihood under the model — the primitive behind reranking,
best-of-n selection, and data filtering. One jitted forward per batch;
works on padded batches via a token mask.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnums=(0,), static_argnames=("per_token",))
def _score_jit(model, params, tokens, mask, *, per_token: bool):
    logits = model.apply({"params": params}, tokens[:, :-1])
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    targets = tokens[:, 1:]
    picked = jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    m = mask[:, 1:].astype(picked.dtype)
    total = jnp.sum(picked * m, axis=-1)
    if per_token:
        return total / jnp.maximum(jnp.sum(m, axis=-1), 1.0)
    return total


def sequence_logprob(model, params, tokens, *, mask=None, per_token=False):
    """log p(tokens[:, 1:] | prefixes) per sequence.

    ``tokens``: (B, T) int32. ``mask``: optional (B, T) {0,1} — position i
    contributes iff ``mask[i] == 1``. The mask gates CONTRIBUTIONS only,
    not attention: masked tokens still sit in the causal context, so it is
    exact for RIGHT-padded batches (trailing pad never precedes a scored
    token — pinned by test) but NOT for left-padded or interior-masked
    sequences; right-align ragged batches before scoring. The first token
    never contributes (it is only conditioned on). ``per_token=True``
    returns the mean instead of the sum (length-normalized scores for
    comparing sequences of different lengths). Returns (B,) float32.
    """
    tokens = jnp.asarray(tokens, jnp.int32)
    if mask is None:
        mask = jnp.ones(tokens.shape, jnp.float32)
    else:
        mask = jnp.asarray(mask, jnp.float32)
        if mask.shape != tokens.shape:
            raise ValueError(
                f"mask shape {mask.shape} != tokens shape {tokens.shape}"
            )
    return _score_jit(model, params, tokens, mask, per_token=per_token)
