"""Per-sequence log-likelihood scoring for the LM family.

The third leg of LM inference next to batch classification and sampling:
``sequence_logprob`` returns each sequence's total (or mean) token
log-likelihood under the model — the primitive behind reranking,
best-of-n selection, and data filtering. One jitted forward per batch;
works on padded batches via a token mask.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def best_of_n(
    model,
    params,
    prompt,
    *,
    n: int,
    max_new_tokens: int,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    rng=None,
    per_token: bool = True,
    eos_id: int | None = None,
    pad_id: int = 0,
    prompt_lens=None,
):
    """Sample ``n`` continuations per prompt row and return the one the
    model itself scores highest.

    The standard rerank loop composed from the two inference primitives:
    ONE ``generate`` call over the (B*n)-row tiled prompt (each row draws
    independently), one ``sequence_logprob`` pass scoring only the
    continuation tokens (the prompt conditions but is masked out of the
    score), then an argmax per original row. Returns ``(tokens (B,
    max_new_tokens), logprob (B,))``. ``per_token=True`` compares
    length-normalized scores.

    With ``eos_id`` set, candidates are variable-length: generation freezes
    a row to ``pad_id`` after its eos, and scoring counts each candidate's
    tokens up to AND INCLUDING its eos — trailing pad contributes nothing,
    so a short confident answer competes fairly against a long one under
    ``per_token``. Ragged prompts ride ``prompt_lens`` (LEFT-padded batch,
    see ``generate``/``pad_ragged``); both the sampling and the scoring
    pass then mask the pad columns, keeping mixed-length reranking
    token-exact vs per-row calls.
    """
    from tpuflow.infer.generate import generate

    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    prompt = jnp.asarray(prompt, jnp.int32)
    B, T = prompt.shape
    tiled = jnp.repeat(prompt, n, axis=0)
    tiled_lens = None
    pad_lens_full = None
    if prompt_lens is not None:
        import numpy as np

        tiled_lens = np.repeat(np.asarray(prompt_lens, np.int32), n, axis=0)
        pad_lens_full = jnp.asarray(T - tiled_lens, jnp.int32)
    conts = generate(
        model,
        params,
        tiled,
        max_new_tokens=max_new_tokens,
        temperature=temperature,
        top_k=top_k,
        top_p=top_p,
        rng=rng,
        eos_id=eos_id,
        pad_id=pad_id,
        prompt_lens=tiled_lens,
    )
    full = jnp.concatenate([tiled, conts], axis=1)
    cont_mask = jnp.ones((B * n, max_new_tokens), jnp.float32)
    if eos_id is not None:
        # Score through the first eos (inclusive); freeze-padded tail out.
        from tpuflow.infer.generate import after_first_true

        cont_mask = jnp.where(
            after_first_true(conts == eos_id), 0.0, cont_mask
        )
    mask = jnp.concatenate(
        [jnp.zeros((B * n, T), jnp.float32), cont_mask], axis=1
    )
    scores = sequence_logprob(
        model, params, full, mask=mask, per_token=per_token,
        pad_lens=pad_lens_full,
    ).reshape(B, n)
    best = jnp.argmax(scores, axis=-1)
    picked = conts.reshape(B, n, max_new_tokens)[jnp.arange(B), best]
    return picked, scores[jnp.arange(B), best]


@functools.partial(jax.jit, static_argnums=(0,), static_argnames=("per_token",))
def _score_jit(model, params, tokens, mask, pad_lens=None, *, per_token: bool):
    logits = model.apply(
        {"params": params}, tokens[:, :-1], pad_lens=pad_lens
    )
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    targets = tokens[:, 1:]
    picked = jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    m = mask[:, 1:].astype(picked.dtype)
    total = jnp.sum(picked * m, axis=-1)
    if per_token:
        return total / jnp.maximum(jnp.sum(m, axis=-1), 1.0)
    return total


def sequence_logprob(
    model, params, tokens, *, mask=None, per_token=False, pad_lens=None,
    prompt_lens=None,
):
    """log p(tokens[:, 1:] | prefixes) per sequence.

    ``tokens``: (B, T) int32. ``mask``: optional (B, T) {0,1} — position i
    contributes iff ``mask[i] == 1``. The mask gates CONTRIBUTIONS only,
    not attention: masked tokens still sit in the causal context, so on its
    own it is exact for RIGHT-padded batches (trailing pad never precedes a
    scored token — pinned by test) but not for left-padded sequences. For
    LEFT-padded batches pass ``prompt_lens`` (B,) real lengths — the
    ``pad_ragged`` convention, matching ``generate`` — or equivalently
    ``pad_lens`` (B,) pad counts (``T - prompt_lens``); the model then
    masks pad columns out of attention and shifts positions per row
    (models.gpt2), making mixed-length scoring token-exact vs per-row dense
    calls. The first (real) token never contributes (it is only conditioned
    on). ``per_token=True`` returns the mean instead of the sum
    (length-normalized scores for comparing sequences of different
    lengths). Returns (B,) float32.
    """
    tokens = jnp.asarray(tokens, jnp.int32)
    T = tokens.shape[1]
    if prompt_lens is not None:
        if pad_lens is not None:
            raise ValueError("pass prompt_lens or pad_lens, not both")
        from tpuflow.infer.generate import prompt_lens_to_pad_lens

        pad_lens = prompt_lens_to_pad_lens(
            prompt_lens, tokens.shape[0], T
        )
    elif pad_lens is not None:
        import numpy as np

        pl = np.asarray(pad_lens, np.int32)
        if (pl < 0).any() or (pl >= T).any():
            raise ValueError(
                f"pad_lens must be in [0, {T - 1}], got "
                f"[{pl.min()}, {pl.max()}]"
            )
    if mask is None:
        if pad_lens is not None:
            # Default for left-padded rows: score real positions only,
            # EXCLUDING each row's first real token — like column 0 of a
            # dense batch, it is conditioned on, never predicted (its
            # would-be predictor is the last pad column).
            mask = (
                jnp.arange(tokens.shape[1])[None, :]
                > jnp.asarray(pad_lens, jnp.int32)[:, None]
            ).astype(jnp.float32)
        else:
            mask = jnp.ones(tokens.shape, jnp.float32)
    else:
        mask = jnp.asarray(mask, jnp.float32)
        if mask.shape != tokens.shape:
            raise ValueError(
                f"mask shape {mask.shape} != tokens shape {tokens.shape}"
            )
    if pad_lens is not None:
        pad_lens = jnp.asarray(pad_lens, jnp.int32)
    return _score_jit(
        model, params, tokens, mask, pad_lens, per_token=per_token
    )
