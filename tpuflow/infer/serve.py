"""Continuous-batching serving engine: persistent slot-based KV decode
with interleaved chunked prefill.

The batch predictor (``tpuflow.infer.engine``) compiles one KV program
per batch and decodes lockstep: aggregate tokens/s collapses the moment
requests have unequal lengths or arrive at different times, because every
row waits for the slowest and every new shape recompiles. TPU serving
throughput comes from the opposite design (the Gemma-on-TPU serving
comparison, PAPERS.md): keep ONE persistently-compiled decode program
saturated and move requests through it independently.

Shape of the engine:

- **Slot-based KV cache.** One fixed ``(max_slots, n_ctx)`` cache owned
  by one compiled decode-block program. Each slot carries its own
  ``live`` / ``length`` / ``pad`` / ``remaining`` state as (S,) operand
  arrays — admissions, generation, and evictions are DATA, never shape,
  so nothing recompiles. The per-row cache positions ride the model's
  ``slot_index`` decode path (``GPT2.__call__``): row b writes its k/v
  at its own column and its queries see ``[pad[b], length[b]]`` only, so
  a reused slot's stale columns stay invisible.

- **Chunked prefill as the admission path.** A waiting request is
  admitted by LEFT-padding its prompt to a small set of bucket widths
  (``pad_to`` semantics: a handful of prefill programs compile, ever)
  and running ``chunked_prefill`` on a (1, W) row — bounding peak
  attention memory to O(chunk x n_ctx) — then a jitted insert writes the
  row's cache into the free slot. Prefill interleaves with decode blocks
  at the scheduler loop, the continuous-batching core.

- **Decode blocks.** Between admissions the engine runs the persistent
  decode program: a ``lax.scan`` of ``decode_block`` single-token steps
  over all slots at once, with per-slot eos / budget / capacity freezing
  inside the program (one host sync per BLOCK, not per token). Greedy
  decoding; ``decode_precision`` (PR 4) makes batched decode
  width-independent, so every request's tokens are exactly what a solo
  ``generate()`` of its prompt produces.

- **AOT warm path.** ``warmup()`` routes through
  ``maybe_enable_compile_cache`` and executes the decode program, the
  insert, and every prefill bucket once, so a restarted server pays
  cache loads instead of the measured 62.9 s compile / 125.1 s
  wall-to-first-step gap (BENCH_r05). ``compile_stats()`` exposes the
  jit cache sizes; after warmup they must never grow — pinned by
  tests/test_serve.py.

- **Per-request int8 (ISSUE 9).** ``TPUFLOW_SERVE_QUANT`` (or the
  ``quant=`` ctor arg) arms a SECOND numeric path: the engine quantizes
  the params once (``tpuflow.infer.quant``, fused-native W8A8 by
  default — int8 x int8 -> int32 on the MXU through
  ``tpuflow.ops.int8_matmul``) and compiles an int8 decode-block
  program + prefill ladder at ``warmup()`` beside the fp ones. Each
  ``submit(quantize=True|False)`` routes its request to one path; mixed
  requests SHARE the one engine and the one slot cache (the per-slot
  attention window keeps rows independent, so a group's program can
  run with the other group masked out of its live set without touching
  its state). ``compile_stats()`` still never grows after warmup — the
  never-recompile contract covers the quantized program too.

Knobs: ``TPUFLOW_SERVE_SLOTS`` (default 8), ``TPUFLOW_SERVE_PREFILL_CHUNK``
(default off), ``TPUFLOW_SERVE_BUCKETS`` (comma widths; default a
power-of-two ladder up to ``n_ctx``), ``TPUFLOW_SERVE_DECODE_BLOCK``
(tokens per decode dispatch, default 8), ``TPUFLOW_SERVE_QUANT``
(=1/fused_native/weight_only arms per-request int8; default off),
``TPUFLOW_SERVE`` (=0 keeps ``GenerationPredictor`` on the legacy
per-batch path).

Telemetry (``serve.*``, catalog-enforced): queue depth, slot occupancy,
per-request TTFT and decode tokens/s, admission/completion events,
prefill/decode spans — riding ``tpuflow.obs`` and the live ``/metrics``
exporter (``tpuflow.obs.export``), watchable via
``tools/tpu_watch.py --follow``.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpuflow import obs
from tpuflow.infer.generate import (
    chunked_prefill,
    normalize_prefill_chunk,
    prompt_lens_to_pad_lens,
)


def _env_int(name: str, default: int, *, minimum: int = 1) -> int:
    """Malformed env values fall to the default (the dispatch_depth
    idiom: a typo'd knob must not crash a server at start)."""
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return max(int(raw), minimum)
    except ValueError:
        print(
            f"[tpuflow] malformed {name}={raw!r} (want an integer); "
            f"using {default}"
        )
        return default


def resolve_serve_quant(quant=None) -> str | None:
    """Per-request-int8 mode from the explicit ctor arg or
    ``TPUFLOW_SERVE_QUANT``: None = disabled; ``1``/``true`` = the
    fused-native headline mode; any quantization-mode spelling
    (``fused_native``/``mxu``/``weight_only``/``weight``) selects that
    mode. A malformed ENV value warns and arms fused-native anyway (the
    operator asked for int8; silently serving fp would falsify every
    capacity plan built on the knob) — an explicit bad ``quant=`` arg
    raises, the bucket-knob idiom split by blast radius."""
    from tpuflow.infer.quant import canonical_mode

    if quant is None:
        raw = os.environ.get("TPUFLOW_SERVE_QUANT", "").strip().lower()
        if raw in ("", "0", "false", "off"):
            return None
        if raw in ("1", "true", "on"):
            return "mxu"
        try:
            return canonical_mode(raw)
        except ValueError:
            print(
                f"[tpuflow] malformed TPUFLOW_SERVE_QUANT={raw!r} (want "
                "1|fused_native|weight_only); arming fused_native"
            )
            return "mxu"
    if quant is False:
        return None
    if quant is True:
        return "mxu"
    return canonical_mode(quant)


def default_buckets(n_ctx: int) -> list[int]:
    """Power-of-two prefill-width ladder, topped by ``n_ctx - 1`` (the
    widest ADMITTABLE width: a bucket of n_ctx leaves no cache column for
    even one generated token, since capacity is checked on the padded
    bucket width). The whole compile set for admission prefill."""
    top = max(n_ctx - 1, 1)
    out: list[int] = []
    w = min(16, top)
    while w < top:
        out.append(w)
        w *= 2
    out.append(top)
    return out


def resolve_buckets(n_ctx: int, buckets=None) -> list[int]:
    """Bucket widths from the explicit arg, TPUFLOW_SERVE_BUCKETS, or the
    default ladder — validated, deduped, ascending, capped at the widest
    admittable width (``n_ctx - 1``)."""
    if buckets is None:
        raw = os.environ.get("TPUFLOW_SERVE_BUCKETS")
        if raw:
            try:
                buckets = [int(x) for x in raw.split(",") if x.strip()]
            except ValueError:
                print(
                    f"[tpuflow] malformed TPUFLOW_SERVE_BUCKETS={raw!r} "
                    "(want comma-separated ints); using the default ladder"
                )
                buckets = None
    if buckets is None:
        return default_buckets(n_ctx)
    out = sorted({int(b) for b in buckets if 1 <= int(b) <= n_ctx - 1})
    if not out:
        raise ValueError(
            f"no usable prefill bucket in {buckets!r} (need 1 <= b <= "
            f"n_ctx - 1 = {n_ctx - 1})"
        )
    return out


@dataclasses.dataclass
class ServeRequest:
    """One request's lifecycle, owned by the engine that created it."""

    id: int
    prompt: np.ndarray  # (L,) int32
    max_new_tokens: int
    eos_id: int | None
    t_submit: float
    quantize: bool = False  # int8 numeric path (engine must be armed)
    bucket: int | None = None
    t_admit: float | None = None
    t_first: float | None = None
    t_done: float | None = None
    tokens: list[int] = dataclasses.field(default_factory=list)
    state: str = "queued"  # queued | running | done
    finish_reason: str | None = None

    @property
    def done(self) -> bool:
        return self.state == "done"

    @property
    def ttft_s(self) -> float | None:
        """Submit → first generated token (the prefill logits' argmax)."""
        if self.t_first is None:
            return None
        return self.t_first - self.t_submit

    @property
    def decode_tokens_per_s(self) -> float | None:
        """Post-first-token decode rate (the slot's steady-state share of
        the batched decode program)."""
        if self.t_done is None or self.t_first is None:
            return None
        n = len(self.tokens) - 1
        dur = self.t_done - self.t_first
        if n <= 0 or dur <= 0:
            return None
        return n / dur

    def result(self) -> np.ndarray:
        """Generated tokens so far (complete once ``done``)."""
        return np.asarray(self.tokens, np.int32)


class ServeEngine:
    """Request-level continuous-batching engine over one model.

    Greedy decoding only (the serving contract is token-exactness vs a
    solo ``generate(temperature=0)`` of the same prompt; stochastic
    per-request sampling would need per-slot rng plumbing that nothing
    consumes yet). Single-process: the cache lives on the default device
    set; on a sharded mesh the slot axis shards over 'data' through
    GSPMD exactly like the batch predictor's batches.
    """

    def __init__(
        self,
        model,
        params,
        *,
        max_slots: int | None = None,
        prefill_chunk: int | None = None,
        buckets=None,
        decode_block: int | None = None,
        pad_id: int = 0,
        quant: str | bool | None = None,
    ):
        self.model = model
        self.params = params
        # Per-request int8 (ISSUE 9): quantize ONCE at construction and
        # keep both numeric paths' params resident — requests pick a
        # path at submit, never a recompile. The quantized tree is a
        # derived view of the same fp params (QuantLeaf pytrees), so
        # checkpoint reload/hot-swap stories stay single-source.
        self.quant_mode = resolve_serve_quant(quant)
        self._qmodel = self._qparams = None
        if self.quant_mode is not None:
            from tpuflow.infer.quant import (
                QuantizedModel,
                quant_decision,
                quantize_model,
            )

            if isinstance(model, QuantizedModel):
                raise ValueError(
                    "ServeEngine(quant=...) wants the raw fp model/params "
                    "and owns both numeric paths; got an already-quantized "
                    "model — drop the wrapper or drop the quant arg"
                )
            dec = quant_decision(params, mode=self.quant_mode)
            obs.event(
                "quant.decision",
                apply=True,  # per-request opt-in: forced, gate advisory
                mode=dec.mode,
                weight_mib=round(dec.weight_bytes / 2**20, 1),
                reason="serve engine per-request int8 (submit(quantize=))",
            )
            self._qmodel, self._qparams = quantize_model(
                model, params, mode=self.quant_mode
            )
        self.n_ctx = int(model.config.n_ctx)
        self.max_slots = (
            int(max_slots)
            if max_slots is not None
            else _env_int("TPUFLOW_SERVE_SLOTS", 8)
        )
        if self.max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {self.max_slots}")
        if prefill_chunk is None:
            prefill_chunk = (
                _env_int("TPUFLOW_SERVE_PREFILL_CHUNK", 0, minimum=0) or None
            )
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}"
            )
        self.prefill_chunk = prefill_chunk
        self.buckets = resolve_buckets(self.n_ctx, buckets)
        self.decode_block = (
            int(decode_block)
            if decode_block is not None
            else _env_int("TPUFLOW_SERVE_DECODE_BLOCK", 8)
        )
        if self.decode_block < 1:
            raise ValueError(
                f"decode_block must be >= 1, got {self.decode_block}"
            )
        self.pad_id = int(pad_id)

        S = self.max_slots
        self._queue: collections.deque[ServeRequest] = collections.deque()
        self._slots: list[ServeRequest | None] = [None] * S
        self._tok = np.zeros((S,), np.int32)
        self._lengths = np.zeros((S,), np.int32)
        self._pads = np.zeros((S,), np.int32)
        self._remaining = np.zeros((S,), np.int32)
        self._live = np.zeros((S,), bool)
        self._quant = np.zeros((S,), bool)  # slot rides the int8 path
        self._eos = np.full((S,), -1, np.int32)
        self._next_id = 0
        self._iters = 0
        self._completed = 0
        self._emitted_tokens = 0
        self._last_gauges: tuple[int, int] | None = None
        self._cache = self._init_cache()

        self._prefill = jax.jit(
            functools.partial(self._prefill_fn, self.model),
            static_argnames=("chunk",),
        )
        self._insert = jax.jit(self._insert_fn, donate_argnums=(0,))
        self._decode = jax.jit(
            functools.partial(self._decode_fn, self.model),
            donate_argnums=(1,),
        )
        self._prefill_q = self._decode_q = None
        if self.quant_mode is not None:
            # The int8 twins: same program SHAPES (slot arrays, cache
            # pytree, bucket widths), different static model + params
            # pytree — so fp and int8 requests interleave through one
            # engine with zero fresh compiles after warmup.
            self._prefill_q = jax.jit(
                functools.partial(self._prefill_fn, self._qmodel),
                static_argnames=("chunk",),
            )
            self._decode_q = jax.jit(
                functools.partial(self._decode_fn, self._qmodel),
                donate_argnums=(1,),
            )

    # ------------------------------------------------------- jitted programs
    def _init_cache(self):
        """Zeroed (max_slots, n_ctx) KV cache with the model's exact cache
        pytree (eval_shape — no compile, no garbage forward)."""

        def mk(params):
            _, variables = self.model.apply(
                {"params": params},
                jnp.zeros((self.max_slots, 1), jnp.int32),
                decode=True,
                mutable=["cache"],
            )
            return variables["cache"]

        shapes = jax.eval_shape(mk, self.params)
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes
        )

    def _prefill_fn(self, model, params, prompt, pads, *, chunk):
        """(1, W) admission prefill → (first greedy token (1,), cache row).
        One program per bucket width W (chunk is fixed per engine);
        ``model`` is partial-bound per numeric path (fp / int8)."""
        logits, cache = chunked_prefill(
            model, params, prompt, chunk, pad_lens=pads
        )
        tok0 = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        return tok0, cache

    def _insert_fn(self, cache, row_cache, slot):
        """Write a (1, n_ctx) prefill cache row into ``slot`` of the big
        cache. K/V leaves are (S, n_ctx, H, D) (or (L, S, n_ctx, H, D)
        under scan_layers — the slot axis sits 4 dims from the end);
        scalar index leaves pass through untouched (slot mode never reads
        them)."""

        def put(big, row):
            if big.ndim >= 4:
                start = (0,) * (big.ndim - 4) + (slot, 0, 0, 0)
                return jax.lax.dynamic_update_slice(
                    big, row.astype(big.dtype), start
                )
            return big

        return jax.tree_util.tree_map(put, cache, row_cache)

    def _decode_fn(self, model, params, cache, tok, lengths, pads,
                   remaining, live, eos):
        """THE persistent decode program: ``decode_block`` single-token
        steps over every slot, per-slot freezing inside the scan. One
        host sync per block. Dead slots keep rewriting one cache column
        with pad-token k/v — masked out of every live row, overwritten by
        the next admission's insert. ``model`` is partial-bound per
        numeric path: the int8 twin runs the same program shape with the
        fused-native W8A8 matmuls."""
        n_ctx = self.n_ctx
        pad_id = self.pad_id

        def one(carry, _):
            cache, tok, lengths, remaining, live = carry
            logits, variables = model.apply(
                {"params": params, "cache": cache},
                tok[:, None],
                decode=True,
                mutable=["cache"],
                pad_lens=pads,
                slot_index=lengths,
            )
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            emitted = jnp.where(live, nxt, pad_id)
            lengths = jnp.where(live, lengths + 1, lengths)
            remaining = jnp.where(live, remaining - 1, remaining)
            # eos itself IS emitted (generate()'s contract); the slot
            # freezes after it. `lengths < n_ctx` guards the NEXT write.
            live = (
                live
                & (nxt != eos)
                & (remaining > 0)
                & (lengths < n_ctx)
            )
            return (
                variables["cache"], emitted, lengths, remaining, live
            ), emitted

        (cache, tok, lengths, remaining, live), toks = jax.lax.scan(
            one,
            (cache, tok, lengths, remaining, live),
            None,
            length=self.decode_block,
        )
        return cache, toks.T, tok, lengths, remaining, live

    # ------------------------------------------------------------ scheduling
    def bucket_for(self, prompt_len: int, max_new_tokens: int) -> int:
        """Smallest bucket width holding the prompt whose padded width
        still fits the generation budget in the cache. Bucket pads eat
        cache columns, so the capacity check is on the BUCKET width."""
        for w in self.buckets:
            if prompt_len <= w and w + max_new_tokens <= self.n_ctx:
                return w
        raise ValueError(
            f"no prefill bucket fits prompt_len={prompt_len} + "
            f"max_new_tokens={max_new_tokens} within n_ctx={self.n_ctx} "
            f"(buckets: {self.buckets})"
        )

    def submit(
        self,
        prompt,
        *,
        max_new_tokens: int,
        eos_id: int | None = None,
        quantize: bool = False,
    ) -> ServeRequest:
        """Enqueue one request; returns its live handle. Validation is
        eager (a request that can never fit must fail at submit, not
        half-way through a decode block). ``quantize=True`` routes the
        request through the engine's int8 programs (requires a
        quant-armed engine: ``quant=`` / ``TPUFLOW_SERVE_QUANT``)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if quantize and self.quant_mode is None:
            raise ValueError(
                "submit(quantize=True) needs a quant-armed engine: pass "
                "ServeEngine(quant='fused_native') or set "
                "TPUFLOW_SERVE_QUANT=1 (the int8 programs compile at "
                "warmup, never mid-flight)"
            )
        bucket = self.bucket_for(prompt.size, max_new_tokens)
        req = ServeRequest(
            id=self._next_id,
            prompt=prompt,
            max_new_tokens=int(max_new_tokens),
            eos_id=None if eos_id is None else int(eos_id),
            t_submit=time.monotonic(),
            quantize=bool(quantize),
            bucket=bucket,
        )
        self._next_id += 1
        self._queue.append(req)
        return req

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def live_slots(self) -> int:
        return int(self._live.sum())

    def compile_stats(self) -> dict[str, int]:
        """Jit-cache sizes of the engine's programs (including the int8
        twins on a quant-armed engine). After ``warmup()`` these must
        never grow — the never-recompile contract, pinned by
        tests/test_serve.py."""
        stats = {
            "prefill": int(self._prefill._cache_size()),
            "insert": int(self._insert._cache_size()),
            "decode": int(self._decode._cache_size()),
        }
        if self.quant_mode is not None:
            stats["prefill_q"] = int(self._prefill_q._cache_size())
            stats["decode_q"] = int(self._decode_q._cache_size())
        return stats

    def _free_slot(self) -> int | None:
        for s, req in enumerate(self._slots):
            if req is None:
                return s
        return None

    def _admit_one(self, req: ServeRequest, slot: int) -> None:
        now = time.monotonic()
        req.t_admit = now
        W = req.bucket
        L = req.prompt.size
        padded = np.full((1, W), self.pad_id, np.int32)
        padded[0, W - L:] = req.prompt
        pads = prompt_lens_to_pad_lens([L], 1, W)
        chunk = normalize_prefill_chunk(self.prefill_chunk, W)
        prefill = self._prefill_q if req.quantize else self._prefill
        prm = self._qparams if req.quantize else self.params
        with obs.span(
            "serve.prefill", request=req.id, bucket=W, prompt_len=int(L),
            chunk=chunk, quant=bool(req.quantize),
        ):
            tok0, row_cache = prefill(
                prm, jnp.asarray(padded), pads, chunk=chunk
            )
            first = int(np.asarray(tok0)[0])
        req.t_first = time.monotonic()
        req.tokens.append(first)
        req.state = "running"
        obs.event(
            "serve.admit", request=req.id, slot=slot, bucket=W,
            prompt_len=int(L),
            queue_wait_s=round(now - req.t_submit, 6),
        )
        obs.gauge("serve.ttft_s", round(req.ttft_s, 6))
        led = obs.goodput_live()
        led.note_serve_ttft(req.ttft_s)
        done = (req.eos_id is not None and first == req.eos_id) or (
            req.max_new_tokens == 1
        )
        self._emitted_tokens += 1
        led.note_serve_tokens(1)
        obs.counter("serve.tokens", 1)
        if done:
            self._finish(
                req, "eos" if req.max_new_tokens > 1 else "budget"
            )
            return
        self._cache = self._insert(
            self._cache, row_cache, np.int32(slot)
        )
        self._slots[slot] = req
        self._tok[slot] = first
        self._lengths[slot] = W
        self._pads[slot] = W - L
        self._remaining[slot] = req.max_new_tokens - 1
        self._live[slot] = True
        self._quant[slot] = req.quantize
        self._eos[slot] = -1 if req.eos_id is None else req.eos_id

    def _finish(self, req: ServeRequest, reason: str) -> None:
        req.t_done = time.monotonic()
        req.state = "done"
        req.finish_reason = reason
        self._completed += 1
        rate = req.decode_tokens_per_s
        obs.event(
            "serve.complete", request=req.id, tokens=len(req.tokens),
            reason=reason, ttft_s=round(req.ttft_s, 6),
            decode_tokens_per_s=None if rate is None else round(rate, 2),
        )
        obs.counter("serve.requests", 1)
        if req.quantize:
            obs.counter("serve.quant_requests", 1)
        if rate is not None:
            obs.gauge("serve.tokens_per_s", round(rate, 2))
        obs.goodput_live().note_serve_complete()

    def _emit_state_gauges(self) -> None:
        """Queue-depth / occupancy gauges on change (plus a periodic
        refresh) — a long idle server must not flood the event stream."""
        state = (len(self._queue), self.live_slots)
        if state != self._last_gauges or self._iters % 64 == 0:
            self._last_gauges = state
            obs.gauge("serve.queue_depth", state[0])
            obs.gauge(
                "serve.slot_occupancy",
                round(state[1] / self.max_slots, 4),
            )
        obs.goodput_live().note_serve_state(
            state[0], state[1], self.max_slots
        )

    def _run_decode_block(self, quant: bool) -> int:
        """One decode block over ONE numeric group's slots (fp or int8):
        run that group's persistent program with the OTHER group masked
        out of the live set, merge the per-slot state back through the
        group mask, harvest tokens, free exited slots. Returns emitted
        token count.

        Why masking composes: each slot row only ever attends within its
        own cache row, and a program only advances (and only writes real
        k/v for) rows live in ITS set — a masked-out row's single
        garbage k/v write lands at its frozen ``lengths`` column, which
        is exactly where that row's OWN program writes real k/v next, so
        it is always overwritten before anything can attend to it.
        Mixed fp+int8 traffic therefore shares one cache and one engine
        with zero cross-talk (pinned by tests/test_serve.py)."""
        mask = self._live & (self._quant == quant)
        if not mask.any():
            return 0
        decode = self._decode_q if quant else self._decode
        prm = self._qparams if quant else self.params
        old_remaining = self._remaining.copy()
        # Two literal span calls (not one with a computed name): the
        # obs_lint drift guard only sees literal emitter names.
        span = (
            obs.span("serve.quant_decode", slots=int(mask.sum()))
            if quant
            else obs.span("serve.decode", slots=int(mask.sum()))
        )
        with span as sp:
            (
                self._cache, toks, tok, lengths, remaining, live
            ) = decode(
                prm,
                self._cache,
                self._tok,
                self._lengths,
                self._pads,
                self._remaining,
                mask,
                self._eos,
            )
            # The host copy of the block's tokens IS the fence.
            # np.array (not asarray): the zero-copy view of a jax
            # array is read-only, and admissions write these. Merge
            # through the group mask — the program's carries hold
            # pad_id tokens for every row outside its live set,
            # including the OTHER group's mid-flight slots.
            toks = np.asarray(toks)
            self._tok = np.where(mask, np.array(tok), self._tok)
            self._lengths = np.where(mask, np.array(lengths), self._lengths)
            self._remaining = np.where(
                mask, np.array(remaining), self._remaining
            )
            self._live = np.where(mask, np.array(live), self._live)
            emitted = int((old_remaining - self._remaining).sum())
            sp.set(tokens=emitted)
        for s, req in enumerate(self._slots):
            if req is None or not mask[s]:
                continue
            n = int(old_remaining[s] - self._remaining[s])
            if n:
                req.tokens.extend(int(t) for t in toks[s, :n])
            if not self._live[s]:
                last = req.tokens[-1] if req.tokens else None
                if req.eos_id is not None and last == req.eos_id:
                    reason = "eos"
                elif len(req.tokens) >= req.max_new_tokens:
                    reason = "budget"
                else:
                    reason = "capacity"  # n_ctx frontier hit
                self._finish(req, reason)
                self._slots[s] = None
                self._quant[s] = False
        return emitted

    def step(self, admit: bool = True) -> bool:
        """One scheduler iteration: admit waiting requests into free
        slots (chunked prefill), then run one decode block per live
        numeric group (fp, plus int8 on a quant-armed engine). Returns
        False when there was nothing to do (idle)."""
        self._iters += 1
        did = False
        while admit and self._queue:
            slot = self._free_slot()
            if slot is None:
                break
            self._admit_one(self._queue.popleft(), slot)
            did = True
        if self._live.any():
            did = True
            emitted = self._run_decode_block(False)
            if self.quant_mode is not None:
                emitted += self._run_decode_block(True)
            self._emitted_tokens += emitted
            obs.goodput_live().note_serve_tokens(emitted)
            if emitted:
                obs.counter("serve.tokens", emitted)
        self._emit_state_gauges()
        return did

    def run_until_idle(self, max_iters: int | None = None) -> None:
        """Drive the scheduler until queue and slots are empty."""
        iters = 0
        while self._queue or self._live.any():
            self.step()
            iters += 1
            if max_iters is not None and iters >= max_iters:
                raise RuntimeError(
                    f"engine not idle after {max_iters} iterations "
                    f"(queue={len(self._queue)}, live={self.live_slots})"
                )

    def generate_many(
        self,
        prompts,
        *,
        max_new_tokens: int,
        eos_id: int | None = None,
        quantize: bool = False,
    ) -> list[np.ndarray]:
        """Submit every prompt, run to completion, return each request's
        generated tokens in submit order (the batch-predictor adapter)."""
        reqs = [
            self.submit(
                p, max_new_tokens=max_new_tokens, eos_id=eos_id,
                quantize=quantize,
            )
            for p in prompts
        ]
        self.run_until_idle()
        return [r.result() for r in reqs]

    # ---------------------------------------------------------------- warmup
    def warmup(self, run_dir: str | None = None) -> dict[str, int]:
        """Compile-or-load every program the engine will ever run: the
        decode block, the insert, and one prefill per bucket — through
        the persistent compile cache (``maybe_enable_compile_cache``), so
        a server restart pays cache loads, not the BENCH_r05 62.9 s
        compile / 125.1 s wall-to-first-step gap. Executes each program
        once on dead-slot state (guaranteed jit-cache hits afterwards;
        the garbage forwards are masked by ``live=False`` everywhere) and
        restores a pristine cache. Returns ``compile_stats()``."""
        from tpuflow.dist import maybe_enable_compile_cache

        maybe_enable_compile_cache(run_dir)
        with obs.span(
            "serve.warmup", buckets=len(self.buckets),
            quant=self.quant_mode or "off",
        ) as sp:
            row_cache = None
            for w in self.buckets:
                chunk = normalize_prefill_chunk(self.prefill_chunk, w)
                _, row_cache = self._prefill(
                    self.params,
                    jnp.zeros((1, w), jnp.int32),
                    prompt_lens_to_pad_lens([w], 1, w),
                    chunk=chunk,
                )
                if self.quant_mode is not None:
                    # The int8 prefill ladder compiles beside the fp one
                    # — a quantize=True admission must be a cache hit.
                    _, row_cache = self._prefill_q(
                        self._qparams,
                        jnp.zeros((1, w), jnp.int32),
                        prompt_lens_to_pad_lens([w], 1, w),
                        chunk=chunk,
                    )
            if row_cache is not None:
                # First insert: the fresh (uncommitted) init cache.
                self._cache = self._insert(
                    self._cache, row_cache, np.int32(0)
                )
            out = self._decode(
                self.params, self._cache, self._tok, self._lengths,
                self._pads, self._remaining, self._live, self._eos,
            )
            self._cache = out[0]
            if self.quant_mode is not None:
                # The int8 decode block on the decode-committed cache —
                # the exact signature the mixed-traffic scheduler replays.
                out = self._decode_q(
                    self._qparams, self._cache, self._tok, self._lengths,
                    self._pads, self._remaining, self._live, self._eos,
                )
                self._cache = out[0]
            if row_cache is not None:
                # Second insert: the steady-state signature — a cache
                # COMMITTED by the decode program (with sharded params
                # the jit key differs from the fresh-zeros variant; both
                # must be warm or the first post-decode admission would
                # recompile, breaking the never-recompile contract).
                self._cache = self._insert(
                    self._cache, row_cache, np.int32(0)
                )
            # Warmup wrote garbage k/v into slot 0's columns; every query
            # of a future occupant is masked to its own [pad, length]
            # window and the insert overwrites the row, but start zeroed
            # anyway so warmup is observationally a no-op. x*0 (not a
            # fresh zeros tree): the result stays committed exactly like
            # every later decode/insert output, so the program signatures
            # warmed above are the ones the serving loop replays.
            self._cache = jax.tree_util.tree_map(
                lambda x: x * 0, self._cache
            )
            jax.block_until_ready(self._cache)
            stats = self.compile_stats()
            sp.set(**stats)
        return stats


def serve_forever(
    engine: ServeEngine,
    *,
    idle_sleep_s: float = 0.005,
    max_s: float | None = None,
    should_stop=None,
) -> None:
    """Long-lived serving loop reusing the gang machinery: heartbeat
    stamps every iteration (the supervisor's stall detector works on a
    serving gang exactly as on a training gang), the live ``/metrics`` +
    ``/status`` exporter starts when ``TPUFLOW_OBS_HTTP_PORT`` is set,
    and a SIGTERM preemption drains — stops admitting, finishes the live
    slots, exits — instead of killing requests mid-decode.

    ``max_s`` bounds the loop (tests / bounded jobs); ``should_stop`` is
    an optional callable polled each iteration.
    """
    from tpuflow.utils import heartbeat, preempt

    obs.maybe_start_export()
    preempt.install_sigterm_handler()
    deadline = None if max_s is None else time.monotonic() + max_s
    draining = False
    while True:
        if preempt.preemption_requested():
            draining = True
        did = engine.step(admit=not draining)
        heartbeat.beat(step=engine._iters)
        if draining and not engine._live.any():
            return
        if should_stop is not None and should_stop():
            return
        if deadline is not None and time.monotonic() > deadline:
            return
        if not did:
            if draining:
                return
            time.sleep(idle_sleep_s)
