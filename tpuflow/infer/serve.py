"""Continuous-batching serving engine: persistent slot-based KV decode
with interleaved chunked prefill.

The batch predictor (``tpuflow.infer.engine``) compiles one KV program
per batch and decodes lockstep: aggregate tokens/s collapses the moment
requests have unequal lengths or arrive at different times, because every
row waits for the slowest and every new shape recompiles. TPU serving
throughput comes from the opposite design (the Gemma-on-TPU serving
comparison, PAPERS.md): keep ONE persistently-compiled decode program
saturated and move requests through it independently.

Shape of the engine:

- **Slot-based KV cache.** One fixed ``(max_slots, n_ctx)`` cache owned
  by one compiled decode-block program. Each slot carries its own
  ``live`` / ``length`` / ``pad`` / ``remaining`` state as (S,) operand
  arrays — admissions, generation, and evictions are DATA, never shape,
  so nothing recompiles. The per-row cache positions ride the model's
  ``slot_index`` decode path (``GPT2.__call__``): row b writes its k/v
  at its own column and its queries see ``[pad[b], length[b]]`` only, so
  a reused slot's stale columns stay invisible.

- **Chunked prefill as the admission path.** A waiting request is
  admitted by LEFT-padding its prompt to a small set of bucket widths
  (``pad_to`` semantics: a handful of prefill programs compile, ever)
  and running ``chunked_prefill`` on a (1, W) row — bounding peak
  attention memory to O(chunk x n_ctx) — then a jitted insert writes the
  row's cache into the free slot. Prefill interleaves with decode blocks
  at the scheduler loop, the continuous-batching core.

- **Decode blocks.** Between admissions the engine runs the persistent
  decode program: a ``lax.scan`` of ``decode_block`` single-token steps
  over all slots at once, with per-slot eos / budget / capacity freezing
  inside the program (one host sync per BLOCK, not per token). Greedy
  decoding; ``decode_precision`` (PR 4) makes batched decode
  width-independent, so every request's tokens are exactly what a solo
  ``generate()`` of its prompt produces.

- **AOT warm path.** ``warmup()`` routes through
  ``maybe_enable_compile_cache`` and executes the decode program, the
  insert, and every prefill bucket once, so a restarted server pays
  cache loads instead of the measured 62.9 s compile / 125.1 s
  wall-to-first-step gap (BENCH_r05). ``compile_stats()`` exposes the
  jit cache sizes; after warmup they must never grow — pinned by
  tests/test_serve.py.

- **Per-request int8 (ISSUE 9).** ``TPUFLOW_SERVE_QUANT`` (or the
  ``quant=`` ctor arg) arms a SECOND numeric path: the engine quantizes
  the params once (``tpuflow.infer.quant``, fused-native W8A8 by
  default — int8 x int8 -> int32 on the MXU through
  ``tpuflow.ops.int8_matmul``) and compiles an int8 decode-block
  program + prefill ladder at ``warmup()`` beside the fp ones. Each
  ``submit(quantize=True|False)`` routes its request to one path; mixed
  requests SHARE the one engine and the one slot cache (the per-slot
  attention window keeps rows independent, so a group's program can
  run with the other group masked out of its live set without touching
  its state). ``compile_stats()`` still never grows after warmup — the
  never-recompile contract covers the quantized program too.

- **Paged KV (ISSUE 11, default on).** The per-slot contiguous
  ``(max_slots, n_ctx)`` cache rows become a fixed POOL of
  ``(n_pages, page_size)`` pages plus a per-slot page table threaded
  through the decode block as data (``Block._paged_attention``) — the
  same "state is data, never shape" trick that made slots
  recompile-free now covers page allocation. What paging buys:

  * **Admission by token budget.** A request is admitted when its page
    need (``ceil((len + max_new [+ draft slack]) / page_size)``) fits
    the free pool, not when a whole ``n_ctx`` row is free — short
    requests stop stranding HBM, and a full pool applies BACKPRESSURE
    (the request stays queued, never dropped). Capacity checks move
    from the padded bucket width to the REAL prompt length (bucket
    pads no longer eat cache columns: the page insert strips them).
  * **Shared-prefix page reuse.** Prompt pages are content-hashed at
    page granularity (a chain over ``prompt[:(j+1)*page_size]``) into a
    refcounted prefix cache: a request whose prompt starts with an
    already-resident prefix (system prompt, few-shot header) maps those
    pages into its table instead of allocating copies. Pad-invariant kv
    (the left-pad exactness contract) is what makes the reuse sound.
    Idle (refcount-0) prefix pages stay cached until pool pressure
    evicts them LRU-first (``serve.page_evict``).
  * **Per-request speculative decode.** ``TPUFLOW_SERVE_SPEC=K`` (or
    ``speculative=K``) arms an in-program verify block: each live slot
    drafts K tokens on the host (``tpuflow.infer.speculative.
    ngram_draft`` — prompt-lookup, no draft model), ONE batched
    (S, K+1) forward verifies them, and every row commits its own
    accepted prefix + bonus token — per-row frontiers that the solo
    ladder's shared cache index could never allow. Acceptance argmaxes
    are width-safe by construction (``decode_precision='highest'`` from
    PR 4; int8 contractions are integer-exact, PR 9), so engine tokens
    stay bit-equal to solo ``generate()``. ``submit(speculative=False)``
    opts a request out (it rides the plain single-token block).

Knobs: ``TPUFLOW_SERVE_SLOTS`` (default 8), ``TPUFLOW_SERVE_PREFILL_CHUNK``
(default off), ``TPUFLOW_SERVE_BUCKETS`` (comma widths; default a
power-of-two ladder up to ``n_ctx``), ``TPUFLOW_SERVE_DECODE_BLOCK``
(tokens per decode dispatch, default 8), ``TPUFLOW_SERVE_QUANT``
(=1/fused_native/weight_only arms per-request int8; default off),
``TPUFLOW_SERVE_PAGED`` (=0 keeps the PR 8 contiguous slot rows — the
regression reference, kept one release), ``TPUFLOW_SERVE_PAGE_SIZE``
(default 16 tokens), ``TPUFLOW_SERVE_PAGES`` (pool size; default
``max_slots * n_ctx / page_size + 1`` — equal HBM to the slot rows),
``TPUFLOW_SERVE_PREFIX_CACHE`` (=0 disables shared-prefix reuse),
``TPUFLOW_SERVE_SPEC`` (=K arms per-request speculative decode),
``TPUFLOW_SERVE`` (=0 keeps ``GenerationPredictor`` on the legacy
per-batch path), ``TPUFLOW_SERVE_TRACE`` (=0 disarms per-request
lifecycle traces), ``TPUFLOW_SERVE_ACCESS_LOG`` (=0 disarms the
per-request JSONL access log), ``TPUFLOW_SERVE_SLO_TTFT_MS`` /
``TPUFLOW_SERVE_SLO_ITL_MS`` (declared latency SLOs; violations emit
events and a counter).

Telemetry (``serve.*``, catalog-enforced): queue depth, slot occupancy,
per-request TTFT and decode tokens/s, admission/completion events,
prefill/decode spans — riding ``tpuflow.obs`` and the live ``/metrics``
exporter (``tpuflow.obs.export``), watchable via
``tools/tpu_watch.py --follow``.

**Serving observatory (ISSUE 13).** Three host-side layers mirror the
training run observatory; none adds a jitted operand, so
``compile_stats()`` is unchanged after warmup with everything armed:

- **Per-request lifecycle traces.** Every ``ServeRequest`` carries a
  trace of its transitions — submitted, queued (with the backpressure
  reason: ``slots`` or ``pages``), admitted (bucket, pages, shared
  prefix pages), first_token (TTFT), every decode/verify tick it
  participated in (tokens committed, drafts accepted), and exactly one
  terminal (``complete`` with the finish reason, or ``drained`` on the
  SIGTERM path) — mirrored as ``serve.trace`` events and, at the
  terminal, as one line in the ``obs/access.p*.jsonl`` access log that
  ``python -m tpuflow.obs serve-summary <run_dir>`` reads (no jax
  import, works mid-run).
- **Engine-time ledger** (``tpuflow.obs.serve_ledger.ServeLedger``,
  at ``engine.ledger``): every second of serve wall charges to exactly
  one bucket — prefill / decode / verify / insert / host_sched / idle —
  by cursor construction, plus occupancy-weighted decode utilization,
  masked-row waste from the (fp,int8)x(spec,plain) group partition,
  and speculative drafted-vs-accepted economics.
- **SLO accounting.** ``TPUFLOW_SERVE_SLO_TTFT_MS`` /
  ``TPUFLOW_SERVE_SLO_ITL_MS`` declare latency SLOs; a violating
  request emits ``serve.slo_violation`` and bumps the
  ``serve.slo_violations`` counter, and TTFT/ITL percentiles (split by
  numeric path and spec/plain group) ride ``/metrics``, ``/status``,
  and ``tpu_watch --follow``.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import hashlib
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from typing import Any

from tpuflow import obs
from tpuflow.obs import device as _device
from tpuflow.obs import profcap as _profcap
from tpuflow.obs import serve_ledger as _ledger
from tpuflow.obs import trace as _reqtrace
from tpuflow.infer.generate import (
    chunked_prefill,
    normalize_prefill_chunk,
    prompt_lens_to_pad_lens,
)
from tpuflow.infer import kv_store as _kvstore
from tpuflow.infer.speculative import ngram_draft
from tpuflow.utils import knobs


def _env_int(name: str, default: int, *, minimum: int = 1) -> int:
    """Malformed env values fall to the default (the dispatch_depth
    idiom: a typo'd knob must not crash a server at start)."""
    # tpulint: disable=knob-dynamic -- name is forwarded verbatim from
    # literal call sites, which the string-literal declaration rule
    # still validates; knobs.raw refuses undeclared names at runtime.
    raw = knobs.raw(name)
    if not raw:
        return default
    try:
        return max(int(raw), minimum)
    except ValueError:
        print(
            f"[tpuflow] malformed {name}={raw!r} (want an integer); "
            f"using {default}"
        )
        return default


def resolve_serve_quant(quant=None) -> str | None:
    """Per-request-int8 mode from the explicit ctor arg or
    ``TPUFLOW_SERVE_QUANT``: None = disabled; ``1``/``true`` = the
    fused-native headline mode; any quantization-mode spelling
    (``fused_native``/``mxu``/``weight_only``/``weight``) selects that
    mode. A malformed ENV value warns and arms fused-native anyway (the
    operator asked for int8; silently serving fp would falsify every
    capacity plan built on the knob) — an explicit bad ``quant=`` arg
    raises, the bucket-knob idiom split by blast radius."""
    from tpuflow.infer.quant import canonical_mode

    if quant is None:
        raw = knobs.raw("TPUFLOW_SERVE_QUANT", "").strip().lower()
        if raw in ("", "0", "false", "off"):
            return None
        if raw in ("1", "true", "on"):
            return "mxu"
        try:
            return canonical_mode(raw)
        except ValueError:
            print(
                f"[tpuflow] malformed TPUFLOW_SERVE_QUANT={raw!r} (want "
                "1|fused_native|weight_only); arming fused_native"
            )
            return "mxu"
    if quant is False:
        return None
    if quant is True:
        return "mxu"
    return canonical_mode(quant)


def _env_flag(name: str, default: bool) -> bool:
    # tpulint: disable=knob-dynamic -- name is forwarded verbatim from
    # literal call sites, which the string-literal declaration rule
    # still validates; knobs.raw refuses undeclared names at runtime.
    raw = knobs.raw(name)
    if raw is None or raw == "":
        return default
    return raw.strip().lower() not in ("0", "false", "off")


def resolve_page_size(n_ctx: int, page_size=None) -> int:
    """Page width in tokens. Must divide ``n_ctx`` (the per-slot table is
    a dense ``n_ctx / page_size`` map). An explicit bad arg raises; a
    malformed/indivisible ENV value degrades to the largest divisor of
    ``n_ctx`` at or below the default with a warning (the bucket-knob
    blast-radius split)."""
    explicit = page_size is not None
    from_env = False
    if page_size is None:
        raw = knobs.raw("TPUFLOW_SERVE_PAGE_SIZE")
        if raw:
            try:
                page_size = int(raw)
                from_env = True
            except ValueError:
                print(
                    f"[tpuflow] malformed TPUFLOW_SERVE_PAGE_SIZE={raw!r} "
                    "(want an integer); using the default"
                )
    ps = int(page_size) if page_size is not None else 16
    if explicit:
        if ps < 1 or n_ctx % ps:
            raise ValueError(
                f"page_size must be >= 1 and divide n_ctx={n_ctx}, got {ps}"
            )
        return ps
    want = ps
    ps = max(min(ps, n_ctx), 1)
    while n_ctx % ps:
        ps -= 1
    if ps != want and from_env:
        print(
            f"[tpuflow] TPUFLOW_SERVE_PAGE_SIZE={want} does not divide "
            f"n_ctx={n_ctx}; using {ps}"
        )
    return ps


def resolve_spec_draft(speculative=None) -> int:
    """Per-request speculative draft length: 0 = off. ``True`` means the
    default draft of 4; an int is the draft length itself. The ENV path
    (``TPUFLOW_SERVE_SPEC``) accepts the same spellings, malformed
    values falling to off with a warning."""
    if speculative is None:
        raw = knobs.raw("TPUFLOW_SERVE_SPEC", "").strip().lower()
        if raw in ("", "0", "false", "off"):
            return 0
        if raw in ("1", "true", "on"):
            return 4
        try:
            return max(int(raw), 0)
        except ValueError:
            print(
                f"[tpuflow] malformed TPUFLOW_SERVE_SPEC={raw!r} (want an "
                "integer draft length); speculative decode stays off"
            )
            return 0
    if speculative is False:
        return 0
    if speculative is True:
        return 4
    k = int(speculative)
    if k < 0:
        raise ValueError(f"speculative draft length must be >= 0, got {k}")
    return k


def resolve_serve_role(role=None) -> str:
    """Serving phase this engine advertises (``TPUFLOW_SERVE_ROLE``):
    ``prefill`` takes the router's ship hops, ``decode`` takes
    admissions, ``both`` (the default) is classic colocated serving.
    The role never hard-gates engine behavior — a decode replica must
    still prefill locally when a shipped set is torn — it is placement
    advice the fleet rows export and the router reads. An explicit bad
    arg raises; a malformed ENV value degrades to ``both`` with a
    warning."""
    if role is None:
        raw = (knobs.raw("TPUFLOW_SERVE_ROLE") or "").strip().lower()
        if raw in ("", "both"):
            return "both"
        if raw in ("prefill", "decode"):
            return raw
        print(
            f"[tpuflow] malformed TPUFLOW_SERVE_ROLE={raw!r} (want "
            "prefill|decode|both); using both"
        )
        return "both"
    r = str(role).strip().lower()
    if r not in ("prefill", "decode", "both"):
        raise ValueError(
            f"role must be prefill|decode|both, got {role!r}"
        )
    return r


class PagePool:
    """Host-side accounting for the paged KV cache: free-list
    allocation, shared-prefix refcounts, and LRU eviction of idle cached
    prefix pages. Pure python/numpy — the DEVICE side only ever sees the
    resulting page tables as data, so this logic is unit-testable with
    zero compiles (tests/test_serve.py).

    Tiered spill (ISSUE 19): with ``tier_cache`` (a
    ``kv_store.TierCache``) and a ``page_reader`` wired, an evicted
    prefix page's CONTENT drops to host DRAM / node-local disk instead
    of being forgotten, and ``acquire`` extends the digest-chain walk
    into the lower tiers — matched lower-tier pages are freshly
    allocated here and reported via :meth:`take_promotions` so the
    engine restores their bytes instead of recomputing prefill. Without
    a tier cache every code path below is byte-identical to PR 11.

    Page 0 is the reserved TRASH page: never allocated, never read.
    Dead slots' zeroed tables and out-of-range writes route there inside
    the decode program (``Block._paged_attention``), which is what makes
    freeing + re-allocating a page safe while its old slot still sits in
    the batch operands.

    Prefix sharing: page j of a prompt is shareable when it is FULLY
    covered by prompt tokens (``(j+1) * page_size <= len``  — decode
    writes start at ``len``, so shared pages are never written) and is
    keyed by the sha1 of the entire prompt prefix through that page
    (causal attention makes page content a pure function of the
    prefix). A matched page's refcount bumps instead of allocating; at
    release, refcount-0 cached pages go IDLE (still matchable) and are
    only reclaimed by LRU eviction under pool pressure
    (``serve.page_evict``)."""

    def __init__(self, n_pages: int, page_size: int,
                 prefix_cache: bool = True, tier_cache=None,
                 page_reader=None):
        if n_pages < 2:
            raise ValueError(
                f"n_pages must be >= 2 (page 0 is the reserved trash "
                f"page), got {n_pages}"
            )
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.prefix_cache = bool(prefix_cache)
        self._free: list[int] = list(range(self.n_pages - 1, 0, -1))
        self._ref: dict[int, int] = {}
        self._hash_to_page: dict[bytes, int] = {}
        self._page_hash: dict[int, bytes] = {}
        self._idle: collections.OrderedDict[int, None] = (
            collections.OrderedDict()
        )
        self.prefix_hits = 0
        self.prefix_lookups = 0
        self.evictions = 0
        self.tier = tier_cache
        self._page_reader = page_reader
        self._pending_promote: list[tuple[int, bytes, str]] = []
        self.tier_hits = 0

    @property
    def usable_pages(self) -> int:
        """Pages a single request could ever hold (pool minus trash)."""
        return self.n_pages - 1

    @property
    def free_pages(self) -> int:
        """Pages allocatable right now: truly free + idle-evictable."""
        return len(self._free) + len(self._idle)

    @property
    def allocated_pages(self) -> int:
        """Pages currently held by at least one live request."""
        return len(self._ref)

    def prefix_digests(self, prompt) -> list[bytes]:
        """Chain keys for every FULLY-prompt-covered page, in order."""
        if not self.prefix_cache:
            return []
        p = np.asarray(prompt, np.int32).reshape(-1)
        ps = self.page_size
        return [
            hashlib.sha1(p[: (j + 1) * ps].tobytes()).digest()
            for j in range(p.size // ps)
        ]

    def match_len(self, digests: list[bytes]) -> int:
        """Longest cached prefix-page chain (no side effects)."""
        m = 0
        for d in digests:
            if d not in self._hash_to_page:
                break
            m += 1
        return m

    def can_fit(self, need: int, matched: int) -> bool:
        return need - matched <= self.free_pages

    def acquire(self, prompt, need: int) -> tuple[list[int], int] | None:
        """Map ``need`` pages for a request whose prompt may share a
        cached prefix. Returns ``(page_ids, matched)`` — the first
        ``matched`` ids are shared prefix pages (refcount bumped, no
        write), the rest freshly allocated — or None when the pool
        cannot fit the request (backpressure: caller leaves it queued).
        Newly-allocated full-prompt pages self-register in the prefix
        cache so the NEXT request with this prefix reuses them."""
        digests = self.prefix_digests(prompt)
        matched = min(self.match_len(digests), need)
        self._pending_promote = []
        if self.tier is not None:
            # Tier walk (ISSUE 19): extend the chain into the lower
            # tiers, contiguously from where HBM broke — each hit gets
            # a FRESH page here (registered below like any full-prompt
            # page) whose bytes the engine restores from the tier.
            j = matched
            while j < min(len(digests), need):
                tier = self.tier.locate(digests[j])
                if tier is None:
                    break
                self._pending_promote.append((j, digests[j], tier))
                j += 1
        if not self.can_fit(need, matched):
            self._pending_promote = []
            return None
        self.prefix_lookups += len(digests[:need])
        self.prefix_hits += matched
        ids: list[int] = []
        for d in digests[:matched]:
            pid = self._hash_to_page[d]
            if self._ref.get(pid, 0) == 0:
                self._idle.pop(pid, None)
            self._ref[pid] = self._ref.get(pid, 0) + 1
            ids.append(pid)
        for j in range(matched, need):
            pid = self._alloc_one()
            self._ref[pid] = 1
            ids.append(pid)
            if j < len(digests) and digests[j] not in self._hash_to_page:
                # A fresh full-prompt page becomes the cached copy of
                # its prefix (skip when another page already owns the
                # digest — e.g. the chain broke on an evicted EARLIER
                # page while a later one survived).
                self._hash_to_page[digests[j]] = pid
                self._page_hash[pid] = digests[j]
        return ids, matched

    def take_promotions(self) -> list[tuple[int, bytes, str]]:
        """The last ``acquire``'s lower-tier matches as ``(page_index,
        digest, tier)`` — consumed by the engine, which fetches each
        bundle and writes it back into the pool (serve.tier_promote)."""
        out, self._pending_promote = self._pending_promote, []
        return out

    def _alloc_one(self) -> int:
        if self._free:
            return self._free.pop()
        pid, _ = self._idle.popitem(last=False)  # LRU-first eviction
        d = self._page_hash.pop(pid)
        del self._hash_to_page[d]
        self.evictions += 1
        if self.tier is not None and self._page_reader is not None:
            # Spill instead of forget: the page's bytes drop a tier and
            # stay findable through the bounded digest→tier index (the
            # ISSUE 19 bugfix — an evicted prefix used to be
            # indistinguishable from never-cached).
            tier = self.tier.spill(d, self._page_reader(pid))
            if tier is not None:
                obs.event("serve.tier_spill", page=pid, tier=tier)
        obs.event("serve.page_evict", page=pid)
        return pid

    def release(self, page_ids) -> None:
        """Drop one ownership of each page; refcount-0 cached prefix
        pages go idle (matchable until evicted), private pages go free."""
        for pid in dict.fromkeys(int(p) for p in page_ids):
            self._ref[pid] -= 1
            if self._ref[pid] == 0:
                del self._ref[pid]
                if pid in self._page_hash:
                    self._idle[pid] = None
                    self._idle.move_to_end(pid)
                else:
                    self._free.append(pid)


def default_buckets(n_ctx: int) -> list[int]:
    """Power-of-two prefill-width ladder, topped by ``n_ctx - 1`` (the
    widest ADMITTABLE width: a bucket of n_ctx leaves no cache column for
    even one generated token, since capacity is checked on the padded
    bucket width). The whole compile set for admission prefill."""
    top = max(n_ctx - 1, 1)
    out: list[int] = []
    w = min(16, top)
    while w < top:
        out.append(w)
        w *= 2
    out.append(top)
    return out


def resolve_buckets(n_ctx: int, buckets=None) -> list[int]:
    """Bucket widths from the explicit arg, TPUFLOW_SERVE_BUCKETS, or the
    default ladder — validated, deduped, ascending, capped at the widest
    admittable width (``n_ctx - 1``)."""
    if buckets is None:
        raw = knobs.raw("TPUFLOW_SERVE_BUCKETS")
        if raw:
            try:
                buckets = [int(x) for x in raw.split(",") if x.strip()]
            except ValueError:
                print(
                    f"[tpuflow] malformed TPUFLOW_SERVE_BUCKETS={raw!r} "
                    "(want comma-separated ints); using the default ladder"
                )
                buckets = None
    if buckets is None:
        return default_buckets(n_ctx)
    out = sorted({int(b) for b in buckets if 1 <= int(b) <= n_ctx - 1})
    if not out:
        raise ValueError(
            f"no usable prefill bucket in {buckets!r} (need 1 <= b <= "
            f"n_ctx - 1 = {n_ctx - 1})"
        )
    return out


@dataclasses.dataclass
class ServeRequest:
    """One request's lifecycle, owned by the engine that created it."""

    id: int
    prompt: np.ndarray  # (L,) int32
    max_new_tokens: int
    eos_id: int | None
    t_submit: float
    quantize: bool = False  # int8 numeric path (engine must be armed)
    speculative: bool = False  # rides the verify block (engine must be armed)
    bucket: int | None = None
    t_admit: float | None = None
    t_first: float | None = None
    t_done: float | None = None
    tokens: list[int] = dataclasses.field(default_factory=list)
    state: str = "queued"  # queued | running | done
    finish_reason: str | None = None
    # Serving observatory (ISSUE 13): the request's lifecycle trace
    # (phase dicts, mirrored as serve.trace events when tracing is
    # armed), its per-tick ITL observations (tick wall / tokens
    # committed — what the SLO gate and the access log read), the last
    # backpressure reason while queued, and its SLO violation count.
    trace: list[dict] = dataclasses.field(default_factory=list)
    itl_s: list[float] = dataclasses.field(default_factory=list)
    queue_reason: str | None = None
    slo_violations: int = 0
    drained: bool = False
    t_last_tick: float | None = None
    # End-to-end tracing (ISSUE 18): the propagated cross-process
    # TraceContext (obs.trace.TraceContext) when this request arrived
    # through the front door, else None — the untraced path stays one
    # `is not None` check.
    trace_ctx: Any = None
    # Disaggregated serving (ISSUE 19): a validated KVPageSet loaded at
    # submit (kv_key=...) — its pages restore at admission instead of
    # being recomputed; None rides the classic local-prefill path.
    kv_import: Any = None

    @property
    def done(self) -> bool:
        return self.state == "done"

    @property
    def group(self) -> str:
        """Traffic-group label: (fp|int8).(plain|spec) — the scheduler's
        decode-block partition, the split the SLO histograms report by."""
        return _ledger.group_key(self.quantize, self.speculative)

    @property
    def terminal_phase(self) -> str | None:
        """The trace's terminal phase (complete | drained), or None while
        the request is still in flight (or tracing is disarmed)."""
        for t in reversed(self.trace):
            if t.get("phase") in ("complete", "drained"):
                return t["phase"]
        return None

    @property
    def ttft_s(self) -> float | None:
        """Submit → first generated token (the prefill logits' argmax)."""
        if self.t_first is None:
            return None
        return self.t_first - self.t_submit

    @property
    def decode_tokens_per_s(self) -> float | None:
        """Post-first-token decode rate (the slot's steady-state share of
        the batched decode program)."""
        if self.t_done is None or self.t_first is None:
            return None
        n = len(self.tokens) - 1
        dur = self.t_done - self.t_first
        if n <= 0 or dur <= 0:
            return None
        return n / dur

    def result(self) -> np.ndarray:
        """Generated tokens so far (complete once ``done``)."""
        return np.asarray(self.tokens, np.int32)


class ServeEngine:
    """Request-level continuous-batching engine over one model.

    Greedy decoding only (the serving contract is token-exactness vs a
    solo ``generate(temperature=0)`` of the same prompt; stochastic
    per-request sampling would need per-slot rng plumbing that nothing
    consumes yet). Single-process: the cache lives on the default device
    set; on a sharded mesh the slot axis shards over 'data' through
    GSPMD exactly like the batch predictor's batches.
    """

    def __init__(
        self,
        model,
        params,
        *,
        max_slots: int | None = None,
        prefill_chunk: int | None = None,
        buckets=None,
        decode_block: int | None = None,
        pad_id: int = 0,
        quant: str | bool | None = None,
        paged: bool | None = None,
        page_size: int | None = None,
        n_pages: int | None = None,
        prefix_cache: bool | None = None,
        speculative: int | bool | None = None,
        spec_ngram: int = 3,
        role: str | None = None,
        kv_store_dir: str | None = None,
        kv_host_mb: float | None = None,
        kv_disk_dir: str | None = None,
    ):
        self.model = model
        self.params = params
        # Per-request int8 (ISSUE 9): quantize ONCE at construction and
        # keep both numeric paths' params resident — requests pick a
        # path at submit, never a recompile. The quantized tree is a
        # derived view of the same fp params (QuantLeaf pytrees), so
        # checkpoint reload/hot-swap stories stay single-source.
        self.quant_mode = resolve_serve_quant(quant)
        self._qmodel = self._qparams = None
        if self.quant_mode is not None:
            from tpuflow.infer.quant import (
                QuantizedModel,
                quant_decision,
                quantize_model,
            )

            if isinstance(model, QuantizedModel):
                raise ValueError(
                    "ServeEngine(quant=...) wants the raw fp model/params "
                    "and owns both numeric paths; got an already-quantized "
                    "model — drop the wrapper or drop the quant arg"
                )
            dec = quant_decision(params, mode=self.quant_mode)
            obs.event(
                "quant.decision",
                apply=True,  # per-request opt-in: forced, gate advisory
                mode=dec.mode,
                weight_mib=round(dec.weight_bytes / 2**20, 1),
                reason="serve engine per-request int8 (submit(quantize=))",
            )
            self._qmodel, self._qparams = quantize_model(
                model, params, mode=self.quant_mode
            )
        self.n_ctx = int(model.config.n_ctx)
        self.max_slots = (
            int(max_slots)
            if max_slots is not None
            else _env_int("TPUFLOW_SERVE_SLOTS", 8)
        )
        if self.max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {self.max_slots}")
        if prefill_chunk is None:
            prefill_chunk = (
                _env_int("TPUFLOW_SERVE_PREFILL_CHUNK", 0, minimum=0) or None
            )
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}"
            )
        self.prefill_chunk = prefill_chunk
        self.buckets = resolve_buckets(self.n_ctx, buckets)
        self.decode_block = (
            int(decode_block)
            if decode_block is not None
            else _env_int("TPUFLOW_SERVE_DECODE_BLOCK", 8)
        )
        if self.decode_block < 1:
            raise ValueError(
                f"decode_block must be >= 1, got {self.decode_block}"
            )
        self.pad_id = int(pad_id)
        # Serving observatory (ISSUE 13): lifecycle tracing, the
        # engine-time ledger (buckets sum to serve wall by
        # construction), declared SLOs, and the per-request access log.
        # All host-side — no jitted program gains an operand, so
        # compile_stats() is identical with everything armed.
        self._trace_on = _env_flag("TPUFLOW_SERVE_TRACE", True)
        self._access_on = _env_flag("TPUFLOW_SERVE_ACCESS_LOG", True)
        self._access: _ledger.AccessLog | None = None
        self.ledger = _ledger.ServeLedger(
            slo_ttft_s=_ledger.resolve_slo_s("TPUFLOW_SERVE_SLO_TTFT_MS"),
            slo_itl_s=_ledger.resolve_slo_s("TPUFLOW_SERVE_SLO_ITL_MS"),
        )
        # Device observatory (ISSUE 15): the anomaly-armed profiler
        # capturer (None unless TPUFLOW_PROF_TRIGGER — the disarmed
        # path is one `is not None` check per decode tick).
        self._profcap = _profcap.maybe_from_env()

        S = self.max_slots
        # Paged KV (ISSUE 11): the pool geometry + the per-slot page
        # tables. The decode model is the SAME module cloned with the
        # pool geometry in its config (params untouched) — geometry is
        # static by construction, tables are data.
        self.paged = (
            _env_flag("TPUFLOW_SERVE_PAGED", True) if paged is None
            else bool(paged)
        )
        self.spec_draft = resolve_spec_draft(speculative)
        self.spec_ngram = int(spec_ngram)
        if self.spec_ngram < 2:
            raise ValueError(f"spec_ngram must be >= 2, got {spec_ngram}")
        if self.spec_draft and not self.paged:
            raise ValueError(
                "per-request speculative decode needs the paged cache "
                "(the contiguous slot rows' block write clamps at the "
                "n_ctx edge; paging routes overshoot to the trash page) "
                "— drop paged=False or TPUFLOW_SERVE_PAGED=0"
            )
        # Disaggregated serving (ISSUE 19): the engine role, the
        # shared KV-page store (ship/import), and the tiered prefix
        # cache. Everything defaults off/"both" — an engine built with
        # no kv knobs is byte-identical to the classic one.
        self.role = resolve_serve_role(role)
        kv_dir = (
            kv_store_dir if kv_store_dir is not None
            else knobs.raw("TPUFLOW_KV_STORE_DIR")
        )
        self.kv_store = _kvstore.KVStore(kv_dir) if kv_dir else None
        self._tier: _kvstore.TierCache | None = None
        self._prefill_calls = 0
        self._row_tmpl = None
        self._pmodel = self._qpmodel = None
        self.pool = None
        if self.paged:
            self.page_size = resolve_page_size(self.n_ctx, page_size)
            self.pages_per_slot = self.n_ctx // self.page_size
            default_pages = S * self.pages_per_slot + 1
            self.n_pages = (
                int(n_pages) if n_pages is not None
                else _env_int("TPUFLOW_SERVE_PAGES", default_pages,
                              minimum=2)
            )
            if self.n_pages < 2:
                raise ValueError(
                    f"n_pages must be >= 2 (page 0 is the trash page), "
                    f"got {self.n_pages}"
                )
            use_prefix = (
                _env_flag("TPUFLOW_SERVE_PREFIX_CACHE", True)
                if prefix_cache is None else bool(prefix_cache)
            )
            # Tiered prefix cache (ISSUE 19): both tiers default OFF —
            # the untiered pool is byte-identical to PR 11.
            host_mb = (
                float(kv_host_mb) if kv_host_mb is not None
                else float(knobs.get_float("TPUFLOW_KV_HOST_MB"))
            )
            tier_disk = (
                kv_disk_dir if kv_disk_dir is not None
                else knobs.raw("TPUFLOW_KV_DISK_DIR")
            )
            if use_prefix and (host_mb > 0 or tier_disk):
                self._tier = _kvstore.TierCache(
                    host_bytes=int(host_mb * 2**20),
                    disk_dir=tier_disk or None,
                    index_max=int(knobs.get_int("TPUFLOW_KV_INDEX_MAX")),
                    disk_max_bytes=int(
                        float(knobs.get_float("TPUFLOW_KV_DISK_MB"))
                        * 2**20
                    ),
                )
            self.pool = PagePool(
                self.n_pages, self.page_size, prefix_cache=use_prefix,
                tier_cache=self._tier,
                page_reader=(
                    self._read_page_host
                    if self._tier is not None else None
                ),
            )
            self._page_table = np.zeros(
                (S, self.pages_per_slot), np.int32
            )
            self._slot_pages: list[list[int]] = [[] for _ in range(S)]
            self._pmodel = model.clone(
                config=dataclasses.replace(
                    model.config,
                    kv_pages=self.n_pages,
                    kv_page_size=self.page_size,
                )
            )
        self._queue: collections.deque[ServeRequest] = collections.deque()
        self._slots: list[ServeRequest | None] = [None] * S
        self._tok = np.zeros((S,), np.int32)
        self._lengths = np.zeros((S,), np.int32)
        self._pads = np.zeros((S,), np.int32)
        self._remaining = np.zeros((S,), np.int32)
        self._live = np.zeros((S,), bool)
        self._quant = np.zeros((S,), bool)  # slot rides the int8 path
        self._spec = np.zeros((S,), bool)  # slot rides the verify block
        self._eos = np.full((S,), -1, np.int32)
        self._next_id = 0
        self._iters = 0
        self._completed = 0
        self._emitted_tokens = 0
        self._spec_committed = 0
        self._spec_forwards = 0
        self._last_gauges: tuple | None = None
        self._cache = self._init_cache()

        decode_model = self._pmodel if self.paged else self.model
        self._prefill = jax.jit(
            functools.partial(self._prefill_fn, self.model),
            static_argnames=("chunk",),
        )
        if self.paged:
            self._insert = jax.jit(
                self._page_insert_fn, donate_argnums=(0,)
            )
        else:
            self._insert = jax.jit(self._insert_fn, donate_argnums=(0,))
        self._decode = jax.jit(
            functools.partial(self._decode_fn, decode_model),
            donate_argnums=(1,),
        )
        self._verify = None
        if self.spec_draft:
            self._verify = jax.jit(
                functools.partial(self._verify_fn, decode_model),
                donate_argnums=(1,),
            )
        self._prefill_q = self._decode_q = self._verify_q = None
        if self.quant_mode is not None:
            # The int8 twins: same program SHAPES (slot arrays, cache
            # pytree, bucket widths), different static model + params
            # pytree — so fp and int8 requests interleave through one
            # engine with zero fresh compiles after warmup.
            qdecode_model = self._qmodel
            if self.paged:
                # The int8 wrapper around the PAGED clone for the decode
                # programs (the prefill twin keeps the row-cache model).
                self._qpmodel = dataclasses.replace(
                    self._qmodel, model=self._pmodel
                )
                qdecode_model = self._qpmodel
            self._prefill_q = jax.jit(
                functools.partial(self._prefill_fn, self._qmodel),
                static_argnames=("chunk",),
            )
            self._decode_q = jax.jit(
                functools.partial(self._decode_fn, qdecode_model),
                donate_argnums=(1,),
            )
            if self.spec_draft:
                self._verify_q = jax.jit(
                    functools.partial(self._verify_fn, qdecode_model),
                    donate_argnums=(1,),
                )

    # ------------------------------------------------------- jitted programs
    def _init_cache(self):
        """Zeroed KV cache with the decode model's exact cache pytree
        (eval_shape — no compile, no garbage forward): a (n_pages,
        page_size) pool when paged, per-slot (max_slots, n_ctx) rows
        otherwise."""

        def mk(params):
            if self.paged:
                _, variables = self._pmodel.apply(
                    {"params": params},
                    jnp.zeros((self.max_slots, 1), jnp.int32),
                    decode=True,
                    mutable=["cache"],
                    slot_index=jnp.zeros((self.max_slots,), jnp.int32),
                    page_table=jnp.zeros(
                        (self.max_slots, self.pages_per_slot), jnp.int32
                    ),
                )
            else:
                _, variables = self.model.apply(
                    {"params": params},
                    jnp.zeros((self.max_slots, 1), jnp.int32),
                    decode=True,
                    mutable=["cache"],
                )
            return variables["cache"]

        shapes = jax.eval_shape(mk, self.params)
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes
        )

    def _prefill_fn(self, model, params, prompt, pads, *, chunk):
        """(1, W) admission prefill → (first greedy token (1,), cache row).
        One program per bucket width W (chunk is fixed per engine);
        ``model`` is partial-bound per numeric path (fp / int8)."""
        logits, cache = chunked_prefill(
            model, params, prompt, chunk, pad_lens=pads
        )
        tok0 = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        return tok0, cache

    def _insert_fn(self, cache, row_cache, slot):
        """Write a (1, n_ctx) prefill cache row into ``slot`` of the big
        cache. K/V leaves are (S, n_ctx, H, D) (or (L, S, n_ctx, H, D)
        under scan_layers — the slot axis sits 4 dims from the end);
        scalar index leaves pass through untouched (slot mode never reads
        them)."""

        def put(big, row):
            if big.ndim >= 4:
                start = (0,) * (big.ndim - 4) + (slot, 0, 0, 0)
                return jax.lax.dynamic_update_slice(
                    big, row.astype(big.dtype), start
                )
            return big

        return jax.tree_util.tree_map(put, cache, row_cache)

    def _page_insert_fn(self, cache, row_cache, table_row, pad, write_mask):
        """Paged admission insert: strip the (1, n_ctx) prefill row's
        LEFT padding (roll by ``pad`` — the real prompt kv moves to
        logical columns [0, len), making cache content pad-invariant,
        the property prefix sharing rests on) and scatter its logical
        pages into the pool slots ``table_row`` names. ``write_mask``
        guards each page: shared prefix pages and unneeded tail entries
        are masked OFF — their writes route to the trash page — so a
        refcounted page is never rewritten by a matching admission.
        All three controls are DATA (no recompile per admission)."""
        ps = self.page_size
        pages_per_slot = self.pages_per_slot
        idx = jnp.where(write_mask, table_row, 0)

        def put(pool, row):
            if pool.ndim < 4 or row.ndim < 4:
                return pool  # scalar index leaves pass through

            def one(pl, rw):
                shifted = jnp.roll(rw[0], -pad, axis=0)  # (n_ctx, H, D)
                pages = shifted.reshape(
                    pages_per_slot, ps, *shifted.shape[1:]
                ).astype(pl.dtype)
                return pl.at[idx].set(
                    jnp.where(
                        write_mask[:, None, None, None], pages, pl[idx]
                    )
                )

            lead = pool.ndim - 4
            p2 = pool.reshape((-1,) + pool.shape[lead:])
            r2 = row.reshape((-1,) + row.shape[row.ndim - 4:])
            return jax.vmap(one)(p2, r2).reshape(pool.shape)

        return jax.tree_util.tree_map(put, cache, row_cache)

    def _verify_fn(self, model, params, cache, page_table, tok, draft,
                   lengths, pads, remaining, live, eos):
        """The speculative verify block (paged engines only): ONE
        (S, draft_len + 1) forward over [cur, draft...] per slot, then a
        PER-ROW commit — the accepted draft prefix plus the model's
        bonus token at the first disagreement, truncated by each row's
        eos / budget / capacity. Rows advance independently (the paged
        cache has no shared index to rewind; rejected-tail kv beyond a
        row's new frontier is masked until its own next forward
        overwrites it — the solo ladder's rewind argument, per row).
        Acceptance compares argmaxes of this one forward, width-safe
        under decode_precision='highest' (and exactly under int8's
        integer contractions), so committed tokens are bit-equal to
        single-token greedy decode. Returns
        (cache, emitted (S, K+1), tok, lengths, remaining, live)."""
        K = self.spec_draft
        n_ctx = self.n_ctx
        pad_id = self.pad_id
        S = tok.shape[0]
        x = jnp.concatenate([tok[:, None], draft], axis=1)  # (S, K+1)
        logits, variables = model.apply(
            {"params": params, "cache": cache},
            x,
            decode=True,
            mutable=["cache"],
            pad_lens=pads,
            slot_index=lengths,
            page_table=page_table,
        )
        cache = variables["cache"]
        am = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (S, K+1)
        # am[:, j] = the model's token after (cur, d_0..d_{j-1});
        # acceptance = leading agreement with the draft, as in the solo
        # ladder — but applied PER ROW.
        match = am[:, :K] == draft
        a = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        j = jnp.arange(K + 1)
        # Committed window w[0..a] = accepted drafts then the bonus
        # token; entries past a are junk a masked commit never reads.
        w = jnp.where(
            j[None, :] < a[:, None],
            jnp.pad(draft, ((0, 0), (0, 1))),
            am[jnp.arange(S)[:, None], jnp.minimum(j[None, :], a[:, None])],
        )
        # Per-row commit count: acceptance + bonus, capped by budget and
        # capacity (live rows hold remaining >= 1 and lengths < n_ctx,
        # so c >= 1 — every verify makes progress, no livelock).
        c = jnp.minimum(jnp.minimum(a + 1, remaining), n_ctx - lengths)
        # eos truncation: commit up to and INCLUDING the first eos in
        # the window (generate()'s eos-is-emitted contract), then die.
        is_eos = w == eos[:, None]  # eos == -1 never matches real tokens
        first_eos = jnp.argmax(is_eos, axis=1)  # 0 when none (guarded)
        has_eos = jnp.any(is_eos & (j[None, :] < c[:, None]), axis=1)
        c = jnp.where(has_eos, jnp.minimum(c, first_eos + 1), c)
        c = jnp.where(live, c, 0)
        emitted = jnp.where(j[None, :] < c[:, None], w, pad_id)
        new_tok = w[jnp.arange(S), jnp.maximum(c - 1, 0)]
        tok = jnp.where(c > 0, new_tok, tok)
        lengths = lengths + c
        remaining = remaining - c
        live = live & ~has_eos & (remaining > 0) & (lengths < n_ctx)
        # Same carry layout as the decode block: the scheduler merges and
        # harvests both programs through one code path (tokens-per-row =
        # the remaining-budget delta, which c already decremented).
        return cache, emitted, tok, lengths, remaining, live

    def _decode_fn(self, model, params, cache, tok, lengths, pads,
                   remaining, live, eos, page_table=None):
        """THE persistent decode program: ``decode_block`` single-token
        steps over every slot, per-slot freezing inside the scan. One
        host sync per block. Dead slots keep rewriting one cache column
        with pad-token k/v — masked out of every live row (paged: routed
        to the trash page by their zeroed tables), overwritten by the
        next admission's insert. ``model`` is partial-bound per numeric
        path AND cache layout: the int8 twin runs the same program shape
        with the fused-native W8A8 matmuls; the paged twin threads
        ``page_table`` (loop-invariant data) into every step."""
        n_ctx = self.n_ctx
        pad_id = self.pad_id

        def one(carry, _):
            cache, tok, lengths, remaining, live = carry
            logits, variables = model.apply(
                {"params": params, "cache": cache},
                tok[:, None],
                decode=True,
                mutable=["cache"],
                pad_lens=pads,
                slot_index=lengths,
                page_table=page_table,
            )
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            emitted = jnp.where(live, nxt, pad_id)
            lengths = jnp.where(live, lengths + 1, lengths)
            remaining = jnp.where(live, remaining - 1, remaining)
            # eos itself IS emitted (generate()'s contract); the slot
            # freezes after it. `lengths < n_ctx` guards the NEXT write.
            live = (
                live
                & (nxt != eos)
                & (remaining > 0)
                & (lengths < n_ctx)
            )
            return (
                variables["cache"], emitted, lengths, remaining, live
            ), emitted

        (cache, tok, lengths, remaining, live), toks = jax.lax.scan(
            one,
            (cache, tok, lengths, remaining, live),
            None,
            length=self.decode_block,
        )
        return cache, toks.T, tok, lengths, remaining, live

    # ------------------------------------------------------------ scheduling
    def bucket_for(self, prompt_len: int, max_new_tokens: int) -> int:
        """Smallest bucket width holding the prompt whose capacity check
        passes. Paged engines check the REAL prompt length against n_ctx
        (the page insert strips bucket pads, so pads cost prefill FLOPs
        only, never cache columns); contiguous slot rows keep the PR 8
        rule — bucket pads eat cache columns, so the check is on the
        padded width."""
        for w in self.buckets:
            if prompt_len > w:
                continue
            fits = (
                prompt_len + max_new_tokens <= self.n_ctx
                if self.paged
                else w + max_new_tokens <= self.n_ctx
            )
            if fits:
                return w
        raise ValueError(
            f"no prefill bucket fits prompt_len={prompt_len} + "
            f"max_new_tokens={max_new_tokens} within n_ctx={self.n_ctx} "
            f"(buckets: {self.buckets})"
        )

    def _pages_needed(self, req: ServeRequest) -> int:
        """Pages covering every logical column the request's programs
        can touch: prompt + budget, plus the verify block's draft-length
        overshoot slack for speculative requests (rejected-tail writes
        land in-bounds; >= n_ctx routes to trash)."""
        slack = self.spec_draft if req.speculative else 0
        top = min(self.n_ctx, req.prompt.size + req.max_new_tokens + slack)
        return -(-top // self.page_size)

    def submit(
        self,
        prompt,
        *,
        max_new_tokens: int,
        eos_id: int | None = None,
        quantize: bool = False,
        speculative: bool | None = None,
        trace: Any = None,
        kv_key: str | None = None,
    ) -> ServeRequest:
        """Enqueue one request; returns its live handle. Validation is
        eager (a request that can never fit must fail at submit, not
        half-way through a decode block). ``quantize=True`` routes the
        request through the engine's int8 programs (requires a
        quant-armed engine: ``quant=`` / ``TPUFLOW_SERVE_QUANT``).
        ``speculative`` routes it through the verify block on a
        spec-armed engine (None = the engine default: on when armed);
        ``speculative=True`` on an unarmed engine raises — the verify
        programs compile at warmup, never mid-flight. ``kv_key`` names a
        shipped page set in the engine's KV store (ISSUE 19): a loadable
        matching set admits the request already-prefilled; a missing /
        torn / mismatched one degrades to local prefill (``kv_fallback``
        trace), never an error."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if quantize and self.quant_mode is None:
            raise ValueError(
                "submit(quantize=True) needs a quant-armed engine: pass "
                "ServeEngine(quant='fused_native') or set "
                "TPUFLOW_SERVE_QUANT=1 (the int8 programs compile at "
                "warmup, never mid-flight)"
            )
        if speculative and not self.spec_draft:
            raise ValueError(
                "submit(speculative=True) needs a spec-armed engine: "
                "pass ServeEngine(speculative=K) or set "
                "TPUFLOW_SERVE_SPEC=K (the verify programs compile at "
                "warmup, never mid-flight)"
            )
        spec = bool(self.spec_draft) if speculative is None else bool(
            speculative
        )
        kv_import = None
        if kv_key is not None and self.kv_store is not None and self.paged:
            with obs.span("serve.kv_import", key=kv_key) as sp:
                pset = self.kv_store.load(kv_key)
                if pset is not None and self._import_ok(
                    pset, prompt, quantize
                ):
                    kv_import = pset
                sp.set(
                    ok=kv_import is not None,
                    pages=0 if pset is None else pset.n_pages,
                )
        bucket = self.bucket_for(prompt.size, max_new_tokens)
        req = ServeRequest(
            id=self._next_id,
            prompt=prompt,
            max_new_tokens=int(max_new_tokens),
            eos_id=None if eos_id is None else int(eos_id),
            t_submit=time.monotonic(),
            quantize=bool(quantize),
            speculative=spec,
            bucket=bucket,
            trace_ctx=trace,
        )
        if self.paged and self._pages_needed(req) > self.pool.usable_pages:
            raise ValueError(
                f"request needs {self._pages_needed(req)} pages but the "
                f"pool holds {self.pool.usable_pages} usable pages "
                f"(n_pages={self.n_pages}, page_size={self.page_size}) — "
                "it could never admit; raise TPUFLOW_SERVE_PAGES"
            )
        req.kv_import = kv_import
        self._next_id += 1
        self._queue.append(req)
        self._trace(
            req, "submitted", prompt_len=int(prompt.size),
            max_new=req.max_new_tokens, bucket=bucket, group=req.group,
        )
        if kv_key is not None and kv_import is None:
            # Local-prefill fallback: the shipped set was missing, torn,
            # or mismatched — the request proceeds as if never shipped.
            self._trace(req, "kv_fallback", key=kv_key)
        return req

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def live_slots(self) -> int:
        return int(self._live.sum())

    def compile_stats(self) -> dict[str, int]:
        """Jit-cache sizes of the engine's programs (including the int8
        twins on a quant-armed engine and the speculative verify blocks
        on a spec-armed one). After ``warmup()`` these must never grow —
        the never-recompile contract, pinned by tests/test_serve.py."""
        stats = {
            "prefill": int(self._prefill._cache_size()),
            "insert": int(self._insert._cache_size()),
            "decode": int(self._decode._cache_size()),
        }
        if self.spec_draft:
            stats["verify"] = int(self._verify._cache_size())
        if self.quant_mode is not None:
            stats["prefill_q"] = int(self._prefill_q._cache_size())
            stats["decode_q"] = int(self._decode_q._cache_size())
            if self.spec_draft:
                stats["verify_q"] = int(self._verify_q._cache_size())
        return stats

    def residency_efficiency(self) -> float | None:
        """HBM residency: tokens resident (live slots' committed cache
        columns) / tokens allocated (live slots' held pages x page_size;
        contiguous engines hold a full n_ctx row per live slot). The
        bench's paged-vs-slot headline — short requests strand most of a
        contiguous row but only their own pages. None when idle."""
        live = np.nonzero(self._live)[0]
        if live.size == 0:
            return None
        resident = int((self._lengths[live] - self._pads[live]).sum())
        if self.paged:
            allocated = sum(
                len(self._slot_pages[int(s)]) for s in live
            ) * self.page_size
        else:
            allocated = int(live.size) * self.n_ctx
        if allocated <= 0:
            return None
        return resident / allocated

    # ------------------------------------- disaggregated serving (ISSUE 19)
    def _cache_leaf_items(self, tree):
        """``(path-key, leaf)`` for every pool-shaped KV leaf (>= 4
        dims: ``(..., pages_or_slot, tokens, H, D)``) in canonical
        flatten order — the shared leaf naming that page bundles,
        shipped sets, and the tier store all key on."""
        out = []
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            if getattr(leaf, "ndim", 0) >= 4:
                out.append((jax.tree_util.keystr(path), leaf))
        return out

    def _read_page_host(self, pid: int) -> dict[str, np.ndarray]:
        """Pool page ``pid`` as a host-side per-leaf bundle ``(...,
        page_size, H, D)`` — the spill/promotion unit. Eager gathers:
        no named program, so ``compile_stats()`` never sees this."""
        out = {}
        for key, leaf in self._cache_leaf_items(self._cache):
            out[key] = np.asarray(
                jnp.take(leaf, pid, axis=leaf.ndim - 4)
            )
        return out

    def _row_template(self):
        """Shape/dtype pytree of a prefill cache row via
        ``jax.eval_shape`` (no compile, no device work), cached. Row
        leaves are bucket-independent — ``(..., 1, n_ctx, H, D)`` KV
        plus the row model's index scalars — so one template serves
        every restore."""
        if self._row_tmpl is None:
            W = self.buckets[0]
            pads = prompt_lens_to_pad_lens([1], 1, W)
            chunk = normalize_prefill_chunk(self.prefill_chunk, W)
            self._row_tmpl = jax.eval_shape(
                functools.partial(
                    self._prefill_fn, self.model, chunk=chunk
                ),
                self.params, jnp.zeros((1, W), jnp.int32), pads,
            )[1]
        return self._row_tmpl

    def _synth_row(self, pages: dict[int, dict[str, np.ndarray]]):
        """A zeroed prefill-row pytree with ``pages`` (logical page
        index -> bundle) written at their columns. Moulded on the
        :meth:`_row_template` shapes/dtypes — the EXACT signature of a
        real prefill row — so the warmed ``_insert`` scatters it with
        ``pad=0`` and zero fresh compiles (pinned by
        tests/test_serve_disagg.py). Index scalars are zeroed host
        arrays: the insert passes them through unread, and a fresh
        buffer never aliases the donated cache operand."""
        ps = self.page_size

        def mk(path, leaf):
            row = np.zeros(leaf.shape, leaf.dtype)
            if row.ndim < 4:
                return row
            key = jax.tree_util.keystr(path)
            for j, bundle in pages.items():
                page = bundle.get(key)
                if page is not None:
                    row[..., 0, j * ps:(j + 1) * ps, :, :] = page
            return row

        return jax.tree_util.tree_map_with_path(mk, self._row_template())

    def _restore_pages(
        self, table_row: np.ndarray, pages: dict[int, dict]
    ) -> None:
        """Scatter restored page bundles (tier promotions / shipped
        pages) into the pool slots ``table_row`` names — one masked
        ``_insert`` over a synthesized row, the admission insert's exact
        program signature."""
        if not pages:
            return
        write_mask = np.zeros((self.pages_per_slot,), bool)
        for j in pages:
            write_mask[j] = True
        # Device-resident leaves on purpose: the jit cache distinguishes
        # committed arrays (what the warmed insert saw — prefill output)
        # from host numpy operands, and a distinct entry would break the
        # never-recompile contract.
        row = jax.tree_util.tree_map(jnp.asarray, self._synth_row(pages))
        with self.ledger.bucket("insert"):
            self._cache = self._insert(
                self._cache, row, jnp.asarray(table_row),
                jnp.int32(0), jnp.asarray(write_mask),
            )

    def prefill_export(
        self, prompt, *, quantize: bool = False
    ) -> _kvstore.KVPageSet:
        """Run admission prefill for ``prompt`` and extract its KV pages
        as a :class:`~tpuflow.infer.kv_store.KVPageSet` — the
        prefill-role half of a disaggregated pair. The row comes from
        the SAME bucketed prefill program an admission uses, then is
        pad-stripped host-side (np.roll by ``-(W - L)``), so page
        content is bit-equal to what a local admission would have
        inserted (PR 11's pad-invariance). Includes the partial tail
        page (private to the request — decode writes land there) and
        the first greedy token, so an exact import admits with zero
        prefill."""
        if not self.paged:
            raise ValueError(
                "KV export needs the paged engine (TPUFLOW_SERVE_PAGED)"
            )
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must have at least one token")
        L = int(prompt.size)
        W = self.bucket_for(L, 1)
        padded = np.full((1, W), self.pad_id, np.int32)
        padded[0, W - L:] = prompt
        pads = prompt_lens_to_pad_lens([L], 1, W)
        chunk = normalize_prefill_chunk(self.prefill_chunk, W)
        prefill = self._prefill_q if quantize else self._prefill
        prm = self._qparams if quantize else self.params
        self._prefill_calls += 1
        with self.ledger.bucket("prefill"):
            tok0, row_cache = prefill(
                prm, jnp.asarray(padded), pads, chunk=chunk
            )
            first = int(np.asarray(tok0)[0])
        ps = self.page_size
        k_ship = -(-L // ps)
        pages: dict[str, np.ndarray] = {}
        for key, leaf in self._cache_leaf_items(row_cache):
            row = np.asarray(leaf)  # (..., 1, n_ctx, H, D)
            shifted = np.roll(row, -(W - L), axis=row.ndim - 3)
            sq = np.take(shifted, 0, axis=row.ndim - 4)
            lead = sq.shape[: sq.ndim - 3]
            paged = sq.reshape(
                lead + (self.pages_per_slot, ps) + sq.shape[-2:]
            )
            paged = np.moveaxis(paged, paged.ndim - 4, 0)
            pages[key] = np.ascontiguousarray(paged[:k_ship])
        return _kvstore.KVPageSet(
            page_size=ps,
            n_tokens=L,
            prompt=prompt,
            digests=_kvstore.chain_digests(prompt, ps),
            pages=pages,
            tok0=first,
            meta={"quant": bool(quantize)},
        )

    def ship(self, prompt, *, quantize: bool = False, store=None) -> str:
        """Prefill + commit: the prefill-role request path. Returns the
        committed ``kv_key`` the router forwards to a decode replica
        (``submit(..., kv_key=...)``)."""
        st = store if store is not None else self.kv_store
        if st is None:
            raise ValueError(
                "ship() needs a KV store: pass store= or set "
                "TPUFLOW_KV_STORE_DIR"
            )
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with obs.span(
            "serve.kv_ship", prompt_len=int(prompt.size),
            quant=bool(quantize),
        ) as sp:
            pset = self.prefill_export(prompt, quantize=quantize)
            key = st.commit(pset)
            sp.set(key=key, pages=pset.n_pages)
        return key

    def _import_ok(self, pset, prompt, quantize: bool) -> bool:
        """A shipped set is usable when its geometry and numeric path
        match and it covers this prompt — exactly (full ship: zero
        prefill) or as a digest-chain prefix (suffix resume: import the
        covered pages, prefill only the suffix). Anything else rides
        local prefill; the serving path never raises on a bad set."""
        if pset.page_size != self.page_size or not pset.pages:
            return False
        if bool(pset.meta.get("quant")) != bool(quantize):
            return False
        if pset.n_tokens == prompt.size and np.array_equal(
            np.asarray(pset.prompt, np.int32), prompt
        ):
            return True
        mine = _kvstore.chain_digests(prompt, self.page_size)
        return _kvstore.chain_match(pset.digests, mine) > 0

    def _note_first_token(self, req: ServeRequest, now: float) -> None:
        """TTFT bookkeeping — shared by the classic admission path and
        the prefill-free ones (full ship / decode-feed, where the first
        token lands in a decode harvest): same gauge, lifecycle trace,
        SLO gate, and goodput note either way."""
        req.t_first = now
        obs.gauge("serve.ttft_s", round(req.ttft_s, 6))
        self._trace(req, "first_token", ttft_s=round(req.ttft_s, 6))
        self.ledger.note_ttft(req.group, req.ttft_s)
        if self.ledger.check_ttft(req.ttft_s, group=req.group):
            self._slo_violation(
                req, "ttft", req.ttft_s, self.ledger.slo_ttft_s
            )
        ctx = req.trace_ctx
        obs.goodput_live().note_serve_ttft(
            req.ttft_s,
            trace_id=(
                ctx.trace_id
                if ctx is not None and ctx.recorded else None
            ),
        )

    # ------------------------------------------- lifecycle traces (ISSUE 13)
    def _trace(self, req: ServeRequest, phase: str, **attrs) -> None:
        """One lifecycle transition: appended to the request's host-side
        trace and mirrored as a serve.trace event. One bool check when
        disarmed (TPUFLOW_SERVE_TRACE=0) — pinned by the overhead test."""
        if not self._trace_on:
            return
        if req.trace_ctx is not None:
            # End-to-end tracing (ISSUE 18): lifecycle events carry the
            # propagated trace id; without a front-door context the key
            # is absent (never an empty string) — pinned by tests.
            attrs["trace_id"] = req.trace_ctx.trace_id
        req.trace.append({"phase": phase, "t": time.monotonic(), **attrs})
        obs.event("serve.trace", request=req.id, phase=phase, **attrs)

    def _tid(self, req: ServeRequest) -> dict:
        """``{"trace_id": ...}`` when a propagated context rides the
        request, else ``{}`` — spread into serve.* lifecycle events so
        the untraced shape is byte-identical to pre-trace builds."""
        ctx = req.trace_ctx
        return {} if ctx is None else {"trace_id": ctx.trace_id}

    def _note_queued(self, req: ServeRequest, reason: str) -> None:
        """Backpressure evidence: trace the queued phase once per reason
        change (a request waiting 10k iterations on a full pool must not
        write 10k events)."""
        if req.queue_reason != reason:
            req.queue_reason = reason
            self._trace(req, "queued", reason=reason)

    def _slo_violation(
        self, req: ServeRequest, kind: str, value: float, limit_s: float
    ) -> None:
        req.slo_violations += 1
        if req.trace_ctx is not None:
            # Tail sampling: an SLO breach force-records the trace even
            # when the head sampler skipped it.
            req.trace_ctx.escalate("slo")
        obs.event(
            "serve.slo_violation", request=req.id, slo=kind,
            value=round(value, 6), limit_s=limit_s, group=req.group,
            **self._tid(req),
        )
        obs.counter("serve.slo_violations", 1)
        if self._profcap is not None:
            # Direct capture trigger (ISSUE 15): a declared-SLO breach
            # is exactly the moment a device trace answers "why".
            self._profcap.note_slo_breach(kind)

    def _access_write(self, req: ServeRequest, terminal: str) -> None:
        """One access-log line at the request's terminal transition
        (complete or drained). Lazy: the writer opens beside the event
        fragments the first time a recorder-enabled process finishes a
        request — no obs dir, no file."""
        if not self._access_on:
            return
        if self._access is None:
            rec = obs.recorder()
            if rec is None:
                return
            self._access = _ledger.AccessLog(rec.directory, proc=rec.proc)
        ttft = req.ttft_s
        rate = req.decode_tokens_per_s
        self._access.write(
            {
                **self._tid(req),
                "request": req.id,
                "ts": req.t_submit,
                "group": req.group,
                "quant": req.quantize,
                "spec": req.speculative,
                "prompt_len": int(req.prompt.size),
                "max_new_tokens": req.max_new_tokens,
                "bucket": req.bucket,
                "tokens": len(req.tokens),
                "terminal": terminal,
                "finish_reason": req.finish_reason or terminal,
                "queue_wait_s": (
                    None if req.t_admit is None
                    else round(req.t_admit - req.t_submit, 6)
                ),
                "ttft_s": None if ttft is None else round(ttft, 6),
                "itl_s": [round(v, 6) for v in req.itl_s],
                "decode_tokens_per_s": (
                    None if rate is None else round(rate, 2)
                ),
                "slo_violations": req.slo_violations,
                "trace": req.trace,
            }
        )

    def drain_queued(self) -> int:
        """Terminal-trace every still-queued request as ``drained`` (the
        SIGTERM drain path: the process is exiting; queued work rides
        the requeue). The queue itself is untouched — a resumed engine
        can still admit them — but every submitted request's trace now
        reaches exactly one terminal event. Returns the count."""
        n = 0
        for req in self._queue:
            if req.drained:
                continue
            req.drained = True
            self._trace(req, "drained", reason="preempt_drain")
            self._access_write(req, "drained")
            if req.trace_ctx is not None:
                _reqtrace.flush_lifecycle(
                    req.trace_ctx, req.trace, engine_request=req.id
                )
            n += 1
        return n

    def _free_slot(self) -> int | None:
        for s, req in enumerate(self._slots):
            if req is None:
                return s
        return None

    def _admit_one(self, req: ServeRequest, slot: int) -> bool:
        """Admit ``req`` into ``slot``. Returns False (request untouched,
        caller leaves it queued) when the page pool cannot fit it —
        token-budget admission backpressure. Page acquisition precedes
        the prefill so a blocked request costs zero device work.

        Disaggregated admission (ISSUE 19): pages covered by an imported
        :class:`~tpuflow.infer.kv_store.KVPageSet` or by lower-tier
        promotions are RESTORED (a masked insert of their committed
        bytes — the admission insert's exact program signature) instead
        of recomputed. When restored + shared pages cover the prompt the
        prefill program never runs: an exact shipped set admits on its
        committed first token (full ship); otherwise the decode program
        is fed ``prompt[L-1]`` at ``lengths = L-1`` — it writes that
        column's kv and emits the first token, bit-equal to prefill by
        the cache-mediated-attention exactness PR 11 pinned (when column
        ``L-1`` lands in a covered page the decode write re-writes
        identical bytes, so shared pages stay sound). A request with
        neither rides the classic path byte-identically."""
        page_ids: list[int] | None = None
        matched = 0
        promoted: list[tuple[int, bytes, str]] = []
        if self.paged:
            got = self.pool.acquire(req.prompt, self._pages_needed(req))
            if got is None:
                self._note_queued(req, "pages")
                return False
            page_ids, matched = got
            promoted = self.pool.take_promotions()
        now = time.monotonic()
        req.t_admit = now
        W = req.bucket
        L = req.prompt.size
        ps = self.page_size if self.paged else 0
        pset = req.kv_import if self.paged else None
        # Restored pages: logical page index -> bundle, contiguous from
        # where HBM matching broke — tier promotions first, then shipped
        # pages extend the run. A failed tier fetch truncates the run;
        # everything past it rides the prefill write instead (never a
        # drop, never a gap).
        restored: dict[int, dict[str, np.ndarray]] = {}
        restore_src: dict[int, str] = {}
        for j, digest, _tier in promoted:
            if j != matched + len(restored):
                break
            got_b = self.pool.tier.fetch(digest)
            if got_b is None:
                break
            restored[j], restore_src[j] = got_b
        exact = (
            pset is not None
            and pset.n_tokens == L
            and np.array_equal(np.asarray(pset.prompt, np.int32),
                               req.prompt)
        )
        if pset is not None:
            k_full = _kvstore.chain_match(
                pset.digests, self.pool.prefix_digests(req.prompt)
            )
            top = pset.n_pages if exact else min(k_full, pset.n_pages)
            j = matched + len(restored)
            while j < min(top, len(page_ids)):
                restored[j] = pset.page_bundle(j)
                restore_src[j] = "ship"
                j += 1
        covered = matched + len(restored)
        full_ship = exact and pset.tok0 is not None and covered * ps >= L
        feed_decode = (
            not full_ship
            and self.paged
            and (pset is not None or self.pool.tier is not None)
            and covered >= 1
            and covered * ps >= L - 1
        )
        mode = (
            "ship" if full_ship else "feed" if feed_decode else "prefill"
        )
        table_row = write_mask = None
        if self.paged:
            table_row = np.zeros((self.pages_per_slot,), np.int32)
            table_row[: len(page_ids)] = page_ids
            write_mask = np.zeros((self.pages_per_slot,), bool)
            write_mask[matched: len(page_ids)] = True
            for j in restored:
                write_mask[j] = False  # restored bytes, not prefill's
        n_host = sum(1 for s in restore_src.values() if s == "host")
        n_disk = sum(1 for s in restore_src.values() if s == "disk")
        if n_host or n_disk:
            self.pool.tier_hits += n_host + n_disk
            obs.event(
                "serve.tier_hit", request=req.id, host=n_host,
                disk=n_disk, **self._tid(req),
            )
        if restored:
            self._restore_pages(table_row, restored)
            if n_host or n_disk:
                obs.event(
                    "serve.tier_promote", request=req.id,
                    pages=n_host + n_disk, **self._tid(req),
                )
        first: int | None = None
        row_cache = None
        if mode == "ship":
            first = int(pset.tok0)
            req.t_first = time.monotonic()
            req.t_last_tick = req.t_first
            req.tokens.append(first)
        elif mode == "feed":
            pass  # the first token comes out of the decode block
        else:
            padded = np.full((1, W), self.pad_id, np.int32)
            padded[0, W - L:] = req.prompt
            pads = prompt_lens_to_pad_lens([L], 1, W)
            chunk = normalize_prefill_chunk(self.prefill_chunk, W)
            prefill = self._prefill_q if req.quantize else self._prefill
            prm = self._qparams if req.quantize else self.params
            self._prefill_calls += 1
            with self.ledger.bucket("prefill"), obs.span(
                "serve.prefill", request=req.id, bucket=W,
                prompt_len=int(L), chunk=chunk, quant=bool(req.quantize),
            ):
                tok0, row_cache = prefill(
                    prm, jnp.asarray(padded), pads, chunk=chunk
                )
                first = int(np.asarray(tok0)[0])
            req.t_first = time.monotonic()
            req.t_last_tick = req.t_first
            req.tokens.append(first)
        req.state = "running"
        extra_trace = {}
        if mode != "prefill" or restored:
            extra_trace = {
                "prefilled": mode,
                "shipped_pages": sum(
                    1 for s in restore_src.values() if s == "ship"
                ),
                "promoted_pages": n_host + n_disk,
            }
        obs.event(
            "serve.admit", request=req.id, slot=slot, bucket=W,
            prompt_len=int(L),
            queue_wait_s=round(now - req.t_submit, 6),
            pages=0 if page_ids is None else len(page_ids),
            shared_pages=matched,
            **self._tid(req),
        )
        self._trace(
            req, "admitted", slot=slot, bucket=W,
            queue_wait_s=round(now - req.t_submit, 6),
            pages=0 if page_ids is None else len(page_ids),
            shared_pages=matched, **extra_trace,
        )
        if first is not None:
            self._note_first_token(req, req.t_first)
            done = (req.eos_id is not None and first == req.eos_id) or (
                req.max_new_tokens == 1
            )
            self._emitted_tokens += 1
            obs.goodput_live().note_serve_tokens(1)
            obs.counter("serve.tokens", 1)
            if done:
                if page_ids is not None:
                    self.pool.release(page_ids)
                self._finish(
                    req, "eos" if req.max_new_tokens > 1 else "budget"
                )
                return True
        if self.paged:
            if mode == "prefill":
                # Pad-stripped page insert: real prompt kv moves to
                # logical [0, L); shared prefix pages and restored pages
                # are masked OFF the write.
                with self.ledger.bucket("insert"):
                    self._cache = self._insert(
                        self._cache, row_cache, jnp.asarray(table_row),
                        jnp.int32(W - L), jnp.asarray(write_mask),
                    )
            self._page_table[slot] = table_row
            self._slot_pages[slot] = list(page_ids)
            self._lengths[slot] = L if mode != "feed" else L - 1
            self._pads[slot] = 0
        else:
            with self.ledger.bucket("insert"):
                self._cache = self._insert(
                    self._cache, row_cache, np.int32(slot)
                )
            self._lengths[slot] = W
            self._pads[slot] = W - L
        self._slots[slot] = req
        self._tok[slot] = (
            first if first is not None else int(req.prompt[L - 1])
        )
        self._remaining[slot] = (
            req.max_new_tokens - 1 if first is not None
            else req.max_new_tokens
        )
        self._live[slot] = True
        self._quant[slot] = req.quantize
        self._spec[slot] = req.speculative and self.spec_draft > 0
        self._eos[slot] = -1 if req.eos_id is None else req.eos_id
        return True

    def _finish(self, req: ServeRequest, reason: str) -> None:
        req.t_done = time.monotonic()
        req.state = "done"
        req.finish_reason = reason
        self._completed += 1
        rate = req.decode_tokens_per_s
        obs.event(
            "serve.complete", request=req.id, tokens=len(req.tokens),
            reason=reason, ttft_s=round(req.ttft_s, 6),
            decode_tokens_per_s=None if rate is None else round(rate, 2),
            **self._tid(req),
        )
        obs.counter("serve.requests", 1)
        if req.quantize:
            obs.counter("serve.quant_requests", 1)
        if rate is not None:
            obs.gauge("serve.tokens_per_s", round(rate, 2))
        self._trace(
            req, "complete", reason=reason, tokens=len(req.tokens),
            slo_violations=req.slo_violations,
        )
        self._access_write(req, "complete")
        if req.trace_ctx is not None:
            # Replica half of the cross-process timeline: convert the
            # lifecycle phases to wall-clock spans and flush them to
            # this replica's trace JSONL under the propagated trace id.
            _reqtrace.flush_lifecycle(
                req.trace_ctx, req.trace, engine_request=req.id
            )
        obs.goodput_live().note_serve_complete(req.group)

    def _emit_state_gauges(self) -> None:
        """Queue-depth / occupancy / page-pool gauges on change (plus a
        periodic refresh) — a long idle server must not flood the event
        stream."""
        pool = self.pool
        tier = None if pool is None else pool.tier
        state = (
            len(self._queue),
            self.live_slots,
            None if pool is None else pool.free_pages,
            None if pool is None else pool.prefix_hits,
            None if tier is None else tier.pages_host,
            None if tier is None else tier.pages_disk,
        )
        fr = self.ledger.fractions()
        if self._iters % 64 == 0:
            # Device observatory (ISSUE 15): throttled HBM poll on the
            # fence the scheduler already pays (self-disabling off-TPU;
            # one bool check thereafter), and the capture governor's
            # wall-deadline check for traces armed between decode ticks.
            _device.maybe_emit_hbm()
            if self._profcap is not None:
                self._profcap.poll()
        if state != self._last_gauges or self._iters % 64 == 0:
            self._last_gauges = state
            obs.gauge("serve.queue_depth", state[0])
            obs.gauge(
                "serve.slot_occupancy",
                round(state[1] / self.max_slots, 4),
            )
            if pool is not None:
                obs.gauge("serve.pages_free", state[2])
                obs.gauge("serve.prefix_hits", state[3])
            if tier is not None:
                obs.gauge("serve.pages_host", state[4])
                obs.gauge("serve.pages_disk", state[5])
            # Engine-time ledger fractions (ISSUE 13): the idle /
            # decode / prefill split one babysitter line reads, plus
            # the token-efficiency gauges, sampled on the same
            # change/periodic cadence as the load gauges. verify and
            # decode merge into one "earning tokens" fraction.
            obs.gauge("serve.idle_fraction", round(fr["idle"], 4))
            obs.gauge(
                "serve.decode_fraction",
                round(fr["decode"] + fr["verify"], 4),
            )
            obs.gauge("serve.prefill_fraction", round(fr["prefill"], 4))
            util = self.ledger.decode_utilization
            if util is not None:
                obs.gauge("serve.decode_utilization", round(util, 4))
            waste = self.ledger.masked_row_waste
            if waste is not None:
                obs.gauge("serve.masked_row_waste", round(waste, 4))
        led = obs.goodput_live()
        led.note_serve_state(state[0], state[1], self.max_slots)
        led.note_serve_ledger(
            {
                "idle": fr["idle"],
                "decode": fr["decode"] + fr["verify"],
                "prefill": fr["prefill"],
                "insert": fr["insert"],
                "host_sched": fr["host_sched"],
            },
            utilization=self.ledger.decode_utilization,
            masked_waste=self.ledger.masked_row_waste,
            slo_violations=self.ledger.slo_violations,
            slo_by_group=self.ledger.slo_by_group,
        )
        if pool is not None:
            led.note_serve_pages(pool.free_pages, pool.usable_pages)
            led.note_serve_prefix(pool.prefix_hits, pool.prefix_lookups)
        led.note_serve_role(self.role)
        if tier is not None:
            led.note_serve_tiers(
                tier.pages_host, tier.pages_disk, pool.tier_hits
            )

    def _run_decode_block(self, quant: bool, spec: bool = False) -> int:
        """One decode (or speculative verify) block over ONE group's
        slots — the groups partition the live set by (numeric path,
        speculative): run that group's persistent program with every
        OTHER group masked out of the live set, merge the per-slot state
        back through the group mask, harvest tokens, free exited slots.
        Returns emitted token count.

        Why masking composes: each slot row only ever attends within its
        own cache row (paged: its own pages), and a program only
        advances (and only writes real k/v for) rows live in ITS set — a
        masked-out row's garbage k/v writes land at its frozen
        ``lengths`` column onward, exactly where that row's OWN program
        writes real k/v next, so they are always overwritten before
        anything can attend to them (a verify block's K+1 garbage
        columns sit beyond the frozen frontier — masked out of every
        query until overwritten, the same argument the solo ladder's
        rewind rests on). Mixed fp+int8+speculative traffic therefore
        shares one cache and one engine with zero cross-talk (pinned by
        tests/test_serve.py)."""
        mask = self._live & (self._quant == quant) & (self._spec == spec)
        if not mask.any():
            return 0
        prm = self._qparams if quant else self.params
        old_remaining = self._remaining.copy()
        group_live = int(mask.sum())
        total_live = int(self._live.sum())
        # Two literal span calls (not one with a computed name): the
        # obs_lint drift guard only sees literal emitter names.
        span = (
            obs.span("serve.quant_decode", slots=int(mask.sum()), spec=spec)
            if quant
            else obs.span("serve.decode", slots=int(mask.sum()), spec=spec)
        )
        # The whole block — host drafts, device dispatch, the fence, the
        # state merge — charges to the decode (or verify) ledger bucket;
        # everything between blocks lands in host_sched by construction.
        with self.ledger.bucket("verify" if spec else "decode"), span as sp:
            if spec:
                # Host-side prompt-lookup drafts per slot (a wrong draft
                # only costs speed; the verify forward arbitrates).
                K = self.spec_draft
                drafts = np.zeros((self.max_slots, K), np.int32)
                for s in np.nonzero(mask)[0]:
                    req = self._slots[int(s)]
                    hist = np.concatenate(
                        [req.prompt, np.asarray(req.tokens, np.int32)]
                    )
                    drafts[s] = ngram_draft(hist, K, ngram=self.spec_ngram)
                verify = self._verify_q if quant else self._verify
                (
                    self._cache, toks, tok, lengths, remaining, live
                ) = verify(
                    prm,
                    self._cache,
                    jnp.asarray(self._page_table),
                    self._tok,
                    jnp.asarray(drafts),
                    self._lengths,
                    self._pads,
                    self._remaining,
                    mask,
                    self._eos,
                )
            else:
                decode = self._decode_q if quant else self._decode
                args = [
                    prm, self._cache, self._tok, self._lengths,
                    self._pads, self._remaining, mask, self._eos,
                ]
                if self.paged:
                    args.append(jnp.asarray(self._page_table))
                (
                    self._cache, toks, tok, lengths, remaining, live
                ) = decode(*args)
            # The host copy of the block's tokens IS the fence.
            # np.array (not asarray): the zero-copy view of a jax
            # array is read-only, and admissions write these. Merge
            # through the group mask — the program's carries hold
            # pad_id tokens for every row outside its live set,
            # including the OTHER groups' mid-flight slots.
            toks = np.asarray(toks)
            self._tok = np.where(mask, np.array(tok), self._tok)
            self._lengths = np.where(mask, np.array(lengths), self._lengths)
            self._remaining = np.where(
                mask, np.array(remaining), self._remaining
            )
            self._live = np.where(mask, np.array(live), self._live)
            emitted = int((old_remaining - self._remaining).sum())
            sp.set(tokens=emitted)
            self.ledger.note_decode_block(
                self.max_slots, group_live, total_live, spec=spec,
                drafted=group_live * self.spec_draft if spec else 0,
                committed=emitted,
            )
            if spec:
                self._spec_committed += emitted
                self._spec_forwards += int(mask.sum())
                rate = self._spec_committed / max(self._spec_forwards, 1)
                obs.gauge("serve.spec_accept_rate", round(rate, 4))
                obs.goodput_live().note_serve_spec(
                    self._spec_committed, self._spec_forwards
                )
        now = time.monotonic()
        led = obs.goodput_live()
        for s, req in enumerate(self._slots):
            if req is None or not mask[s]:
                continue
            n = int(old_remaining[s] - self._remaining[s])
            if n:
                req.tokens.extend(int(t) for t in toks[s, :n])
                # One ITL observation per tick (tick wall / tokens
                # committed): the per-token latency the SLO gate,
                # /metrics percentiles, and the access log all share.
                anchor = (
                    req.t_last_tick
                    if req.t_last_tick is not None else req.t_first
                )
                itl = None
                if anchor is not None:
                    itl = max(now - anchor, 0.0) / n
                    req.itl_s.append(itl)
                    self.ledger.note_itl(req.group, itl)
                    ctx = req.trace_ctx
                    led.note_serve_itl(
                        itl,
                        trace_id=(
                            ctx.trace_id
                            if ctx is not None and ctx.recorded
                            else None
                        ),
                    )
                    if self._profcap is not None:
                        # Median+MAD ITL spike detector (ISSUE 15); the
                        # same call advances a live capture's bound.
                        self._profcap.observe_itl(itl)
                if req.t_first is None:
                    # Prefill-free admission (ISSUE 19): the request's
                    # first token came out of the decode program, so
                    # TTFT lands on this harvest — after the ITL anchor
                    # above, which must not see a zero-width tick.
                    self._note_first_token(req, now)
                req.t_last_tick = now
                if spec:
                    self._trace(
                        req, "tick", tokens=n, spec=True,
                        drafted=self.spec_draft, accepted=n - 1,
                    )
                else:
                    self._trace(req, "tick", tokens=n, spec=False)
                if itl is not None and self.ledger.check_itl(
                    itl, group=req.group
                ):
                    self._slo_violation(
                        req, "itl", itl, self.ledger.slo_itl_s
                    )
            if not self._live[s]:
                last = req.tokens[-1] if req.tokens else None
                if req.eos_id is not None and last == req.eos_id:
                    reason = "eos"
                elif len(req.tokens) >= req.max_new_tokens:
                    reason = "budget"
                else:
                    reason = "capacity"  # n_ctx frontier hit
                self._finish(req, reason)
                self._slots[s] = None
                self._quant[s] = False
                self._spec[s] = False
                if self.paged:
                    self.pool.release(self._slot_pages[s])
                    self._slot_pages[s] = []
                    self._page_table[s, :] = 0
        return emitted

    @property
    def spec_accept_rate(self) -> float | None:
        """Cumulative tokens committed per speculative verify, per row
        (1.0 = speculation bought nothing; draft_len + 1 is the max)."""
        if not self._spec_forwards:
            return None
        return self._spec_committed / self._spec_forwards

    def step(self, admit: bool = True) -> bool:
        """One scheduler iteration: admit waiting requests into free
        slots (chunked prefill; paged engines also need the page pool to
        fit — a blocked head-of-queue request applies backpressure),
        then run one decode block per live group — (fp, int8) x (plain,
        speculative). Returns False when there was nothing to do."""
        self._iters += 1
        did = False
        while admit and self._queue:
            slot = self._free_slot()
            if slot is None:
                self._note_queued(self._queue[0], "slots")
                break
            if not self._admit_one(self._queue[0], slot):
                break  # page backpressure: stays queued, never dropped
            self._queue.popleft()
            did = True
        if self._live.any():
            did = True
            emitted = 0
            for quant in (False, True) if self.quant_mode else (False,):
                for spec in (False, True) if self.spec_draft else (False,):
                    emitted += self._run_decode_block(quant, spec)
            self._emitted_tokens += emitted
            obs.goodput_live().note_serve_tokens(emitted)
            if emitted:
                obs.counter("serve.tokens", emitted)
        self._emit_state_gauges()
        return did

    def run_until_idle(self, max_iters: int | None = None) -> None:
        """Drive the scheduler until queue and slots are empty."""
        iters = 0
        while self._queue or self._live.any():
            self.step()
            iters += 1
            if max_iters is not None and iters >= max_iters:
                raise RuntimeError(
                    f"engine not idle after {max_iters} iterations "
                    f"(queue={len(self._queue)}, live={self.live_slots})"
                )

    def generate_many(
        self,
        prompts,
        *,
        max_new_tokens: int,
        eos_id: int | None = None,
        quantize: bool = False,
        speculative: bool | None = None,
    ) -> list[np.ndarray]:
        """Submit every prompt, run to completion, return each request's
        generated tokens in submit order (the batch-predictor adapter)."""
        reqs = [
            self.submit(
                p, max_new_tokens=max_new_tokens, eos_id=eos_id,
                quantize=quantize, speculative=speculative,
            )
            for p in prompts
        ]
        self.run_until_idle()
        return [r.result() for r in reqs]

    # ---------------------------------------------------------------- warmup
    def _insert_warm_args(self):
        """The insert call's non-cache operands for a warmup/AOT pass:
        paged engines write one full table of trash-routed pages (table
        zeros + mask all-on exercises the real scatter against the
        reserved page), contiguous engines take slot 0."""
        if self.paged:
            return (
                jnp.zeros((self.pages_per_slot,), jnp.int32),
                jnp.int32(0),
                jnp.ones((self.pages_per_slot,), bool),
            )
        return (np.int32(0),)

    def _decode_warm_args(self):
        """Dead-slot operands for one decode/verify warmup execution."""
        args = [
            self._tok, self._lengths, self._pads, self._remaining,
            self._live, self._eos,
        ]
        if self.paged:
            args.append(jnp.asarray(self._page_table))
        return args

    def warmup(self, run_dir: str | None = None) -> dict[str, int]:
        """Compile-or-load every program the engine will ever run: the
        decode block (and the speculative verify block when armed), the
        insert, and one prefill per bucket — through the persistent
        compile cache (``maybe_enable_compile_cache``), so a server
        restart pays cache loads, not the BENCH_r05 62.9 s compile /
        125.1 s wall-to-first-step gap. Executes each program once on
        dead-slot state (guaranteed jit-cache hits afterwards; the
        garbage forwards are masked by ``live=False`` everywhere — paged
        writes land in the trash page) and restores a pristine cache.
        Returns ``compile_stats()``."""
        from tpuflow.dist import maybe_enable_compile_cache

        maybe_enable_compile_cache(run_dir)
        # Per-program compile fences (ISSUE 15): each first execution
        # below IS that program's trace+compile(-or-cache-load) wall, so
        # a couple of monotonic reads per program give the device
        # ledger its warmup-side compile_s entries for free. The AOT
        # path (collect_program_ledger / prewarm) later enriches the
        # same names with cost/memory analysis.
        marks: list[tuple[str, float]] = []

        def _fence(name: str, t0: float):
            marks.append((name, time.monotonic() - t0))

        with obs.span(
            "serve.warmup", buckets=len(self.buckets),
            quant=self.quant_mode or "off", paged=self.paged,
            spec=self.spec_draft,
        ) as sp:
            row_cache = None
            for w in self.buckets:
                chunk = normalize_prefill_chunk(self.prefill_chunk, w)
                t0 = time.monotonic()
                _, row_cache = self._prefill(
                    self.params,
                    jnp.zeros((1, w), jnp.int32),
                    prompt_lens_to_pad_lens([w], 1, w),
                    chunk=chunk,
                )
                _fence(f"prefill@{w}", t0)
                if self.quant_mode is not None:
                    # The int8 prefill ladder compiles beside the fp one
                    # — a quantize=True admission must be a cache hit.
                    t0 = time.monotonic()
                    _, row_cache = self._prefill_q(
                        self._qparams,
                        jnp.zeros((1, w), jnp.int32),
                        prompt_lens_to_pad_lens([w], 1, w),
                        chunk=chunk,
                    )
                    _fence(f"prefill_q@{w}", t0)
            if row_cache is not None:
                # First insert: the fresh (uncommitted) init cache.
                t0 = time.monotonic()
                self._cache = self._insert(
                    self._cache, row_cache, *self._insert_warm_args()
                )
                _fence("insert", t0)
            t0 = time.monotonic()
            out = self._decode(
                self.params, self._cache, *self._decode_warm_args()
            )
            self._cache = out[0]
            _fence("decode", t0)
            if self.spec_draft:
                # The verify block (and below, its int8 twin): dead-slot
                # drafts of zeros exercise the exact (S, K+1) signature
                # the speculative scheduler replays.
                zdraft = jnp.zeros(
                    (self.max_slots, self.spec_draft), jnp.int32
                )
                t0 = time.monotonic()
                out = self._verify(
                    self.params, self._cache,
                    jnp.asarray(self._page_table), self._tok, zdraft,
                    self._lengths, self._pads, self._remaining,
                    self._live, self._eos,
                )
                self._cache = out[0]
                _fence("verify", t0)
            if self.quant_mode is not None:
                # The int8 decode block on the decode-committed cache —
                # the exact signature the mixed-traffic scheduler replays.
                t0 = time.monotonic()
                out = self._decode_q(
                    self._qparams, self._cache, *self._decode_warm_args()
                )
                self._cache = out[0]
                _fence("decode_q", t0)
                if self.spec_draft:
                    t0 = time.monotonic()
                    out = self._verify_q(
                        self._qparams, self._cache,
                        jnp.asarray(self._page_table), self._tok, zdraft,
                        self._lengths, self._pads, self._remaining,
                        self._live, self._eos,
                    )
                    self._cache = out[0]
                    _fence("verify_q", t0)
            if row_cache is not None:
                # Second insert: the steady-state signature — a cache
                # COMMITTED by the decode program (with sharded params
                # the jit key differs from the fresh-zeros variant; both
                # must be warm or the first post-decode admission would
                # recompile, breaking the never-recompile contract).
                self._cache = self._insert(
                    self._cache, row_cache, *self._insert_warm_args()
                )
            # Warmup wrote garbage k/v into slot 0's columns; every query
            # of a future occupant is masked to its own [pad, length]
            # window and the insert overwrites the row, but start zeroed
            # anyway so warmup is observationally a no-op. x*0 (not a
            # fresh zeros tree): the result stays committed exactly like
            # every later decode/insert output, so the program signatures
            # warmed above are the ones the serving loop replays.
            self._cache = jax.tree_util.tree_map(
                lambda x: x * 0, self._cache
            )
            jax.block_until_ready(self._cache)
            stats = self.compile_stats()
            sp.set(**stats)
        if obs.recorder() is not None and knobs.get_bool(
            "TPUFLOW_DEVICE_LEDGER"
        ):
            # Warmup-side device ledger (ISSUE 15): per-program compile
            # wall into programs.json — a few buffered events and one
            # small JSON write, nothing on the serving hot path.
            try:
                ledger = _device.ProgramLedger(source="warmup")
                for name, dt in marks:
                    ledger.note_entry(
                        {"name": name, "compile_s": round(dt, 4)}
                    )
                ledger.write()
            except Exception as e:
                print(
                    f"[tpuflow] warmup device ledger failed (ignored): "
                    f"{e!r}"
                )
        return stats

    def aot_lower(
        self, max_new_tokens: int = 128, ledger=None
    ) -> int:
        """AOT-lower (``jit(...).lower(...).compile()``) every program
        signature this engine replays — decode block, speculative verify,
        page/slot insert, and each admittable bucket's prefill, plus the
        int8 twins on a quant-armed engine — WITHOUT executing anything
        (row caches come from ``eval_shape``). With the persistent
        compile cache enabled the executables land on disk, which is
        ``tools/prewarm_cache.py``'s whole job; the engine owns the
        signature list so the tool can't drift from the programs the
        scheduler actually runs. ``max_new_tokens`` prunes buckets the
        run could never admit into. Returns the program count.

        ``ledger`` (a ``tpuflow.obs.device.ProgramLedger``) records each
        compiled program's wall-s + cost/memory analysis as it lands —
        the AOT path holds the only object carrying both analyses, and
        lowering here never touches the jit dispatch cache, so
        ``compile_stats()`` is bitwise unchanged by ledger collection."""

        def _compile(name, lowered):
            t0 = time.monotonic()
            compiled = lowered.compile()
            if ledger is not None:
                ledger.note_compiled(
                    name, compiled, compile_s=time.monotonic() - t0
                )
            return compiled

        pairs = [
            ("", self._prefill, self._decode, self._verify, self.params)
        ]
        if self.quant_mode is not None:
            pairs.append(
                ("_q", self._prefill_q, self._decode_q, self._verify_q,
                 self._qparams)
            )
        programs = 0
        row_shape = None
        for suffix, prefill, decode, verify, prm in pairs:
            _compile(
                f"decode{suffix}",
                decode.lower(prm, self._cache, *self._decode_warm_args()),
            )
            programs += 1
            if verify is not None:
                _compile(
                    f"verify{suffix}",
                    verify.lower(
                        prm, self._cache, jnp.asarray(self._page_table),
                        self._tok,
                        jnp.zeros(
                            (self.max_slots, self.spec_draft), jnp.int32
                        ),
                        self._lengths, self._pads, self._remaining,
                        self._live, self._eos,
                    ),
                )
                programs += 1
            for w in self.buckets:
                # Contiguous rows admit on the PADDED width, so buckets
                # the budget can never fit are dead signatures; paged
                # capacity is the real length — every bucket can host a
                # short-enough prompt.
                if not self.paged and w + max_new_tokens > self.n_ctx:
                    continue
                chunk = normalize_prefill_chunk(self.prefill_chunk, w)
                pf_args = (
                    prm,
                    jnp.zeros((1, w), jnp.int32),
                    prompt_lens_to_pad_lens([w], 1, w),
                )
                _compile(
                    f"prefill{suffix}@{w}",
                    prefill.lower(*pf_args, chunk=chunk),
                )
                programs += 1
                row_shape = jax.eval_shape(
                    functools.partial(prefill, chunk=chunk), *pf_args
                )[1]
        if row_shape is not None:
            # The insert signature (abstract row cache from eval_shape —
            # no prefill ever executes). The decode-committed second
            # signature only diverges under sharded params; the engine's
            # own warmup() covers it at server start.
            _compile(
                "insert",
                self._insert.lower(
                    self._cache, row_shape, *self._insert_warm_args()
                ),
            )
            programs += 1
        return programs

    def collect_program_ledger(
        self, max_new_tokens: int = 128, path: str | None = None
    ):
        """The engine's device ledger (ISSUE 15): AOT-compile every
        signature through :meth:`aot_lower` with a recording ledger,
        run the static HBM budget check, and persist ``programs.json``
        (default: beside the recorder's event fragments). With the
        persistent compile cache enabled the recompiles are cache
        loads. The AOT path never touches the jit dispatch cache, so
        ``compile_stats()`` is identical before and after — pinned by
        tests/test_serve.py. Returns the ledger."""
        ledger = _device.ProgramLedger(source="serve")
        self.aot_lower(max_new_tokens=max_new_tokens, ledger=ledger)
        ledger.budget_check()
        ledger.write(path)
        return ledger


def serve_forever(
    engine: ServeEngine,
    *,
    idle_sleep_s: float = 0.005,
    max_s: float | None = None,
    should_stop=None,
) -> None:
    """Long-lived serving loop reusing the gang machinery: heartbeat
    stamps every iteration (the supervisor's stall detector works on a
    serving gang exactly as on a training gang), the live ``/metrics`` +
    ``/status`` exporter starts when ``TPUFLOW_OBS_HTTP_PORT`` is set
    (export start also stamps this replica into
    ``TPUFLOW_FLEET_REGISTRATION_DIR`` when configured, so a fleet
    observatory discovers it — ISSUE 14), and a SIGTERM preemption
    drains — stops admitting, finishes the live slots, exits — instead
    of killing requests mid-decode.

    With ``TPUFLOW_ROUTER_GATEWAY`` armed (the default) the loop also
    starts a ``ReplicaGateway`` — the replica-side ``/generate``
    endpoint the front-door router forwards to — sharing the step
    loop's lock (submit and step interleave safely) and advertising its
    URL as ``generate_url`` in this process's ``/status`` snapshot, so
    the fleet row the router picks carries a forwardable address.

    ``max_s`` bounds the loop (tests / bounded jobs); ``should_stop`` is
    an optional callable polled each iteration.
    """
    from tpuflow.utils import heartbeat, preempt

    obs.maybe_start_export()
    step_lock = threading.RLock()
    gateway = None
    if knobs.get_bool("TPUFLOW_ROUTER_GATEWAY"):
        # Production ingress (ISSUE 17): without this, every fleet row
        # is status-only and the router's http_forward has nothing to
        # POST to. Ephemeral port — the URL travels via /status, no
        # static port to collide on. Bind host follows the /status
        # exporter's knob so both endpoints share reachability.
        from tpuflow.infer.frontdoor import ReplicaGateway

        gw_host = knobs.raw("TPUFLOW_OBS_HTTP_HOST", "127.0.0.1")
        try:
            gateway = ReplicaGateway(
                engine, lock=step_lock, host=gw_host
            )
        except OSError as e:
            print(
                f"[tpuflow] replica gateway failed to bind on "
                f"{gw_host} ({e}); serving status-only"
            )
        else:
            url = gateway.url
            if gw_host == "0.0.0.0":  # noqa: S104 (operator knob)
                import socket as _socket
                from urllib.parse import urlsplit

                port = urlsplit(url).port
                url = (
                    f"http://{_socket.gethostname()}:{port}/generate"
                )
            obs.goodput_live().note_serve_generate_url(url)
    if obs.recorder() is not None and knobs.get_bool(
        "TPUFLOW_DEVICE_LEDGER"
    ):
        # Device observatory (ISSUE 15): the full per-program
        # cost/memory ledger at server start — with the persistent
        # compile cache warm (warmup() just enabled it) the AOT
        # recompiles are cache loads, and an operator sees every
        # program's HBM footprint (plus the static budget verdict)
        # BEFORE traffic arrives.
        try:
            engine.collect_program_ledger()
        except Exception as e:
            print(
                f"[tpuflow] device program ledger failed (ignored): {e!r}"
            )
    preempt.install_sigterm_handler()
    deadline = None if max_s is None else time.monotonic() + max_s
    draining = False
    try:
        while True:
            if preempt.preemption_requested() and not draining:
                # Drain hook (ISSUE 17): flip the exported flag the same
                # iteration admissions stop, so the front-door router
                # sees ``serve_draining`` on the next /status poll and
                # re-routes queued work instead of waiting for
                # staleness to prove a death that is actually a drain.
                draining = True
                obs.goodput_live().note_serve_draining(True)
                if gateway is not None:
                    # New /generate requests 503 "draining" at once —
                    # the router re-dispatches instead of queueing work
                    # on a replica that will never admit it.
                    gateway.draining = True
            with step_lock:
                did = engine.step(admit=not draining)
            heartbeat.beat(step=engine._iters)
            if draining and not engine._live.any():
                # Queued requests ride the requeue; their traces reach
                # the drained terminal so no submitted request vanishes
                # from the access log (ISSUE 13).
                with step_lock:
                    engine.drain_queued()
                return
            if should_stop is not None and should_stop():
                return
            if deadline is not None and time.monotonic() > deadline:
                return
            if not did:
                if draining:
                    with step_lock:
                        engine.drain_queued()
                    return
                with engine.ledger.bucket("idle"):
                    time.sleep(idle_sleep_s)
    finally:
        if gateway is not None:
            # Retract the advertised URL before the socket dies so a
            # fleet poll racing the shutdown never hands the router an
            # address that can only ever refuse.
            obs.goodput_live().note_serve_generate_url(None)
            gateway.close()
        # Run registry (ISSUE 16): whatever ended the loop — drain,
        # stop callable, deadline, or an exception on its way out —
        # this replica's headline (requests, TTFT/ITL percentiles from
        # the mergeable buckets, SLO count) lands in the cross-run
        # registry when TPUFLOW_REGISTRY_PATH is armed. One knob read
        # when it is not; never masks the in-flight exception.
        from tpuflow.obs import registry as registry_mod

        registry_mod.maybe_append_live("serve")
