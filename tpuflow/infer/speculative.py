"""Prompt-lookup speculative decoding: draft from the context, verify in
one forward — token-exact greedy decoding at a fraction of the steps.

No draft model: candidate continuations come from the sequence itself —
the trailing (ngram-1)-gram is matched against the prompt + generated
text and the tokens following its most recent occurrence become the
draft, LADDERING down to shorter grams (ultimately a single token) when
the longer gram never recurs — byte-level and natural-language corpora
repeat short grams constantly even when long ones don't.
Each iteration then runs ONE cached forward over the draft_len+1 chunk
(multi-token warm-cache attention is exact: Block._cached_attention's
masked full-cache path), accepts the longest prefix on which the model's
own argmax agrees, keeps the model's token at the first disagreement
(the standard "bonus" token — so every iteration commits >= 1 token and
exactness is unconditional), rewinds the shared cache index past the
rejected tail (stale cache entries beyond the index are masked out of
attention until overwritten), and repeats inside one jitted
``lax.while_loop``.

Batching: rows draft independently; the batch advances by the MINIMUM
acceptance across live rows (the cache index is shared), so speedup is
the batch's worst-case agreement — batch 1 gets the full win. Greedy
only (sampling would need stochastic acceptance-rejection); dense
prompts only.

The serving engine (tpuflow.infer.serve, ISSUE 11) lifts the
batch-minimum restriction: its paged KV cache gives every slot an
independent frontier, so the batched decode block verifies each slot's
host-drafted tokens (``ngram_draft`` below — the numpy twin of the
in-program ladder) and commits PER ROW. The acceptance comparison is
width-safe by the same two pins this module documents
(``decode_precision='highest'`` + integer-exact int8), so speculation
composes with continuous batching instead of being solo-only.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpuflow.infer.generate import (
    after_first_true,
    check_cache_capacity,
    chunked_prefill,
    normalize_prefill_chunk,
)


def _reset_index(cache, value):
    """Set every cache/pos index leaf to ``value`` (the rewind). Index
    leaves are the integer counters named ``cache_index``/``pos_index``
    (scalar, or (n_layer,) under scan_layers)."""
    flat = jax.tree_util.tree_flatten_with_path(cache)
    out = []
    for path, leaf in flat[0]:
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        if any(str(n).endswith("_index") for n in names):
            out.append(jnp.broadcast_to(value.astype(leaf.dtype), leaf.shape))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(flat[1], out)


def ngram_draft(history, K: int, *, ngram: int = 3):
    """Host-side (numpy) twin of ``_draft_ladder`` for ONE sequence: the
    K tokens that followed the most recent earlier occurrence of the
    trailing (ngram-1)-gram, laddering down to shorter grams, falling
    back to repeat-last-token. The serving engine drafts per slot with
    this between decode blocks (tpuflow.infer.serve: each request's
    history lives on the host anyway, and a wrong draft only costs
    speed — the in-program verify forward arbitrates), so the drafting
    policy stays one implementation away from the solo ladder above.
    Returns a (K,) int32 draft; ``history`` must be non-empty."""
    import numpy as np

    h = np.asarray(history, np.int32).reshape(-1)
    n = h.size
    if n == 0:
        raise ValueError("ngram_draft needs a non-empty history")
    G = max(int(ngram) - 1, 1)
    for g in range(min(G, n - 1), 0, -1):
        key = h[n - g:]
        # Windows over h[:n-1]: starts 0..n-g-1, so the trailing gram
        # itself (start n-g) is never its own match.
        win = np.lib.stride_tricks.sliding_window_view(h[: n - 1], g)
        hits = np.nonzero((win == key).all(axis=1))[0]
        if hits.size:
            s = int(hits[-1])
            cand = h[s + g : s + g + K]
            if cand.size < K:
                cand = np.concatenate(
                    [cand, np.full(K - cand.size, h[-1], np.int32)]
                )
            return cand.astype(np.int32)
    return np.full(K, h[-1], np.int32)


def _draft_ladder(hist, n_hist, *, K: int, G: int):
    """Per-row prompt lookup with an n-gram LADDER: the K tokens that
    followed the most recent earlier occurrence of the trailing G-gram;
    when that gram never recurs, retry with shorter and shorter grams
    down to 1 (natural text rarely repeats long grams but constantly
    repeats short ones — the ladder keeps acceptance above the
    repeat-last-token floor). Wrong drafts only cost speed, never
    correctness: the verify forward arbitrates. ``hist``: (B, W) history
    buffers; ``n_hist`` = tokens valid in hist (prompt + committed +
    cur)."""
    W = hist.shape[1]
    # Window origins extend to -(G-1): a g-gram (g < G) only needs the
    # LAST g columns of its window in range, so matches ending in the
    # first G-g history positions live at negative origins. The old
    # pos = arange(W) never visited them — short-gram matches at the
    # start of the prompt were invisible to the ladder (the
    # first-positions blind spot).
    pos = jnp.arange(-(G - 1), W)

    def row(h):
        # One fused scan over the history computes, for EVERY gram
        # length g <= G at once, whether each window position matches
        # the trailing g-gram (suffix-aligned comparisons share the
        # same equality matrix).
        tail = jax.vmap(
            lambda o: jax.lax.dynamic_index_in_dim(h, o, keepdims=False)
        )(n_hist - G + jnp.arange(G))
        idx = pos[:, None] + jnp.arange(G)[None, :]
        # Negative idx clips to 0 — garbage columns, but only in the
        # first G-g slots a g-gram never reads (see the per-g origin
        # bound below).
        windows = h[jnp.clip(idx, 0, W - 1)]
        eq = windows == tail[None, :]  # (W+G-1, G)
        # suffix_ok[i, g-1] = window at origin pos[i] matches the tail
        # on its LAST g entries (i.e. a g-gram match ending at pos[i]+G).
        suffix_ok = jnp.cumprod(eq[:, ::-1], axis=1).astype(bool)
        in_range = (pos + G < n_hist) & (pos + G + K <= W)
        start = jnp.int32(0)
        found_any = jnp.bool_(False)
        # Ladder from the longest gram down: take the first length with
        # any match (static unroll over G <= ngram-1 lengths). Sentinel
        # -G-1 sits below every legal origin (>= -(G-1)), so "no match"
        # stays distinguishable now that origins go negative.
        for g in range(G, 0, -1):
            ok_g = suffix_ok[:, g - 1] & in_range & (pos + G - g >= 0)
            m_g = jnp.where(ok_g, pos, -G - 1).max()
            found_g = m_g > -G
            take = found_g & ~found_any
            start = jnp.where(take, m_g + G, start)
            found_any = found_any | found_g
        cand = jax.lax.dynamic_slice(h, (start,), (K,))
        # Ladder exhausted (token never seen before): repeat the last
        # token (often right for byte-level runs).
        last = jax.lax.dynamic_index_in_dim(h, n_hist - 1, keepdims=False)
        return jnp.where(found_any, cand, jnp.full((K,), last))

    return jax.vmap(row)(hist)


@functools.partial(
    jax.jit,
    static_argnums=(0,),
    static_argnames=("max_new_tokens", "draft_len", "ngram", "eos_id",
                     "pad_id", "with_stats", "prefill_chunk"),
)
def _spec_jit(
    model,
    params,
    prompt,
    *,
    max_new_tokens: int,
    draft_len: int,
    ngram: int,
    eos_id: int | None,
    pad_id: int,
    with_stats: bool = False,
    prefill_chunk: int | None = None,
):
    B, T = prompt.shape
    K = draft_len
    G = ngram - 1  # match key length
    L = max_new_tokens + K + 1  # output slack for the last overshoot write
    W = T + L  # full history width (drafting searches this)

    # Prefill the prompt (one shot, or chunked for long prompts — same
    # memory trade as generate's knob), sample the first token (greedy).
    logits, cache = chunked_prefill(model, params, prompt, prefill_chunk)
    cur = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

    # One buffer serves both drafting (full history) and output (the
    # slice past the prompt) — committed tokens are written once. cur
    # lands at column T NOW so the very first draft's match key ends in
    # the real sampled token, not a pad (the body re-writes it, which is
    # idempotent).
    hist = jnp.concatenate(
        [prompt, jnp.full((B, L), pad_id, jnp.int32)], axis=1
    )
    hist = jax.lax.dynamic_update_slice(hist, cur[:, None], (0, T))
    done0 = (cur == eos_id) if eos_id is not None else jnp.zeros((B,), bool)

    def draft(hist, n_hist):
        return _draft_ladder(hist, n_hist, K=K, G=G)

    def cond(state):
        n_out, _, _, _, done, _ = state
        return (n_out < max_new_tokens) & ~jnp.all(done)

    def body(state):
        n_out, hist, cur, cache, done, n_fwd = state
        # hist holds prompt + all committed tokens + cur at n_hist-1.
        n_hist = T + n_out + 1
        d = draft(hist, n_hist)  # (B, K)
        x = jnp.concatenate([cur[:, None], d], axis=1)  # (B, K+1)
        logits, vars_out = model.apply(
            {"params": params, "cache": cache},
            x,
            decode=True,
            mutable=["cache"],
        )
        cache = vars_out["cache"]
        am = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (B, K+1)
        # am[:, j] = model's token after (cur, d_0..d_{j-1}); acceptance =
        # leading agreement with the draft.
        match = am[:, :K] == d
        a_row = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        a_row = jnp.where(done, K, a_row)  # frozen rows never constrain
        a = jnp.min(a_row)  # shared cache index → batch-uniform advance

        # Committed window (K+1 wide, a+1 valid): accepted draft prefix,
        # then the model's token at the disagreement, then junk the next
        # iteration overwrites.
        j = jnp.arange(K + 1)
        window = jnp.where(
            j[None, :] < a, jnp.pad(d, ((0, 0), (0, 1))), am[
                jnp.arange(B)[:, None], jnp.minimum(j[None, :], a)
            ]
        )
        # eos freeze inside the window + already-done rows emit pad.
        if eos_id is not None:
            is_eos = (window == eos_id) & (j[None, :] <= a)
            window = jnp.where(
                after_first_true(is_eos) | done[:, None], pad_id, window
            )
            done = done | jnp.any(is_eos & ~done[:, None], axis=1)
        else:
            window = jnp.where(done[:, None], pad_id, window)

        # cur itself is committed NOW (it was only sampled before).
        hist = jax.lax.dynamic_update_slice(hist, cur[:, None], (0, T + n_out))
        hist = jax.lax.dynamic_update_slice(hist, window, (0, T + n_out + 1))

        new_cur = window[jnp.arange(B), a]
        # Keys for cur, d_0..d_{a-1} (cache positions T+n_out..T+n_out+a)
        # are valid; rewind the shared index past the rejected tail. The
        # cache index is always T + committed-count — derived, not carried,
        # so the rewind can't desynchronize from the output count.
        cache = _reset_index(cache, jnp.int32(T) + n_out + a + 1)
        return n_out + a + 1, hist, new_cur, cache, done, n_fwd + 1

    init = (jnp.int32(0), hist, cur, cache, done0, jnp.int32(0))
    n_out, hist, cur, cache, done, n_fwd = jax.lax.while_loop(cond, body, init)
    # If the loop never ran (or exited right at the budget), the pending
    # cur was never committed — flush it raw (the eos re-freeze below pads
    # anything after a row's first eos; the eos itself is emitted).
    hist = jax.lax.dynamic_update_slice(
        hist, cur[:, None], (0, T + jnp.minimum(n_out, L - 1))
    )
    # Output = the history past the prompt; trim overshoot and re-freeze
    # anything past each row's first eos (the uniform advance can
    # overshoot a row's budgeted region).
    out = hist[:, T:T + max_new_tokens]
    if eos_id is not None:
        out = jnp.where(after_first_true(out == eos_id), pad_id, out)
    if with_stats:
        # n_fwd counts verify forwards; committed tokens are clamped to
        # the budget — the final iteration can overshoot max_new_tokens
        # and the overshoot is trimmed from the output, so counting it
        # would overstate realized acceptance (tokens/forward: 1.0 means
        # speculation bought nothing, draft_len+1 is the max).
        return out, {
            "n_forwards": n_fwd,
            "n_committed": jnp.minimum(n_out, max_new_tokens),
        }
    return out


def speculative_generate(
    model,
    params,
    prompt,
    *,
    max_new_tokens: int,
    draft_len: int = 8,
    ngram: int = 3,
    eos_id: int | None = None,
    pad_id: int = 0,
    return_stats: bool = False,
    prefill_chunk: int | None = None,
):
    """Greedy decode via prompt-lookup speculation, committing up to
    ``draft_len + 1`` tokens per model forward when the context repeats.

    ``prefill_chunk``: stream the prompt into the cache in fixed slices
    (long-context memory bound, same semantics as ``generate``'s knob).
    For bitwise parity against plain greedy on a bf16-prefill model, use
    the SAME chunking on both paths — prefill widths round bf16 values
    identically only when they match.

    Token-exact vs ``generate(..., temperature=0)``: acceptance compares
    the model's argmax over a (K+1)-token warm-cache chunk against
    single-token decode. Two width-dependence sources are pinned off on
    the decode path: the compute dtype (``GPT2Config.decode_dtype``,
    f32 by default — bf16 rounding of layer outputs differs
    systematically between chunk widths) and the MXU matmul precision
    (``GPT2Config.decode_precision``, HIGHEST by default — TPU DEFAULT
    precision lowers even f32 matmuls to bf16 multiply passes whose
    rounding depends on the program's tiling, i.e. the chunk width; the
    r5 on-chip ``numerics_ok: false`` with CPU bit-exactness intact).
    Verified bit-exact across the CPU scenarios including a 128-token
    bf16 decode and pad-laden drafts (tests/test_speculative.py); the
    bench FAILS loudly (exit 3) on a fresh on-chip mismatch rather than
    recording a null speedup.

    Composes with fused-native int8 (``quantize_model(mode='mxu')``,
    ISSUE 9): the quantized matmuls are integer contractions with exact
    accumulation, so THEY are width-independent by construction — the
    (K+1)-chunk verify forward and single-token decode quantize each
    token's activations identically (per-row = per-token) and the int8
    dot cannot round differently across chunk widths. The remaining
    width-sensitive ops (LayerNorm, softmax, residual adds) stay under
    the same decode_dtype/decode_precision pins as the fp path.

    ``prompt``: dense (B, T) int32 (ragged batches: decode rows
    separately, or use ``generate``). ``ngram`` is the match-key length
    + 1 (3 = match on the trailing 2-gram). Returns (B, max_new_tokens);
    with ``return_stats=True`` returns ``(tokens, stats)`` where stats
    carries ``n_forwards`` (verify passes) and ``n_committed`` (tokens
    the loop emitted, >= max_new_tokens means budget reached) — realized
    acceptance is ``n_committed / n_forwards`` tokens per forward.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    B, T = prompt.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if draft_len < 1:
        raise ValueError(f"draft_len must be >= 1, got {draft_len}")
    if ngram < 2:
        raise ValueError(f"ngram must be >= 2, got {ngram}")
    if T < ngram - 1:
        raise ValueError(
            f"prompt length {T} is shorter than the {ngram - 1}-token "
            "match key; use generate() for such prompts"
        )
    # The uniform advance can run the cache up to draft_len+1 past the
    # budget before the loop notices — reserve that slack in n_ctx.
    check_cache_capacity(model, T, max_new_tokens + draft_len + 1)
    prefill_chunk = normalize_prefill_chunk(prefill_chunk, T)
    return _spec_jit(
        model,
        params,
        prompt,
        max_new_tokens=max_new_tokens,
        draft_len=draft_len,
        ngram=ngram,
        eos_id=eos_id,
        pad_id=pad_id,
        with_stats=return_stats,
        prefill_chunk=prefill_chunk,
    )
