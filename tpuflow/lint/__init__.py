"""Shared AST-lint infrastructure for ``tools/tpulint.py``.

Four passes ride one parsed-source cache (:class:`tpuflow.lint.core.Tree`):

1. ``tpuflow.lint.knob_pass``   — the TPUFLOW_* knob registry contract.
2. ``tpuflow.lint.jit_pass``    — jit-boundary audit (trace-time constant
   reads, host syncs, donation discipline).
3. ``tpuflow.lint.recompile_pass`` — the serving engine's never-recompile
   contract, cross-checked statically.
4. ``tpuflow.lint.obs_pass``    — the telemetry-name catalog lint
   (formerly all of ``tools/obs_lint.py``; that file is now a shim).

Each pass exposes ``run(tree, ...) -> list[Finding]`` and is
parameterized over its inputs (registry, catalog, file paths) so the
fixture tests in ``tests/test_tpulint.py`` can aim it at seeded-violation
snippets instead of the real tree.
"""

from tpuflow.lint.core import Finding, Tree  # noqa: F401
