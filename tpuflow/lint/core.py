"""Parsed-source cache, findings, and the pragma contract.

Every tpulint pass walks the same repository snapshot: :class:`Tree`
reads and ``ast.parse``\\ s each file once, and all passes share the
cache — the "shared AST walk" that lets obs_lint become pass 4 without
a second tree traversal.

Suppression: a finding is silenced by an inline pragma

    # tpulint: disable=<rule>[,<rule>] -- <justification>

on the offending line, or in the comment block immediately above the
offending statement. The justification text after ``--`` is REQUIRED:
a pragma without one is itself a finding (``pragma-justification``).
The lint exists to keep hand-maintained invariants honest; an
unexplained exemption is exactly the kind of silent drift it hunts.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

# What `tools/tpulint.py` scans by default, relative to the repo root.
# tests/ are walked too but individual rules scope themselves (e.g. the
# raw-env-read ban exempts tests, the undeclared-name rule does not —
# a typo'd monkeypatch.setenv would otherwise test nothing).
DEFAULT_SCAN = ("tpuflow", "tools", "flows", "bench.py", "tests")

_PRAGMA_RE = re.compile(
    r"#\s*tpulint:\s*disable=([a-z0-9_,\- ]+?)\s*(?:--\s*(.*\S))?\s*$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Tree:
    """One repository snapshot: file discovery + source/AST caches."""

    def __init__(self, root: str, scan: tuple[str, ...] = DEFAULT_SCAN):
        self.root = os.path.abspath(root)
        self.scan = scan
        self._files: list[str] | None = None
        self._src: dict[str, str] = {}
        self._ast: dict[str, ast.Module | None] = {}
        self._pragmas: dict[str, dict[int, tuple[set, bool, int]]] = {}
        self.parse_errors: list[Finding] = []

    # ------------------------------------------------------------ files
    def files(self) -> list[str]:
        """Repo-relative paths of every scanned ``.py`` file."""
        if self._files is not None:
            return self._files
        out = []
        for entry in self.scan:
            full = os.path.join(self.root, entry)
            if os.path.isfile(full):
                out.append(entry)
                continue
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        out.append(
                            os.path.relpath(
                                os.path.join(dirpath, fname), self.root
                            )
                        )
        self._files = sorted(set(out))
        return self._files

    def source(self, rel: str) -> str:
        if rel not in self._src:
            with open(os.path.join(self.root, rel)) as f:
                self._src[rel] = f.read()
        return self._src[rel]

    def tree(self, rel: str) -> ast.Module | None:
        """Parsed module, or None (with a recorded finding) on a syntax
        error — a file the passes can't see must not pass silently."""
        if rel not in self._ast:
            try:
                self._ast[rel] = ast.parse(self.source(rel))
            except SyntaxError as e:
                self._ast[rel] = None
                self.parse_errors.append(
                    Finding("syntax-error", rel, e.lineno or 0, str(e.msg))
                )
        return self._ast[rel]

    # ---------------------------------------------------------- pragmas
    def _pragma_map(self, rel: str) -> dict[int, tuple[set, bool, int]]:
        """line -> (rules, justified, pragma_line). A pragma covers its
        own line; a comment-line pragma also covers the comment block it
        opens and the first code line after it."""
        if rel in self._pragmas:
            return self._pragmas[rel]
        mapping: dict[int, tuple[set, bool, int]] = {}
        try:
            lines = self.source(rel).split("\n")
        except OSError:
            # Synthetic finding paths ("tpuflow", a missing README) have
            # no source to carry pragmas.
            self._pragmas[rel] = mapping
            return mapping
        i = 0
        while i < len(lines):
            m = _PRAGMA_RE.search(lines[i])
            if not m:
                i += 1
                continue
            rules = {
                r.strip() for r in m.group(1).split(",") if r.strip()
            }
            justified = bool(m.group(2))
            entry = (rules, justified, i + 1)
            mapping[i + 1] = entry
            if lines[i].lstrip().startswith("#"):
                # Comment-block pragma: extend through the rest of the
                # block onto the first code line.
                j = i + 1
                while j < len(lines) and (
                    not lines[j].strip()
                    or lines[j].lstrip().startswith("#")
                ):
                    mapping[j + 1] = entry
                    j += 1
                if j < len(lines):
                    mapping[j + 1] = entry
            i += 1
        self._pragmas[rel] = mapping
        return mapping

    def suppression(self, rel: str, line: int, rule: str):
        """(suppressed, pragma_finding_or_None) for a finding at
        rel:line of ``rule``."""
        entry = self._pragma_map(rel).get(line)
        if entry is None:
            return False, None
        rules, justified, pragma_line = entry
        if rule not in rules:
            return False, None
        if not justified:
            return True, Finding(
                "pragma-justification", rel, pragma_line,
                f"pragma disables {rule!r} without a justification — "
                "append `-- <why this finding is safe to silence>`",
            )
        return True, None


class Sink:
    """Finding collector that applies the pragma contract once."""

    def __init__(self, tree: Tree):
        self.tree = tree
        self.findings: list[Finding] = []
        self._pragma_findings: dict[tuple, Finding] = {}

    def emit(self, rel: str, line: int, rule: str, message: str) -> None:
        suppressed, pragma_finding = self.tree.suppression(rel, line, rule)
        if pragma_finding is not None:
            key = (pragma_finding.path, pragma_finding.line)
            self._pragma_findings[key] = pragma_finding
        if not suppressed:
            self.findings.append(Finding(rule, rel, line, message))

    def result(self) -> list[Finding]:
        return sorted(
            self.findings + list(self._pragma_findings.values()),
            key=lambda f: (f.path, f.line, f.rule),
        )


# ------------------------------------------------------------- helpers
def dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
