"""Pass 2 — jit-boundary audit.

For every ``jax.jit`` site whose traced callable is resolvable in the
same module (a named def, a bound method, a ``functools.partial`` over
one, a lambda, or a decorated def), the traced BODY is audited for
host-world leaks, and the call's donation contract is checked — the
static form of the PR-4 donation audit comments.

Rules (best-effort by design: the walk covers the direct body of the
traced function, not its transitive callees — the seeded-fixture tests
pin exactly what fires):

- ``jit-env-read``  — ``os.environ`` / knob-accessor reads inside a
  traced body: the value is baked into the compiled program at trace
  time and silently ignored forever after (``decode_precision``-pinning
  taught us these must live OUTSIDE the trace).
- ``jit-time``      — ``time.*()`` calls inside a traced body: a
  trace-time constant masquerading as a clock.
- ``jit-host-rng``  — host RNG (``random.*`` / ``np.random.*``) inside
  a traced body: baked entropy; use ``jax.random`` with a threaded key.
- ``jit-host-sync`` — ``.tolist()`` / ``.item()`` /
  ``block_until_ready`` / ``jax.device_get`` / ``float()`` / ``int()``
  applied to a traced-function parameter inside the body: a host sync
  (or a ConcretizationError at trace time) on what must remain a
  device-side value.
- ``jit-donate-nonstate`` — a donated argument whose parameter name
  does not look like step/engine state (``state`` / ``cache`` /
  ``params`` / ``carry`` / ``window`` / ``buf``): the PR-4 discipline
  is that ONLY the state the step replaces is donated — batches and
  resharders must stay undonated.
- ``jit-donate-reuse`` — a call site of a known jitted program that
  reads a donated operand again after the call without rebinding it:
  the donated buffer is dead (``is_deleted()``) the moment the call
  dispatches.
"""

from __future__ import annotations

import ast
import re

from tpuflow.lint.core import Sink, Tree, dotted

_STATE_RE = re.compile(r"(state|cache|carry|param|window|buf)", re.I)

_TIME_FNS = {
    "time", "monotonic", "perf_counter", "time_ns", "process_time",
    "monotonic_ns", "perf_counter_ns",
}
_SYNC_ATTRS = {"tolist", "item", "block_until_ready"}


def _is_jit_func(node: ast.AST) -> bool:
    """node is `jax.jit` or bare `jit`."""
    d = dotted(node)
    return d in ("jax.jit", "jit")


def _partial_of_jit(call: ast.Call):
    """For `functools.partial(jax.jit, **kw)` returns the call, else
    None."""
    if (
        isinstance(call, ast.Call)
        and dotted(call.func) in ("functools.partial", "partial")
        and call.args
        and _is_jit_func(call.args[0])
    ):
        return call
    return None


def _donate_positions(call: ast.Call) -> tuple[int, ...]:
    """Literal donate_argnums positions (IfExp takes the enabled
    branch; unparseable forms -> empty)."""
    for kw in call.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        value = kw.value
        if isinstance(value, ast.IfExp):
            value = value.body
        if isinstance(value, ast.Tuple):
            out = []
            for elt in value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, int
                ):
                    out.append(elt.value)
            return tuple(out)
        if isinstance(value, ast.Constant) and isinstance(
            value.value, int
        ):
            return (value.value,)
    return ()


class _Module:
    """Per-module def index + parent links."""

    def __init__(self, mod: ast.Module):
        self.mod = mod
        self.defs: dict[str, ast.FunctionDef] = {}
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(mod):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                self.defs.setdefault(node.name, node)

    def enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            cur = self.parents.get(cur)
        return cur

    def resolve(self, node: ast.AST):
        """(body_node, param_names, bound_positionals) for the traced
        callable, or None. `self` is dropped for methods."""
        bound = 0
        while True:
            if isinstance(node, ast.Lambda):
                params = [a.arg for a in node.args.args]
                return node, params[bound:], bound
            if isinstance(node, ast.Call) and dotted(node.func) in (
                "functools.partial", "partial"
            ):
                bound += len(node.args) - 1
                node = node.args[0]
                continue
            name = None
            if isinstance(node, ast.Name):
                name = node.id
            elif isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name
            ) and node.value.id == "self":
                name = node.attr
            if name is None:
                return None
            fn = self.defs.get(name)
            if fn is None:
                return None
            params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
            if params and params[0] in ("self", "cls"):
                params = params[1:]
            return fn, params[bound:], bound


def _audit_body(sink: Sink, rel: str, body: ast.AST, params: list[str]):
    param_set = set(params)
    for node in ast.walk(body):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func) or ""
        if d.endswith("environ.get") or d.endswith("os.getenv") or (
            d.startswith("knobs.")
        ) or d == "getenv":
            sink.emit(
                rel, node.lineno, "jit-env-read",
                f"{d}(...) inside a traced body is a trace-time "
                "constant — resolve the knob outside the jit and pass "
                "the value in",
            )
        elif d.startswith("time.") and d.split(".", 1)[1] in _TIME_FNS:
            sink.emit(
                rel, node.lineno, "jit-time",
                f"{d}() inside a traced body bakes the trace-time "
                "clock into the compiled program",
            )
        elif d.startswith(("random.", "np.random.", "numpy.random.")):
            sink.emit(
                rel, node.lineno, "jit-host-rng",
                f"{d}() inside a traced body bakes host entropy at "
                "trace time — use jax.random with a threaded key",
            )
        elif d in ("jax.device_get",):
            sink.emit(
                rel, node.lineno, "jit-host-sync",
                f"{d}() inside a traced body forces a host sync",
            )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SYNC_ATTRS
        ):
            sink.emit(
                rel, node.lineno, "jit-host-sync",
                f".{node.func.attr}() inside a traced body forces a "
                "host sync (or fails at trace time)",
            )
        elif (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int", "bool")
            and node.args
            and isinstance(node.args[0], ast.Name)
            and node.args[0].id in param_set
        ):
            sink.emit(
                rel, node.lineno, "jit-host-sync",
                f"{node.func.id}() of traced parameter "
                f"{node.args[0].id!r} concretizes a device value "
                "inside the traced body",
            )


def _target_string(node: ast.AST) -> str | None:
    """A stable key for the binding target / call head ('step',
    'self._decode')."""
    if isinstance(node, ast.Name):
        return node.id
    return dotted(node)


def _access_events(fn: ast.AST, key: str):
    """(lineno, is_store) events for reads/writes of `key` inside fn."""
    events = []
    for node in ast.walk(fn):
        k = None
        if isinstance(node, ast.Name):
            k = node.id
        elif isinstance(node, ast.Attribute):
            k = dotted(node)
        if k != key:
            continue
        is_store = isinstance(
            getattr(node, "ctx", None), (ast.Store, ast.Del)
        )
        events.append((node.lineno, is_store))
    return events


def run(tree: Tree):
    sink = Sink(tree)
    for rel in tree.files():
        norm = rel.replace("\\", "/")
        if norm.startswith("tests/"):
            continue
        mod = tree.tree(rel)
        if mod is None:
            continue
        index = _Module(mod)
        # binding key -> donate positions (for the reuse rule)
        bindings: dict[str, tuple[int, ...]] = {}

        for node in ast.walk(mod):
            # ---- decorated defs --------------------------------------
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    donate = ()
                    is_jit = False
                    if _is_jit_func(dec):
                        is_jit = True
                    elif isinstance(dec, ast.Call) and _is_jit_func(
                        dec.func
                    ):
                        is_jit = True
                        donate = _donate_positions(dec)
                    elif isinstance(dec, ast.Call) and _partial_of_jit(
                        dec
                    ):
                        is_jit = True
                        donate = _donate_positions(dec)
                    if not is_jit:
                        continue
                    params = [
                        a.arg
                        for a in node.args.posonlyargs + node.args.args
                    ]
                    if params and params[0] in ("self", "cls"):
                        params = params[1:]
                    _audit_body(sink, rel, node, params)
                    for p in donate:
                        if p < len(params) and not _STATE_RE.search(
                            params[p]
                        ):
                            sink.emit(
                                rel, node.lineno, "jit-donate-nonstate",
                                f"donated arg {p} ({params[p]!r}) of "
                                f"jitted {node.name!r} is not "
                                "step/engine state — only the state "
                                "the program replaces may be donated",
                            )
                    bindings[node.name] = donate
            # ---- jit(...) call sites ---------------------------------
            if not (
                isinstance(node, ast.Call) and _is_jit_func(node.func)
            ):
                continue
            if not node.args:
                continue
            donate = _donate_positions(node)
            resolved = index.resolve(node.args[0])
            if resolved is not None:
                body, params, _bound = resolved
                _audit_body(sink, rel, body, params)
                for p in donate:
                    if p < len(params) and not _STATE_RE.search(
                        params[p]
                    ):
                        sink.emit(
                            rel, node.lineno, "jit-donate-nonstate",
                            f"donated arg {p} ({params[p]!r}) is not "
                            "step/engine state — only the state the "
                            "program replaces may be donated",
                        )
            # record the binding for reuse analysis
            parent = index.parents.get(node)
            if isinstance(parent, ast.Assign) and donate:
                for target in parent.targets:
                    key = _target_string(target)
                    if key:
                        bindings[key] = donate

        # ---- donated-operand reuse at call sites --------------------
        for node in ast.walk(mod):
            if not isinstance(node, ast.Call):
                continue
            key = _target_string(node.func)
            donate = bindings.get(key or "")
            if not donate:
                continue
            fn = index.enclosing_function(node)
            if fn is None:
                continue
            # positions past a *unpack are not statically addressable
            plain = len(node.args)
            for i, a in enumerate(node.args):
                if isinstance(a, ast.Starred):
                    plain = i
                    break
            end = getattr(node, "end_lineno", node.lineno)
            for p in donate:
                if p >= plain:
                    continue
                src = _target_string(node.args[p])
                if src is None:
                    continue
                events = _access_events(fn, src)
                stores = sorted(
                    ln for ln, st in events if st and ln >= node.lineno
                )
                loads = sorted(
                    ln for ln, st in events if not st and ln > end
                )
                for ln in loads:
                    if not any(s <= ln for s in stores):
                        sink.emit(
                            rel, ln, "jit-donate-reuse",
                            f"{src!r} was donated to {key!r} at line "
                            f"{node.lineno} and is read again here "
                            "without being rebound — the donated "
                            "buffer is deleted at dispatch",
                        )
                        break
    return sink.result()
