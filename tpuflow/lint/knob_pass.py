"""Pass 1 — the TPUFLOW_* knob-registry contract.

Rules:

- ``knob-raw-env``      — a raw ``os.environ`` read (``.get``, subscript
  load, ``in`` membership, ``os.getenv``) of a ``TPUFLOW_*`` name
  anywhere outside ``tpuflow/utils/knobs.py``. Every knob read goes
  through the registry's typed accessors; a raw read bypasses the
  declaration check that makes typos die loudly. tests/ are exempt
  (chaos-test gang snippets exercise the raw plumbing deliberately —
  their literals are still covered by ``knob-undeclared``).
- ``knob-dynamic``      — an env read or knob accessor whose name
  argument is not a string literal: invisible to every static rule
  here. Needs a pragma with a justification where genuinely necessary
  (e.g. a helper forwarding a literal from its call sites).
- ``knob-undeclared``   — any exact ``TPUFLOW_*`` string literal (reads,
  writes, ``monkeypatch.setenv``, manifest env lists) naming a knob the
  registry does not declare. This is where a
  ``TPUFLOW_SERVE_PAGED``-style typo dies at lint time instead of
  silently defaulting.
- ``knob-readme-stale`` — the README's generated knob-table region is
  missing or does not match ``python -m tpuflow.utils.knobs
  --markdown`` byte-for-byte (every registry entry is documented in a
  README knob table, by construction of the generated region).
- ``knob-readme-unknown`` — the README mentions a ``TPUFLOW_*`` name the
  registry does not declare (prose drifting from code).
"""

from __future__ import annotations

import re

import ast

from tpuflow.lint.core import Sink, Tree, const_str, dotted

# The registry module itself, repo-relative: the one place raw reads live.
REGISTRY_FILE = "tpuflow/utils/knobs.py"

ACCESSORS = (
    "raw", "is_set", "get_str", "get_int", "get_float", "get_bool",
    "get_int_lenient", "get_float_lenient",
)

_NAME_RE = re.compile(r"^TPUFLOW_[A-Z0-9_]+$")
_README_TOKEN_RE = re.compile(r"TPUFLOW_[A-Z0-9_]+")


def _declared_names(registry=None) -> frozenset[str]:
    if registry is not None:
        return frozenset(registry)
    from tpuflow.utils.knobs import REGISTRY

    return frozenset(REGISTRY)


def _knob_literal(value: str) -> str | None:
    """Normalized declared-name candidate for an exact TPUFLOW_* string
    literal; None for non-knob strings. Trailing underscores are
    stripped so prefix literals (``"TPUFLOW_SERVE_"``) resolve to their
    base knob; the bare ``TPUFLOW_`` prefix is not a name."""
    if not _NAME_RE.match(value):
        return None
    name = value.rstrip("_")
    if name in ("TPUFLOW",):
        return None
    return name


def _is_environ(node: ast.AST) -> bool:
    d = dotted(node)
    return d is not None and (d == "environ" or d.endswith(".environ"))


def run(
    tree: Tree,
    registry=None,
    readme_rel: str | None = "README.md",
    check_readme: bool = True,
):
    declared = _declared_names(registry)
    sink = Sink(tree)

    for rel in tree.files():
        mod = tree.tree(rel)
        if mod is None:
            continue
        in_registry = rel.replace("\\", "/") == REGISTRY_FILE
        in_tests = rel.replace("\\", "/").startswith("tests/")
        in_tpuflow = rel.replace("\\", "/").startswith("tpuflow/")
        for node in ast.walk(mod):
            # ---- raw env reads -------------------------------------
            if isinstance(node, ast.Call):
                d = dotted(node.func) or ""
                is_env_get = (
                    d.endswith("environ.get") or d.endswith("os.getenv")
                    or d == "getenv"
                )
                if is_env_get and node.args:
                    name = const_str(node.args[0])
                    if name is None:
                        if in_tpuflow and not in_registry:
                            sink.emit(
                                rel, node.lineno, "knob-dynamic",
                                f"env read {d}(<non-literal>) — a "
                                "dynamic name is invisible to the "
                                "registry rules; read through "
                                "tpuflow.utils.knobs with a literal "
                                "name",
                            )
                    elif (
                        name.startswith("TPUFLOW_")
                        and not in_registry
                        and not in_tests
                    ):
                        sink.emit(
                            rel, node.lineno, "knob-raw-env",
                            f"raw env read of {name!r} bypasses the "
                            "knob registry — use tpuflow.utils.knobs "
                            "accessors",
                        )
                # ---- knob accessor calls ---------------------------
                if (
                    d.startswith("knobs.")
                    and d.split(".", 1)[1] in ACCESSORS
                    and node.args
                    and not in_registry
                    and not in_tests
                ):
                    name = const_str(node.args[0])
                    if name is None:
                        sink.emit(
                            rel, node.lineno, "knob-dynamic",
                            f"{d}(<non-literal>) — accessor names must "
                            "be string literals so the declared-name "
                            "rule can check them statically",
                        )
            # ---- environ subscript reads ---------------------------
            if (
                isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and _is_environ(node.value)
            ):
                name = const_str(node.slice)
                if (
                    name
                    and name.startswith("TPUFLOW_")
                    and not in_registry
                    and not in_tests
                ):
                    sink.emit(
                        rel, node.lineno, "knob-raw-env",
                        f"raw os.environ[{name!r}] read bypasses the "
                        "knob registry — use tpuflow.utils.knobs "
                        "accessors",
                    )
            # ---- membership reads ----------------------------------
            if isinstance(node, ast.Compare) and any(
                isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
            ):
                name = const_str(node.left)
                if (
                    name
                    and name.startswith("TPUFLOW_")
                    and any(_is_environ(c) for c in node.comparators)
                    and not in_registry
                    and not in_tests
                ):
                    sink.emit(
                        rel, node.lineno, "knob-raw-env",
                        f"raw `{name!r} in os.environ` check bypasses "
                        "the knob registry — use knobs.is_set",
                    )
            # ---- undeclared exact literals -------------------------
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                name = _knob_literal(node.value)
                if name is not None and name not in declared:
                    sink.emit(
                        rel, node.lineno, "knob-undeclared",
                        f"{node.value!r} is not declared in "
                        "tpuflow/utils/knobs.py — a typo'd knob name "
                        "silently defaults; declare it or fix the "
                        "spelling",
                    )

    # ---- README sync -------------------------------------------------
    if check_readme and readme_rel is not None:
        import os

        from tpuflow.utils import knobs as knobs_mod

        readme_path = os.path.join(tree.root, readme_rel)
        for err in knobs_mod.check_readme(readme_path):
            sink.emit(readme_rel, 1, "knob-readme-stale", err)
        try:
            with open(readme_path) as f:
                readme_text = f.read()
        except OSError:
            readme_text = ""
        seen = set()
        for i, line in enumerate(readme_text.split("\n"), start=1):
            for tok in _README_TOKEN_RE.findall(line):
                name = _knob_literal(tok)
                if name and name not in declared and name not in seen:
                    seen.add(name)
                    sink.emit(
                        readme_rel, i, "knob-readme-unknown",
                        f"README mentions {tok!r} but the registry does "
                        "not declare it — prose drifted from code",
                    )

    return sink.result()
