"""Pass 4 — telemetry-name catalog lint (formerly ``tools/obs_lint.py``).

Every literal span/counter/gauge/histogram/event name emitted under
``tpuflow/`` must be registered — with the same kind — in
``tpuflow.obs.catalog.CATALOG``; dynamic-name emitter calls are errors;
the ISSUE-chain REQUIRED_EMITTERS must all exist; the tier-1 duration
guard rides along.

Promoted in this pass (ISSUE 12 satellite): an **unemitted catalog
entry** — a registered name with no literal emitter anywhere — is now
an ERROR, not a warning. Dead ``serve.*``/``train.*`` names in the
catalog make the runbooks describe telemetry that no longer exists.
``UNEMITTED_GRANDFATHER`` is the explicit exception list; it is EMPTY
and must stay empty — stage a name and its emitter in the same PR (the
recorder's own close-path ``obs.dropped`` record is recognized via its
raw dict literal, which is why the list could be burned down to
nothing).

Rules: ``obs-unregistered``, ``obs-kind-mismatch``, ``obs-dynamic-name``,
``obs-missing-required``, ``obs-unemitted``, ``obs-tier1-duration``.
"""

from __future__ import annotations

import json
import os
import re

from tpuflow.lint.core import Sink, Tree

# obs.span("name", ...) / obs.counter("name") / ... (the module-level
# API; `_rec.` covers tpuflow.obs.health, which imports the recorder
# module under that alias to avoid a circular package import)
_API_RE = re.compile(
    r"\b(?:obs|_rec)\.(span|counter|gauge|histogram|event)"
    r"\(\s*[\"']([a-z0-9_.]+)[\"']"
)
# obs.timed_iter(loader, "name") — records histogram observations
_TIMED_ITER_RE = re.compile(
    r"\bobs\.timed_iter\([^)]*?,\s*[\"']([a-z0-9_.]+)[\"']", re.S
)
# rec.record("span", "name", ...) — the low-level recorder API
_RECORD_RE = re.compile(
    r"\.record\(\s*[\"'](span|counter|gauge|histogram|event)[\"']\s*,"
    r"\s*[\"']([a-z0-9_.]+)[\"']",
    re.S,
)
# A raw JSONL record dict with literal kind+name keys — the recorder's
# own close path emits obs.dropped this way (the buffered emitter API
# cannot run while the recorder is closing). Counting it keeps the
# unemitted-entry rule honest without a grandfather entry.
_RAW_RECORD_RE = re.compile(
    r"[\"']kind[\"']\s*:\s*[\"'](span|counter|gauge|histogram|event)"
    r"[\"']\s*,\s*[\"']name[\"']\s*:\s*[\"']([a-z0-9_.]+)[\"']",
    re.S,
)
# An emitter whose NAME is not a string literal is invisible to this
# lint — flag it; emit literal names (one call per name) instead.
_DYNAMIC_RE = re.compile(
    r"\b(?:obs|_rec)\.(span|counter|gauge|histogram|event)\(\s*(?![\"'])\S"
)
# recorder.py's internals forward (kind, self._name) — dynamic by
# construction; its literal names (the raw close-path record) still
# count as emitters above.
_DYNAMIC_EXEMPT = ("tpuflow/obs/recorder.py",)
# The lint package documents the emitter API shapes it greps for; its
# own pattern examples are not emitters.
_SCAN_EXEMPT_PREFIX = "tpuflow/lint/"

# (kind, name) pairs the tree is REQUIRED to emit somewhere — the
# runbook evidence trails of ISSUEs 5-11. The pytest twin
# (tests/test_obs.py) checks these plus its own per-subsystem list.
REQUIRED_EMITTERS: tuple[tuple[str, str], ...] = (
    ("event", "ckpt.io_retry"),
    ("event", "ckpt.io_error"),
    ("event", "ckpt.save_failed"),
    ("event", "ckpt.gc"),
    ("span", "ckpt.upload"),
    ("event", "ckpt.restore_tier"),
    ("event", "ckpt.emergency_save"),
    ("event", "ckpt.verify"),
    ("event", "ckpt.corrupt"),
    ("gauge", "goodput.productive_s"),
    ("gauge", "goodput.lost_s"),
    ("gauge", "goodput.fraction"),
    ("event", "obs.flight"),
    ("event", "obs.export"),
    ("span", "flow.gang_resize"),
    ("event", "flow.member_lost"),
    ("gauge", "dist.mesh_generation"),
    ("gauge", "serve.queue_depth"),
    ("gauge", "serve.slot_occupancy"),
    ("gauge", "serve.ttft_s"),
    ("gauge", "serve.tokens_per_s"),
    ("counter", "serve.tokens"),
    ("counter", "serve.requests"),
    ("event", "serve.admit"),
    ("event", "serve.complete"),
    ("span", "serve.warmup"),
    ("span", "serve.prefill"),
    ("span", "serve.decode"),
    ("gauge", "serve.pages_free"),
    ("gauge", "serve.prefix_hits"),
    ("gauge", "serve.spec_accept_rate"),
    ("event", "serve.page_evict"),
    ("span", "serve.quant_decode"),
    ("counter", "serve.quant_requests"),
    # Serving observatory (ISSUE 13): lifecycle traces, engine-time
    # ledger fractions, and declared-SLO accounting.
    ("event", "serve.trace"),
    ("event", "serve.slo_violation"),
    ("counter", "serve.slo_violations"),
    ("gauge", "serve.idle_fraction"),
    ("gauge", "serve.decode_fraction"),
    ("gauge", "serve.prefill_fraction"),
    ("gauge", "serve.decode_utilization"),
    ("gauge", "serve.masked_row_waste"),
    # Disaggregated prefill/decode + tiered KV (ISSUE 19): the ship /
    # import spans, the tier spill/hit/promote trail, and the per-tier
    # page gauges.
    ("span", "serve.kv_ship"),
    ("span", "serve.kv_import"),
    ("event", "serve.tier_hit"),
    ("event", "serve.tier_promote"),
    ("event", "serve.tier_spill"),
    ("gauge", "serve.pages_host"),
    ("gauge", "serve.pages_disk"),
    ("event", "router.ship"),
    ("event", "router.ship_fallback"),
    # Fleet observatory (ISSUE 14): registration, the poll sweep, and
    # the staleness evidence trail.
    ("event", "fleet.register"),
    ("span", "fleet.poll"),
    ("gauge", "fleet.size"),
    ("gauge", "fleet.qps"),
    ("event", "fleet.replica_stale"),
    # Device observatory (ISSUE 15): the per-program ledger, the HBM
    # gauges, the static budget check, and triggered capture.
    ("event", "device.program"),
    ("gauge", "device.hbm_used"),
    ("gauge", "device.hbm_peak"),
    ("gauge", "device.hbm_limit"),
    ("event", "device.hbm_budget"),
    ("event", "prof.capture"),
    # Decision observatory (ISSUE 16): the run registry's append audit
    # and the alert engine's deduplicated lifecycle events.
    ("event", "registry.append"),
    ("event", "alert.fired"),
    ("event", "alert.resolved"),
    # Front-door router (ISSUE 17): admission, failover, and drain
    # evidence — the chaos harness's zero-drop claim is audited from
    # exactly these events.
    ("event", "router.admit"),
    ("event", "router.reject"),
    ("event", "router.retry"),
    ("event", "router.reroute"),
    ("event", "router.drain"),
    ("event", "router.replace"),
    ("gauge", "router.queue_depth"),
    ("gauge", "router.budget_pages"),
    # End-to-end tracing (ISSUE 18): tail-sampling escalation, flush
    # audit, and the appended/dropped span counters.
    ("event", "trace.escalate"),
    ("event", "trace.flush"),
    ("counter", "trace.spans"),
    ("counter", "trace.dropped"),
    ("event", "quant.decision"),
    ("event", "quant.kernel_fallback"),
    ("event", "ops.flash_bwd_fused"),
    ("event", "train.remat_policy"),
    ("gauge", "train.exposed_comm_s"),
    ("gauge", "train.comm_overlap_s"),
)

# Catalog entries allowed to have no emitter. EMPTY by design: the
# unemitted warning was promoted to an error (ISSUE 12) and the list
# burned down — register a name in the same PR as its emitter. Add an
# entry here only with a comment saying which PR removes it.
UNEMITTED_GRANDFATHER: frozenset[str] = frozenset()

# Tier-1 duration guard (ISSUE 6 satellite): tests/conftest.py records
# every full 'not slow' session's wall time; exceeding the guard fails
# the lint BEFORE CI starts getting killed by the hard timeout.
# ISSUE 16 slow-mark audit: the suite had crept to ~1170s; marking the
# 14 biggest call-time outliers brought a clean run to 767s, and the
# guard was pinned at 800 so that headroom can't silently erode back.
# ISSUE 18 re-pin: the accumulated fast suites (trace units included,
# all jax-free) sit just over 800 on the CI host; 820 keeps ~50s of
# real headroom under the 870 hard budget.
TIER1_BUDGET_S = 870.0
TIER1_GUARD_S = 820.0
TIER1_DURATION_FILE = ".tier1_duration.json"
_TIER1_MIN_TESTS = 100


def tier1_duration_guard(root: str) -> str | None:
    """Error string when the last recorded full tier-1 session exceeded
    the duration guard, else None."""
    try:
        with open(os.path.join(root, TIER1_DURATION_FILE)) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    if rec.get("markexpr") != "not slow":
        return None
    try:
        if int(rec.get("testscollected", 0)) < _TIER1_MIN_TESTS:
            return None
        dur = float(rec.get("duration_s", 0.0))
    except (TypeError, ValueError):
        return None
    if dur > TIER1_GUARD_S:
        return (
            f"tier-1 suite recorded {dur:.0f}s, over the "
            f"{TIER1_GUARD_S:.0f}s guard of the {TIER1_BUDGET_S:.0f}s "
            "budget — slow-mark the newest long tests or speed the "
            "suite up before CI starts timing out"
        )
    return None


def _lineno(src: str, pos: int) -> int:
    return src.count("\n", 0, pos) + 1


def emitted_names(tree: Tree) -> list[tuple[str, str, str, int]]:
    """(relpath, kind, name, lineno) for every literal emitter call
    under tpuflow/."""
    out = []
    for rel in tree.files():
        norm = rel.replace("\\", "/")
        if not norm.startswith("tpuflow/") or norm.startswith(
            _SCAN_EXEMPT_PREFIX
        ):
            continue
        src = tree.source(rel)
        for m in _API_RE.finditer(src):
            out.append((rel, m.group(1), m.group(2), _lineno(src, m.start())))
        for m in _TIMED_ITER_RE.finditer(src):
            out.append((rel, "histogram", m.group(1), _lineno(src, m.start())))
        for m in _RECORD_RE.finditer(src):
            out.append((rel, m.group(1), m.group(2), _lineno(src, m.start())))
        for m in _RAW_RECORD_RE.finditer(src):
            out.append((rel, m.group(1), m.group(2), _lineno(src, m.start())))
    return out


def run(
    tree: Tree,
    catalog: dict | None = None,
    required: tuple = REQUIRED_EMITTERS,
    grandfather: frozenset = UNEMITTED_GRANDFATHER,
    duration_guard: bool = True,
):
    if catalog is None:
        from tpuflow.obs.catalog import CATALOG as catalog

    sink = Sink(tree)
    used: set[str] = set()
    kinds: set[tuple[str, str]] = set()
    for rel, kind, name, lineno in emitted_names(tree):
        used.add(name)
        kinds.add((kind, name))
        if name not in catalog:
            sink.emit(
                rel, lineno, "obs-unregistered",
                f"emits {kind} {name!r} not registered in "
                "tpuflow.obs.catalog.CATALOG",
            )
        elif catalog[name][0] != kind:
            sink.emit(
                rel, lineno, "obs-kind-mismatch",
                f"emits {name!r} as {kind} but the catalog registers "
                f"it as {catalog[name][0]}",
            )
    for rel in tree.files():
        norm = rel.replace("\\", "/")
        if (
            not norm.startswith("tpuflow/")
            or norm in _DYNAMIC_EXEMPT
            or norm.startswith(_SCAN_EXEMPT_PREFIX)
        ):
            continue
        src = tree.source(rel)
        for m in _DYNAMIC_RE.finditer(src):
            sink.emit(
                rel, _lineno(src, m.start()), "obs-dynamic-name",
                f"emitter with a non-literal name ({m.group(0)!r}...) "
                "is invisible to this lint — emit literal catalog "
                "names instead",
            )
    for kind, name in required:
        if (kind, name) not in kinds:
            sink.emit(
                "tpuflow", 0, "obs-missing-required",
                f"required emitter missing from tpuflow/: {name!r} "
                f"({kind})",
            )
    for name in sorted(set(catalog) - used - set(grandfather)):
        sink.emit(
            "tpuflow/obs/catalog.py", 1, "obs-unemitted",
            f"catalog name {name!r} has no literal emitter in tpuflow/ "
            "— dead catalog entries make runbooks describe telemetry "
            "that does not exist; delete the entry or land its emitter "
            "(UNEMITTED_GRANDFATHER is the explicit, empty-by-design "
            "exception list)",
        )
    if duration_guard:
        err = tier1_duration_guard(tree.root)
        if err:
            sink.emit(TIER1_DURATION_FILE, 0, "obs-tier1-duration", err)
    return sink.result()
