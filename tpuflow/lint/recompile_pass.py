"""Pass 3 — the never-recompile contract, cross-checked statically.

The serving engine's contract (PR 8, extended by 9 and 11) is that
``compile_stats()`` never grows after ``warmup()``, and that
``tools/prewarm_cache.py`` can land every program the scheduler replays
in the persistent cache ahead of gang launch. Until now that contract
lived only in runtime tests (``tests/test_serve.py`` pins the cache
sizes) — a NEW jit program added to ``ServeEngine`` without a warmup
execution, a ``compile_stats`` entry, and an ``aot_lower`` signature
would pass review and fail in production as a stray recompile erasing
the PR 8-11 throughput wins.

This pass extracts the engine's jit program inventory statically (every
``self.<attr> = jax.jit(...)`` in the engine class) and fails when a
program is missing from any of the three coverage surfaces:

- ``compile_stats``  (the runtime contract's observable),
- ``warmup``         (the executed warm path),
- ``aot_lower``      (the AOT signature list prewarm routes through),

or when ``tools/prewarm_cache.py`` stops routing through
``aot_lower()`` (the tool drifting from the engine-owned list is
exactly the bug ISSUE 11 moved the list into the engine to kill).

Rule: ``serve-aot-coverage``.
"""

from __future__ import annotations

import ast

from tpuflow.lint.core import Sink, Tree, dotted

SERVE_REL = "tpuflow/infer/serve.py"
PREWARM_REL = "tools/prewarm_cache.py"
ENGINE_CLASS = "ServeEngine"
COVERAGE_METHODS = ("compile_stats", "warmup", "aot_lower")


def _is_jit_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and dotted(node.func) in (
        "jax.jit", "jit"
    )


def _self_attrs(node: ast.AST) -> set[str]:
    out = set()
    for n in ast.walk(node):
        if (
            isinstance(n, ast.Attribute)
            and isinstance(n.value, ast.Name)
            and n.value.id == "self"
        ):
            out.add(n.attr)
    return out


def run(
    tree: Tree,
    serve_rel: str = SERVE_REL,
    prewarm_rel: str = PREWARM_REL,
    engine_class: str = ENGINE_CLASS,
    coverage_methods: tuple[str, ...] = COVERAGE_METHODS,
):
    sink = Sink(tree)
    mod = tree.tree(serve_rel)
    if mod is None:
        sink.emit(
            serve_rel, 1, "serve-aot-coverage",
            "cannot parse the serving engine module",
        )
        return sink.result()

    engine = None
    for node in ast.walk(mod):
        if isinstance(node, ast.ClassDef) and node.name == engine_class:
            engine = node
            break
    if engine is None:
        sink.emit(
            serve_rel, 1, "serve-aot-coverage",
            f"class {engine_class!r} not found — the never-recompile "
            "cross-check has nothing to anchor to; update "
            "tpuflow/lint/recompile_pass.py if the engine moved",
        )
        return sink.result()

    # ---- the jit program inventory: self.<attr> = jax.jit(...) -------
    programs: dict[str, int] = {}
    for node in ast.walk(engine):
        if isinstance(node, ast.Assign) and _is_jit_call(node.value):
            for target in node.targets:
                if (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    programs.setdefault(target.attr, node.lineno)
    if not programs:
        sink.emit(
            serve_rel, engine.lineno, "serve-aot-coverage",
            f"{engine_class} declares no `self.<attr> = jax.jit(...)` "
            "programs — the inventory extraction broke; fix the pass "
            "before trusting it",
        )

    # ---- each program must appear in every coverage surface -----------
    methods = {
        n.name: n
        for n in engine.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for meth in coverage_methods:
        fn = methods.get(meth)
        if fn is None:
            sink.emit(
                serve_rel, engine.lineno, "serve-aot-coverage",
                f"{engine_class}.{meth}() is missing — it is one of the "
                "three surfaces the never-recompile contract is checked "
                "against",
            )
            continue
        covered = _self_attrs(fn)
        for attr, lineno in sorted(programs.items()):
            if attr not in covered:
                sink.emit(
                    serve_rel, lineno, "serve-aot-coverage",
                    f"jit program self.{attr} is not referenced by "
                    f"{engine_class}.{meth}() — a program outside the "
                    f"{meth} surface breaks the never-recompile "
                    "contract (stray recompile / cold compile at "
                    "serve time)",
                )

    # ---- prewarm must route through the engine-owned list -------------
    pmod = tree.tree(prewarm_rel)
    if pmod is None:
        sink.emit(
            prewarm_rel, 1, "serve-aot-coverage",
            "cannot parse the prewarm tool",
        )
        return sink.result()
    routes = any(
        isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and n.func.attr == "aot_lower"
        for n in ast.walk(pmod)
    )
    if not routes:
        sink.emit(
            prewarm_rel, 1, "serve-aot-coverage",
            f"does not call {engine_class}.aot_lower() — the tool has "
            "drifted from the engine-owned AOT signature list and can "
            "no longer guarantee prewarm coverage",
        )
    return sink.result()
