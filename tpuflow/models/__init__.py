"""Flax model zoo + losses.

Parity with the reference's model/ops layer (torch.nn MLP + CrossEntropyLoss +
SGD, reference my_ray_module.py:94-112,141-142) plus the larger models named by
the acceptance configs (ResNet-18/50, GPT-2) behind the same trainer API.
"""

from tpuflow.models.mlp import NeuralNetwork
from tpuflow.models.losses import cross_entropy_loss, accuracy

__all__ = ["NeuralNetwork", "cross_entropy_loss", "accuracy", "get_model"]


def get_model(name: str, **kwargs):
    """Model registry — models are pluggable behind the trainer API (the
    acceptance configs name ResNet-18/50 and GPT-2-medium, BASELINE.md)."""
    name = name.lower()
    if name in ("mlp", "neural_network", "fashion_mnist_mlp"):
        return NeuralNetwork(**kwargs)
    if name in ("resnet18", "resnet50"):
        from tpuflow.models.resnet import ResNet18, ResNet50

        return (ResNet18 if name == "resnet18" else ResNet50)(**kwargs)
    if name in ("gpt2", "gpt2_medium", "gpt2-medium"):
        from tpuflow.models.gpt2 import GPT2, GPT2Config

        if name != "gpt2":
            kwargs.setdefault("config", GPT2Config.medium())
        return GPT2(**kwargs)
    if name in ("vit", "vit_tiny", "vit_small"):
        from tpuflow.models.vit import ViT

        if name == "vit_tiny":  # ViT-Ti/16
            for k, v in dict(
                n_embd=192, n_layer=12, n_head=3, patch_size=16
            ).items():
                kwargs.setdefault(k, v)
        elif name == "vit_small":  # ViT-S/16
            for k, v in dict(
                n_embd=384, n_layer=12, n_head=6, patch_size=16
            ).items():
                kwargs.setdefault(k, v)
        return ViT(**kwargs)
    raise KeyError(
        f"unknown model {name!r}; available: mlp, resnet18, resnet50, "
        "gpt2, gpt2_medium, vit, vit_tiny, vit_small"
    )
