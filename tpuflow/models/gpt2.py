"""GPT-2 causal language model in Flax — the FSDP acceptance-config model.

The driver acceptance configs name "GPT-2-medium FSDP → pjit fully-sharded
checkpoint (multi-host v5e-32)" (BASELINE.md config 5); the reference repo has
no transformer at all, so this is a TPU-first design, not a translation:
bf16 activations on the MXU, attention behind the pluggable ``tpuflow.ops``
dispatch ('xla' | Pallas 'flash' | sequence-parallel 'ring'), weights tied
between the token embedding and the LM head, and shapes kept static for jit.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from tpuflow.ops import attention


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_ctx: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.1
    ln_eps: float = 1e-5  # GPT-2's LayerNorm epsilon (HF-checkpoint parity)
    attn_impl: str = "xla"  # 'auto' | 'xla' | 'flash' | 'ring' | 'ulysses'
    dtype: jnp.dtype = jnp.float32  # activation dtype; bfloat16 on TPU
    # Rematerialize each block on the backward pass (jax.checkpoint): peak
    # activation memory drops from O(n_layer·B·T·C) to O(B·T·C) + one block's
    # intermediates, the standard HBM-for-FLOPs trade for long-context /
    # large-model training on TPU.
    remat: bool = False
    # Selective remat: name of a jax.checkpoint_policies entry controlling
    # WHICH intermediates the block saves vs recomputes. None = save only
    # block inputs (max memory savings, most recompute). The TPU-standard
    # middle ground is 'dots_with_no_batch_dims_saveable': matmul outputs
    # (MXU work) are saved, elementwise/softmax (cheap VPU work, the bulk
    # of activation bytes) recompute — most of the memory win at a
    # fraction of the recompute cost.
    remat_policy: str | None = None
    # Roll the layer stack into one nn.scan'd block: the transformer block is
    # traced/compiled ONCE instead of n_layer times (compile time stops
    # scaling with depth) and params stack along a leading layer axis, which
    # the path+shape sharding rules handle transparently. Checkpoints are not
    # interchangeable between scan and non-scan layouts.
    scan_layers: bool = False
    # Mixture-of-Experts: n_experts > 0 replaces every block's MLP with a
    # Switch-routed expert MLP (tpuflow.models.moe) whose weights shard over
    # the 'expert' mesh axis (expert parallelism).
    n_experts: int = 0
    capacity_factor: float = 1.25
    moe_aux_weight: float = 1e-2  # load-balance loss coefficient
    # Decode-path (KV-cache) compute dtype. Autoregressive decode is
    # HBM-bandwidth-bound — weights stream as bf16 regardless — so f32
    # compute costs ~nothing and makes decode numerics WIDTH-INDEPENDENT:
    # bf16 rounding of layer outputs differs systematically between a
    # (K+1)-token chunk forward and single-token decode (one bf16 ulp is
    # 0.4%, dwarfing the 1e-7 f32 accumulation noise), which flipped
    # near-tie argmaxes and broke speculative decode's exactness vs plain
    # greedy (r4 on-chip numerics_ok=false; reproduced on CPU-bf16 at
    # scan_layers). None = use ``dtype`` (the old width-dependent
    # behavior, for capacity-critical serving).
    decode_dtype: jnp.dtype | None = jnp.float32
    # KV-cache storage dtype. None = the decode compute dtype above (so
    # exactness-by-default); set bfloat16 to halve cache bytes for long
    # contexts at the cost of the width-dependent rounding amplifier.
    cache_dtype: jnp.dtype | None = None
    # Decode-path (KV-cache, non-prefill) matmul precision. decode_dtype
    # = f32 removed the LAYER-STACK width dependence, but on TPU the
    # MXU's DEFAULT precision still lowers f32 matmuls to bf16 multiply
    # passes whose rounding depends on the program's tiling — i.e. on
    # the chunk WIDTH — so a (K+1)-token verify forward and single-token
    # decode could still argmax-flip near-tie logits (the r5 on-chip
    # speculative numerics_ok=false on BOTH prompt legs while every CPU
    # scenario stayed bit-exact; the suspected ladder-acceptance pad bug
    # was ruled out — acceptance compares argmaxes of ONE forward, see
    # tests/test_speculative.py::test_pad_laden_drafts_stay_exact).
    # 'highest' pins decode-mode matmuls (attention, Dense, LM head) to
    # true f32 — decode is HBM-bandwidth-bound, so the extra MXU passes
    # are ~free. None = platform default (the old behavior, for
    # capacity-critical serving). Prefill keeps DEFAULT precision: it is
    # the one compute-bound decode call and runs at the same width in
    # every decode strategy, so it cannot introduce width-dependent
    # rounding.
    decode_precision: str | None = "highest"
    # Paged KV cache (the serving engine's block-granular layout,
    # ISSUE 11). kv_pages > 0 switches slot-mode decode calls that pass
    # a ``page_table`` to a POOLED cache: instead of one contiguous
    # (B, n_ctx, H, D) row per slot, the cache is a fixed
    # (kv_pages, kv_page_size, H, D) pool and each slot's logical row is
    # scattered across the pages its (B, n_ctx/kv_page_size) table
    # names. kv_page_size must divide n_ctx. Page 0 is the engine's
    # TRASH page: out-of-range writes and dead slots (zeroed tables)
    # land there and nothing ever reads it, so a freed page can be
    # re-allocated to a new request without the old slot's frozen
    # garbage write chasing it. Training/scoring/solo-generate forwards
    # never consult these fields.
    kv_pages: int = 0
    kv_page_size: int = 0

    def compute_dtype(self, decode: bool):
        """Activation/compute dtype for this forward: ``decode_dtype``
        on the KV-cache path (width-independent f32 by default — see the
        field comment), ``dtype`` for training/scoring forwards."""
        if decode and self.decode_dtype is not None:
            return self.decode_dtype
        return self.dtype

    def kv_cache_dtype(self):
        """Storage dtype of the KV cache (``cache_dtype`` override, else
        the decode compute dtype)."""
        if self.cache_dtype is not None:
            return self.cache_dtype
        return self.compute_dtype(decode=True)

    def matmul_precision(self, decode: bool):
        """``jax.lax.Precision`` for this forward's matmuls: the pinned
        ``decode_precision`` on the KV-cache (non-prefill) path, else
        None (platform default). See the field comment for why decode
        needs width-independent rounding."""
        if decode and self.decode_precision:
            import jax

            return jax.lax.Precision(self.decode_precision.lower())
        return None

    @classmethod
    def small_test(cls, **kw) -> "GPT2Config":
        """Tiny config for tests (fast CPU compile)."""
        kw = {
            "vocab_size": 512,
            "n_ctx": 128,
            "n_embd": 128,
            "n_layer": 2,
            "n_head": 4,
            **kw,
        }
        return cls(**kw)

    @classmethod
    def medium(cls, **kw) -> "GPT2Config":
        """GPT-2-medium (355M): 24 layers, 1024 hidden, 16 heads."""
        kw = {"n_embd": 1024, "n_layer": 24, "n_head": 16, **kw}
        return cls(**kw)

    @classmethod
    def from_preset(
        cls,
        preset: str,
        *,
        attn_impl: str = "auto",
        seq_len: int = 64,
        stage_axis: int = 1,
        n_experts: int = 0,
        dtype=None,
    ) -> "GPT2Config":
        """The flows' preset table: ``test`` (tiny, fast CPU compile),
        ``gpt2`` (124M), ``medium`` (355M). Full-size presets scan the
        layer stack (compile time independent of depth) and rematerialize
        blocks (activation memory independent of depth) — the TPU-first
        defaults for real training. ``dtype`` overrides the ACTIVATION
        dtype (params/optimizer stay f32 — flax's param_dtype default):
        ``jnp.bfloat16`` is the standard TPU mixed-precision recipe (MXU
        operands in bf16, f32 master weights, f32 softmax/CE via the
        model's float32 loss head)."""
        extra = {} if dtype is None else {"dtype": dtype}
        if preset == "medium":
            return cls.medium(
                attn_impl=attn_impl, scan_layers=True, remat=True,
                n_experts=n_experts, **extra,
            )
        if preset == "gpt2":
            return cls(
                attn_impl=attn_impl, scan_layers=True, remat=True,
                n_experts=n_experts, **extra,
            )
        if preset == "test":
            return cls.small_test(
                attn_impl=attn_impl,
                n_ctx=max(128, seq_len),
                # Pipeline parallelism requires the scan-stacked block
                # layout (one leading layer axis to shard over 'stage').
                scan_layers=stage_axis > 1,
                n_layer=max(2, stage_axis),
                n_experts=n_experts,
                **extra,
            )
        raise ValueError(
            f"unknown preset {preset!r}; available: test, gpt2, medium"
        )


def _masked_attention(q, k, v, valid, precision=None):
    """Masked softmax attention, float32 statistics (bf16-safe), static
    shapes. ``valid`` broadcasts against the (B, H, Tq, Tk) score matrix.
    Fully-masked query rows (a left-pad column whose every key is invalid)
    degrade to a uniform softmax over the -1e30 constants — finite garbage
    that no real query ever attends to, so it stays isolated.
    ``precision`` pins the einsum matmul precision (the decode path
    passes Precision.HIGHEST for width-independent MXU rounding)."""
    import jax

    D = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32),
        precision=precision,
    ) * scale
    s = jnp.where(valid, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32), precision=precision
    ).astype(q.dtype)


def _left_pad_attention(q, k, v, pad_lens):
    """Causal attention over a LEFT-padded (B, T, H, D) batch: key columns
    ``< pad_lens[b]`` are masked out of row b."""
    T = q.shape[1]
    pos = jnp.arange(T)
    valid = (pos[None, :] <= pos[:, None])[None, None]  # causal (T, T)
    valid = valid & (pos[None, None, None, :] >= pad_lens[:, None, None, None])
    return _masked_attention(q, k, v, valid)


class Block(nn.Module):
    """Pre-LN transformer block: LN → MHA → residual, LN → MLP → residual.

    ``decode=True`` switches the attention to a fixed-size KV cache
    (``cache`` collection: ``cached_key``/``cached_value`` (B, n_ctx, H, D)
    + scalar ``cache_index``): the incoming T tokens are written at the
    current index and q attends over the cache through a static-shape mask
    (position ≤ query position) — one compilation for prefill (T=prompt)
    and one for single-token decode (T=1), XLA-friendly throughout. The
    reference has no generation path at all (its predictor is one
    classifier forward, my_ray_module.py:275-284); this is the LM-family
    completion of the batch-inference capability (SURVEY.md §2b D12).
    """

    config: GPT2Config

    @nn.compact
    def __call__(self, x, train: bool, decode: bool = False, pad_lens=None,
                 prefill: bool = False, slot_index=None, page_table=None):
        cfg = self.config
        B, T, C = x.shape
        head_dim = cfg.n_embd // cfg.n_head
        # Decode-path compute dtype (f32 by default: width-independent
        # numerics on the HBM-bound path; see GPT2Config.decode_dtype).
        # Prefill keeps the training dtype — prompt ingestion runs with
        # the SAME width in every decode strategy, so it cannot introduce
        # width-dependent rounding, and it is the one decode-mode call
        # that is compute-bound (TxT attention over the whole prompt).
        dt = cfg.compute_dtype(decode and not prefill)
        # Width-independent decode rounding: pin MXU precision on the
        # non-prefill decode path (see GPT2Config.decode_precision).
        prec = cfg.matmul_precision(decode and not prefill)

        h = nn.LayerNorm(epsilon=cfg.ln_eps, dtype=dt, name="ln_1")(x)
        qkv = nn.Dense(
            3 * cfg.n_embd, dtype=dt, precision=prec, name="c_attn"
        )(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, cfg.n_head, head_dim)
        k = k.reshape(B, T, cfg.n_head, head_dim)
        v = v.reshape(B, T, cfg.n_head, head_dim)
        if decode:
            a = self._cached_attention(
                q, k, v, pad_lens, prec, slot_index, page_table
            )
        elif pad_lens is not None:
            # Ragged (LEFT-padded) batch without a cache — the scoring path:
            # pad columns are masked out of every key set and real positions
            # are row-shifted, so a padded forward is token-exact vs a dense
            # per-row forward (tpuflow.infer.score on mixed-length batches).
            a = _left_pad_attention(q, k, v, pad_lens)
        else:
            a = attention(q, k, v, causal=True, impl=cfg.attn_impl)
        a = a.reshape(B, T, cfg.n_embd)
        a = nn.Dense(cfg.n_embd, dtype=dt, precision=prec, name="c_proj")(a)
        a = nn.Dropout(cfg.dropout, deterministic=not train)(a)
        x = x + a

        h = nn.LayerNorm(epsilon=cfg.ln_eps, dtype=dt, name="ln_2")(x)
        if cfg.n_experts > 0:
            from tpuflow.models.moe import MoEMLP

            h = MoEMLP(
                d_model=cfg.n_embd,
                d_ff=4 * cfg.n_embd,
                n_experts=cfg.n_experts,
                capacity_factor=cfg.capacity_factor,
                aux_weight=cfg.moe_aux_weight,
                dtype=dt,
                name="moe",
            )(h, train)
        else:
            h = nn.Dense(
                4 * cfg.n_embd, dtype=dt, precision=prec, name="mlp_fc"
            )(h)
            h = nn.gelu(h)
            h = nn.Dense(
                cfg.n_embd, dtype=dt, precision=prec, name="mlp_proj"
            )(h)
        h = nn.Dropout(cfg.dropout, deterministic=not train)(h)
        return x + h

    def _paged_attention(self, q, k, v, pad_lens, precision, slot_index,
                         page_table):
        """Paged (block-pooled) KV-cache attention — the serving engine's
        slot mode over a page pool (ISSUE 11).

        The cache is ONE (kv_pages, kv_page_size, H, D) pool shared by
        every slot; ``page_table`` (B, n_ctx/page_size) int32 maps each
        row's logical cache columns onto pool pages, and is threaded
        through the decode program as DATA — admissions, evictions and
        prefix-page sharing never change a shape, so the engine's
        never-recompile contract extends to page management.

        Writes: row b's T new k/v land at logical columns
        ``slot_index[b] + t``, each routed to
        ``table[b, col // ps] * ps + col % ps`` of the flattened pool.
        Out-of-range columns (>= n_ctx: a dying row's overshoot) and
        dead slots (tables zeroed by the engine) route to page 0 — the
        reserved TRASH page nothing ever reads — so a page freed and
        re-allocated to a new request can never be corrupted by its old
        slot's frozen garbage write (the paged analogue of the slot
        engine's overwritten-at-own-column argument).

        Reads: each row gathers its logical (n_ctx, H, D) view through
        its table and runs the SAME masked attention as the contiguous
        slot path — columns ``[pad_lens[b], slot_index[b] + t]`` only.
        Masked columns may be backed by the trash page or a stale page:
        their scores are the -1e30 constant either way, so the gathered
        garbage never reaches a real query (and the gathered bytes equal
        the contiguous row read — paging moves capacity accounting, not
        the attention's HBM traffic).
        """
        cfg = self.config
        B, T, H, D = q.shape
        ps = cfg.kv_page_size
        n_pages = cfg.kv_pages
        pages_per_row = cfg.n_ctx // ps
        cdt = cfg.kv_cache_dtype()
        ck = self.variable(
            "cache", "cached_key", jnp.zeros, (n_pages, ps, H, D), cdt
        )
        cv = self.variable(
            "cache", "cached_value", jnp.zeros, (n_pages, ps, H, D), cdt
        )
        # Created (never read/advanced) so the paged cache pytree keeps
        # the structure of a row cache — the engine's page-insert
        # tree_maps the two together.
        self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        pos = slot_index[:, None] + jnp.arange(T)[None, :]  # (B, T) logical
        page = jnp.take_along_axis(
            page_table, jnp.clip(pos // ps, 0, pages_per_row - 1), axis=1
        )
        flat = jnp.where(pos < cfg.n_ctx, page * ps + pos % ps, 0)

        def scatter(pool, new):
            body = pool.reshape(n_pages * ps, H, D)
            body = body.at[flat.reshape(-1)].set(
                new.astype(cdt).reshape(B * T, H, D)
            )
            return body.reshape(n_pages, ps, H, D)

        ck.value = scatter(ck.value, k)
        cv.value = scatter(cv.value, v)
        k_all = ck.value[page_table].reshape(B, cfg.n_ctx, H, D)
        v_all = cv.value[page_table].reshape(B, cfg.n_ctx, H, D)
        k_pos = jnp.arange(cfg.n_ctx)
        valid = k_pos[None, None, None, :] <= pos[:, None, :, None]
        if pad_lens is not None:
            valid = valid & (
                k_pos[None, None, None, :] >= pad_lens[:, None, None, None]
            )
        return _masked_attention(q, k_all, v_all, valid, precision=precision)

    def _cached_attention(self, q, k, v, pad_lens=None, precision=None,
                          slot_index=None, page_table=None):
        """Fixed-size KV-cache attention (decode mode).

        Writes the new k/v at ``cache_index`` and attends q over the whole
        cache behind a mask — shapes stay static for jit, the cache updates
        ride ``lax.dynamic_update_slice`` (no data-dependent shapes), and
        the O(n_ctx) masked attention is the HBM-bandwidth-optimal form for
        single-token decode on TPU (a (1, n_ctx) GEMV per head on the MXU).

        ``pad_lens`` (B,) marks rows as LEFT-padded: cache columns
        ``< pad_lens[b]`` are invisible to every query of row b (ragged
        prompt batches; tpuflow.infer.generate ``prompt_lens``).

        ``slot_index`` (B,) switches to PER-ROW cache positions (the
        continuous-batching serving engine, tpuflow.infer.serve): row b's
        k/v land at column ``slot_index[b]`` via a vmapped update, and
        row b's queries see columns ``[pad_lens[b], slot_index[b] + t]``
        only — so sequences of different lengths admit, decode, and evict
        independently inside ONE compiled program, and a reused slot's
        stale columns beyond the new sequence's frontier stay invisible.
        The scalar ``cache_index`` is not consulted or advanced: the
        engine owns per-slot lengths.

        Multi-token calls: a fresh-cache prefill (``start == 0``, no pads)
        takes the T x T fast path through the pluggable attention dispatch;
        any other multi-token call — chunked prefill at ``start > 0``, or a
        padded prefill — runs masked attention over the whole cache, which
        is exact for every (start, pad) combination (``lax.cond`` picks the
        branch at runtime, so both compile into the one program).
        """
        import jax

        cfg = self.config
        B, T, H, D = q.shape
        if slot_index is not None and page_table is not None:
            if cfg.kv_pages <= 0:
                raise ValueError(
                    "page_table passed but the config declares no page "
                    "pool — set kv_pages/kv_page_size (the serving "
                    "engine clones its decode model with them)"
                )
            return self._paged_attention(
                q, k, v, pad_lens, precision, slot_index, page_table
            )
        cdt = cfg.kv_cache_dtype()
        ck = self.variable(
            "cache",
            "cached_key",
            jnp.zeros,
            (B, cfg.n_ctx, H, D),
            cdt,
        )
        cv = self.variable(
            "cache",
            "cached_value",
            jnp.zeros,
            (B, cfg.n_ctx, H, D),
            cdt,
        )
        idx = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        if slot_index is not None:
            def row_write(cache_row, new_row, s):
                return jax.lax.dynamic_update_slice(
                    cache_row, new_row, (s, 0, 0)
                )

            ck.value = jax.vmap(row_write)(
                ck.value, k.astype(cdt), slot_index
            )
            cv.value = jax.vmap(row_write)(
                cv.value, v.astype(cdt), slot_index
            )
            q_pos = slot_index[:, None] + jnp.arange(T)[None, :]  # (B, T)
            k_pos = jnp.arange(cfg.n_ctx)
            valid = (
                k_pos[None, None, None, :] <= q_pos[:, None, :, None]
            )  # (B, 1, T, n_ctx)
            if pad_lens is not None:
                valid = valid & (
                    k_pos[None, None, None, :]
                    >= pad_lens[:, None, None, None]
                )
            return _masked_attention(
                q, ck.value, cv.value, valid, precision=precision
            )
        start = idx.value
        ck.value = jax.lax.dynamic_update_slice(
            ck.value, k.astype(cdt), (0, start, 0, 0)
        )
        cv.value = jax.lax.dynamic_update_slice(
            cv.value, v.astype(cdt), (0, start, 0, 0)
        )
        idx.value = start + T

        def cache_attention():
            # Key position k is visible to query position start+t iff
            # k <= start+t (and, for left-padded rows, k >= pad_lens[b]).
            q_pos = start + jnp.arange(T)[:, None]
            k_pos = jnp.arange(cfg.n_ctx)[None, :]
            valid = (k_pos <= q_pos)[None, None]
            if pad_lens is not None:
                valid = valid & (
                    k_pos[None, None] >= pad_lens[:, None, None, None]
                )
            return _masked_attention(
                q, ck.value, cv.value, valid, precision=precision
            )

        if T > 1:
            # Fresh-cache prefill (start == 0) takes an exact T x T path —
            # the pluggable dispatch when dense, the left-padded masked
            # form when ragged — instead of softmaxing over n_ctx - T dead
            # cache columns; warm-cache (chunked) prefill takes the general
            # cache path. Runtime branch: start is traced. Decode mode is
            # never differentiated, so 'auto' dispatch uses the FWD-ONLY
            # flash crossover (needs_bwd=False): prefill gets the flash
            # win from the much lower fwd threshold even at sequence
            # lengths where the backward would have lost to XLA.
            fast = (
                (lambda: attention(
                    q, k, v, causal=True, impl=cfg.attn_impl,
                    needs_bwd=False,
                ).astype(q.dtype))
                if pad_lens is None
                else (lambda: _left_pad_attention(q, k, v, pad_lens))
            )
            return jax.lax.cond(start == 0, fast, cache_attention)
        return cache_attention()


class _ScanBlock(nn.Module):
    """Scan-body adapter: (carry, broadcast train/decode) → (carry, no ys)."""

    config: GPT2Config

    @nn.compact
    def __call__(self, x, train: bool, decode: bool = False, pad_lens=None,
                 prefill: bool = False, slot_index=None, page_table=None):
        return (
            Block(self.config, name="block")(
                x, train, decode, pad_lens, prefill, slot_index, page_table
            ),
            None,
        )


class GPT2(nn.Module):
    """Token ids (B, T) int32 → logits (B, T, vocab). LM head tied to wte."""

    config: GPT2Config = GPT2Config()

    @nn.compact
    def __call__(
        self, tokens, *, train: bool = False, decode: bool = False,
        pad_lens=None, prefill: bool = False, slot_index=None,
        page_table=None,
    ):
        """``pad_lens`` (B,) int32 marks LEFT-padded rows: row b's first
        ``pad_lens[b]`` columns are padding — their positions clamp to 0,
        and every attention masks them out of the key set (ragged prompt
        generation / scoring; tpuflow.infer). ``prefill=True`` marks a
        decode-mode call that ingests the prompt: it keeps the training
        compute dtype (same-width in every decode strategy, so no
        width-dependent rounding; and it is the compute-bound decode
        call) while verify chunks and single-token steps run in
        ``decode_dtype``. ``slot_index`` (B,) int32 switches decode mode
        to PER-ROW cache positions (the serving engine's slot-based KV
        cache): row b writes/reads at its own column, positions come
        from ``slot_index - pad_lens``, and the model-level ``pos_index``
        is neither consulted nor advanced. ``page_table``
        (B, n_ctx/kv_page_size) int32 further switches slot mode to the
        PAGED cache pool (``kv_pages``/``kv_page_size`` config fields):
        logical columns route through the table onto shared pool pages
        (Block._paged_attention) — positions and masking are identical
        to contiguous slot mode."""
        cfg = self.config
        B, T = tokens.shape
        if pad_lens is not None:
            pad_lens = jnp.asarray(pad_lens, jnp.int32)
        if slot_index is not None:
            slot_index = jnp.asarray(slot_index, jnp.int32)
        if page_table is not None:
            page_table = jnp.asarray(page_table, jnp.int32)
        wte = self.param(
            "wte",
            nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.n_embd),
            jnp.float32,
        )
        wpe = self.param(
            "wpe",
            nn.initializers.normal(0.01),
            (cfg.n_ctx, cfg.n_embd),
            jnp.float32,
        )
        if decode:
            # Autoregressive mode: positions continue from the model-level
            # cache index (the blocks keep their own KV indices in the same
            # 'cache' collection; see Block._cached_attention).
            import jax

            pos = self.variable(
                "cache", "pos_index", lambda: jnp.zeros((), jnp.int32)
            )
            start = pos.value
            if slot_index is None:
                pos.value = start + T
            if slot_index is not None:
                # Slot mode: per-row positions from the engine's per-slot
                # lengths (pad columns shift them down, as in ragged
                # decode). The shared pos_index stays untouched.
                base = slot_index[:, None] + jnp.arange(T)[None, :]
                if pad_lens is not None:
                    base = base - pad_lens[:, None]
                positions = jnp.clip(base, 0, cfg.n_ctx - 1)
                pe = wpe[positions]  # (B, T, C)
            elif pad_lens is not None:
                # Left-padded rows: real positions shift down by the row's
                # pad count (clamped — pad columns read position 0, whose
                # output real tokens never attend to).
                positions = jnp.clip(
                    start + jnp.arange(T)[None, :] - pad_lens[:, None],
                    0,
                    cfg.n_ctx - 1,
                )
                pe = wpe[positions]  # (B, T, C)
            else:
                pe = jax.lax.dynamic_slice(
                    wpe, (start, jnp.int32(0)), (T, cfg.n_embd)
                )
        elif pad_lens is not None:
            positions = jnp.clip(
                jnp.arange(T)[None, :] - pad_lens[:, None], 0, cfg.n_ctx - 1
            )
            pe = wpe[positions]
        else:
            pe = wpe[:T]
        dt = cfg.compute_dtype(decode and not prefill)
        x = wte[tokens].astype(dt) + pe.astype(dt)
        x = nn.Dropout(cfg.dropout, deterministic=not train)(x)
        def remat_wrap(mod):
            import jax as _jax

            policy = None
            if cfg.remat_policy == "dots":
                # The ISSUE 10 selector's middle ground: save every MXU
                # dot output PLUS the named flash-attention output, so
                # the backward recomputes only cheap elementwise/softmax
                # work (and, inside a flash custom_vjp, the one fwd
                # kernel re-run jax's remat can't elide — see the
                # checkpoint_name note in ops/flash_attention.py; the
                # zero-recompute mode is remat OFF, selector 'none').
                cp = _jax.checkpoint_policies
                policy = cp.dots_with_no_batch_dims_saveable
                try:
                    policy = cp.save_from_both_policies(
                        policy,
                        cp.save_only_these_names("flash_out"),
                    )
                except AttributeError:
                    pass  # old jax without name policies: dots alone
            elif cfg.remat_policy:
                try:
                    policy = getattr(
                        _jax.checkpoint_policies, cfg.remat_policy
                    )
                except AttributeError:
                    raise ValueError(
                        f"unknown remat_policy {cfg.remat_policy!r}; valid "
                        "names are the jax.checkpoint_policies attributes"
                    ) from None
            # Args (with the module at 0): x=1, train=2, decode=3,
            # pad_lens=4, prefill=5, slot_index=6, page_table=7.
            # train/decode/prefill are Python bools that steer tracing —
            # static. pad_lens, slot_index, and page_table are DATA
            # arrays (tracers during ragged/slot/paged decode): marking
            # pad_lens static, as (2, 3, 4) once did, crashed every
            # remat=True decode-mode call with TracerBoolConversionError.
            return nn.remat(mod, static_argnums=(2, 3, 5), policy=policy)

        if cfg.scan_layers:
            body = remat_wrap(_ScanBlock) if cfg.remat else _ScanBlock
            blocks = nn.scan(
                body,
                # 'losses' must be declared or nn.scan silently DROPS the
                # per-layer sown values (the MoE load-balance aux loss).
                variable_axes={"params": 0, "losses": 0, "cache": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.n_layer,
                in_axes=nn.broadcast,
            )
            x, _ = blocks(cfg, name="h")(
                x, train, decode, pad_lens, prefill, slot_index, page_table
            )
        else:
            block_cls = remat_wrap(Block) if cfg.remat else Block
            for i in range(cfg.n_layer):
                x = block_cls(cfg, name=f"h{i}")(
                    x, train, decode, pad_lens, prefill, slot_index,
                    page_table,
                )
        x = nn.LayerNorm(epsilon=cfg.ln_eps, dtype=dt, name="ln_f")(x)
        if self.has_variable("quant", "wte_q"):
            # Native int8 LM head (ISSUE 9): the fused-native quantizer
            # (tpuflow.infer.quant mode='mxu') supplies an int8 view of
            # the tied wte with PER-VOCAB-ROW scales as its own 'quant'
            # collection — the 'params' tree keeps the fp structure this
            # module declares, so checkpoints and shardings never see a
            # fork. Decode streams the (vocab, n_embd) head — a third of
            # GPT-2-124M's bytes — as int8, and the integer contraction
            # is exact, hence width-independent on the MXU: the
            # decode_precision pinning below exists to fix exactly the
            # rounding an int8 matmul cannot exhibit.
            from tpuflow.ops.int8_matmul import int8_matmul

            head = self.get_variable("quant", "wte_q")
            return int8_matmul(
                x, head.q, head.scale, w_contract_last=True,
                out_dtype=jnp.float32,
            )
        # Weight-tied LM head; logits come straight out of the MXU's f32
        # accumulator (preferred_element_type) — never rounded through
        # bf16. The old einsum→bf16→f32 path collapsed near-tie logits
        # onto equal bf16 values, and argmax over those flipped between
        # the chunked verify forward and single-token decode (one part of
        # the r4 on-chip speculative numerics_ok=false; decode_dtype
        # handles the layer-stack part). f32 logits also feed a stable
        # softmax/CE in training.
        return jnp.einsum(
            "btc,vc->btv",
            x,
            wte.astype(dt),
            preferred_element_type=jnp.float32,
            # Decode non-prefill: HIGHEST precision so the logits'
            # rounding is width-independent on the MXU too (the f32
            # accumulator alone does not fix the bf16 multiply passes
            # DEFAULT precision lowers f32 operands to).
            precision=cfg.matmul_precision(decode and not prefill),
        )
