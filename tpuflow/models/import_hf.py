"""Import HuggingFace GPT-2 checkpoints into tpuflow's Flax parameter tree.

A user of the reference stack brings torch weights; this is the bridge: any
``transformers`` GPT-2 model (or its raw ``state_dict``) converts into the
exact pytree ``tpuflow.models.gpt2.GPT2`` trains, checkpoints, and decodes
with — so pretrained weights drop into the FSDP trainer and the KV-cache
generator unchanged. It is also the framework's external-correctness proof:
``tests/test_hf_import.py`` asserts our logits match the canonical torch
implementation on identical weights.

Mapping notes (HF ``GPT2LMHeadModel`` → ours):

- HF's ``Conv1D`` stores kernels as (in, out) — the same layout as flax
  ``nn.Dense``; no transposes anywhere.
- ``ln_*.weight/bias`` → LayerNorm ``scale``/``bias`` (our ``ln_eps``
  default already matches GPT-2's 1e-5).
- The LM head is weight-tied to ``wte`` in both.
- With ``scan_layers=True`` the per-layer trees stack along a leading
  layer axis (axis 0), matching ``nn.scan``'s parameter layout.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np


def _np(t) -> np.ndarray:
    """torch tensor / array-like → float32 numpy."""
    if hasattr(t, "detach"):
        t = t.detach().cpu()
        if hasattr(t, "float"):
            t = t.float()  # torch can't .numpy() a bfloat16 tensor
        t = t.numpy()
    return np.asarray(t, np.float32)


# ONE mapping drives both directions: (hf module, our module,
# ((hf param, our param), ...)). HF LayerNorms use weight/bias, Conv1Ds
# weight/bias; ours use scale/bias and kernel/bias respectively.
_BLOCK_MAP = (
    ("ln_1", "ln_1", (("weight", "scale"), ("bias", "bias"))),
    ("attn.c_attn", "c_attn", (("weight", "kernel"), ("bias", "bias"))),
    ("attn.c_proj", "c_proj", (("weight", "kernel"), ("bias", "bias"))),
    ("ln_2", "ln_2", (("weight", "scale"), ("bias", "bias"))),
    ("mlp.c_fc", "mlp_fc", (("weight", "kernel"), ("bias", "bias"))),
    ("mlp.c_proj", "mlp_proj", (("weight", "kernel"), ("bias", "bias"))),
)


def _block_params(sd: Mapping[str, Any], i: int) -> dict:
    p = f"transformer.h.{i}."
    return {
        ours: {
            our_param: _np(sd[f"{p}{hf_mod}.{hf_param}"])
            for hf_param, our_param in pairs
        }
        for hf_mod, ours, pairs in _BLOCK_MAP
    }


def hf_gpt2_to_params(source, config) -> dict:
    """HF GPT-2 (model instance or ``state_dict``) → tpuflow params pytree.

    ``config`` is the matching ``tpuflow.models.gpt2.GPT2Config`` (use
    :func:`config_from_hf` to derive it). MoE configs cannot be imported
    (no HF equivalent).
    """
    if config.n_experts:
        raise ValueError("HF GPT-2 has no MoE variant to import from")
    sd = source.state_dict() if hasattr(source, "state_dict") else dict(source)
    wte = _np(sd["transformer.wte.weight"])
    if "lm_head.weight" in sd:
        # Our LM head is weight-tied to wte; an untied fine-tune would
        # import into silently wrong logits.
        if not np.array_equal(_np(sd["lm_head.weight"]), wte):
            raise ValueError(
                "checkpoint has an untied lm_head (lm_head.weight != "
                "wte.weight); the tpuflow GPT-2 ties the LM head to the "
                "token embedding and cannot represent it"
            )
    params: dict = {
        "wte": wte,
        "wpe": _np(sd["transformer.wpe.weight"]),
        "ln_f": {
            "scale": _np(sd["transformer.ln_f.weight"]),
            "bias": _np(sd["transformer.ln_f.bias"]),
        },
    }
    for field, want, got in (
        ("vocab_size", config.vocab_size, params["wte"].shape[0]),
        ("n_ctx", config.n_ctx, params["wpe"].shape[0]),
        ("n_embd", config.n_embd, params["wte"].shape[1]),
    ):
        if want != got:
            raise ValueError(
                f"config.{field}={want} does not match the checkpoint ({got})"
            )
    n_ckpt_layers = 0
    while f"transformer.h.{n_ckpt_layers}.ln_1.weight" in sd:
        n_ckpt_layers += 1
    if config.n_layer != n_ckpt_layers:
        raise ValueError(
            f"config.n_layer={config.n_layer} does not match the checkpoint "
            f"({n_ckpt_layers} layers)"
        )
    blocks = [_block_params(sd, i) for i in range(config.n_layer)]
    if config.scan_layers:
        import jax

        params["h"] = {
            "block": jax.tree_util.tree_map(
                lambda *xs: np.stack(xs, axis=0), *blocks
            )
        }
    else:
        for i, b in enumerate(blocks):
            params[f"h{i}"] = b
    return params


def params_to_hf_state_dict(params, config) -> dict:
    """tpuflow params pytree → HF GPT-2 ``state_dict`` (the export
    direction: fine-tune here, publish a checkpoint any transformers user
    can load). Inverse of :func:`hf_gpt2_to_params`; numpy float32 values
    (convert with ``torch.from_numpy`` / ``load_state_dict`` downstream).
    Scan-stacked layouts are unstacked back into per-layer entries; the
    tied ``lm_head.weight`` is emitted explicitly (HF models accept and
    re-tie it)."""
    if config.n_experts:
        raise ValueError("HF GPT-2 has no MoE variant to export to")
    import jax

    def arr(x):
        return np.asarray(x, np.float32)

    sd = {
        "transformer.wte.weight": arr(params["wte"]),
        "transformer.wpe.weight": arr(params["wpe"]),
        "transformer.ln_f.weight": arr(params["ln_f"]["scale"]),
        "transformer.ln_f.bias": arr(params["ln_f"]["bias"]),
    }
    sd["lm_head.weight"] = sd["transformer.wte.weight"]

    def block(i):
        if config.scan_layers:
            return {
                k: jax.tree_util.tree_map(lambda x: x[i], v)
                for k, v in params["h"]["block"].items()
            }
        return params[f"h{i}"]

    for i in range(config.n_layer):
        b = block(i)
        for hf_mod, ours, pairs in _BLOCK_MAP:
            for hf_param, our_param in pairs:
                sd[f"transformer.h.{i}.{hf_mod}.{hf_param}"] = arr(
                    b[ours][our_param]
                )
    return sd


def config_from_hf(hf_config, **overrides):
    """``transformers.GPT2Config`` → ``GPT2Config`` (dropout 0 for eval).

    Rejects GPT-2 variants whose forward pass our Block does not reproduce
    (non-tanh-GELU activations, per-layer attention scaling) rather than
    importing them into silently wrong logits.
    """
    from tpuflow.models.gpt2 import GPT2Config

    act = getattr(hf_config, "activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(
            f"unsupported activation_function={act!r}: the tpuflow GPT-2 "
            "block uses tanh-approximate GELU (gelu_new)"
        )
    for flag in ("scale_attn_by_inverse_layer_idx", "reorder_and_upcast_attn"):
        if getattr(hf_config, flag, False):
            raise ValueError(
                f"unsupported GPT-2 variant: {flag}=True changes the "
                "attention math and cannot be imported"
            )
    if not getattr(hf_config, "scale_attn_weights", True):
        raise ValueError(
            "unsupported GPT-2 variant: scale_attn_weights=False"
        )
    n_inner = getattr(hf_config, "n_inner", None)
    if n_inner not in (None, 4 * hf_config.n_embd):
        raise ValueError(
            f"unsupported GPT-2 variant: n_inner={n_inner} (the tpuflow "
            f"block uses the standard 4*n_embd={4 * hf_config.n_embd} MLP)"
        )
    kw = dict(
        vocab_size=hf_config.vocab_size,
        n_ctx=hf_config.n_positions,
        n_embd=hf_config.n_embd,
        n_layer=hf_config.n_layer,
        n_head=hf_config.n_head,
        dropout=0.0,
        ln_eps=float(hf_config.layer_norm_epsilon),
    )
    kw.update(overrides)
    return GPT2Config(**kw)
