"""Loss / metric functions (parity: nn.CrossEntropyLoss at reference
my_ray_module.py:141 and the accuracy computation at my_ray_module.py:170-175)."""

from __future__ import annotations

import jax.numpy as jnp
import optax


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax cross-entropy with integer labels (↔ nn.CrossEntropyLoss
    default reduction='mean')."""
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Fraction of argmax predictions matching labels (reference
    my_ray_module.py:170: ``(pred.argmax(1) == y)``)."""
    return (jnp.argmax(logits, axis=-1) == labels).mean()
