"""Loss / metric functions (parity: nn.CrossEntropyLoss at reference
my_ray_module.py:141 and the accuracy computation at my_ray_module.py:170-175)."""

from __future__ import annotations

import jax.numpy as jnp
import optax


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax cross-entropy with integer labels (↔ nn.CrossEntropyLoss
    default reduction='mean')."""
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Fraction of argmax predictions matching labels (reference
    my_ray_module.py:170: ``(pred.argmax(1) == y)``)."""
    return (jnp.argmax(logits, axis=-1) == labels).mean()


def sum_sown_losses(updates: dict) -> jnp.ndarray:
    """Scalar sum of every leaf sown into the 'losses' collection (e.g. the
    MoE load-balance aux) — the single convention shared by the train step
    and the pipeline schedule. A scanned layer stack sows (n_layer,)-stacked
    leaves; summing keeps the result scalar either way."""
    import jax

    total = jnp.float32(0.0)
    for leaf in jax.tree_util.tree_leaves(updates.get("losses", {})):
        total = total + jnp.sum(leaf)
    return total
