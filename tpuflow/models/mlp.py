"""Parity MLP classifier.

Reproduces the reference `NeuralNetwork` (my_ray_module.py:94-112):
784 → 512 → 512 → 10 with ReLU + Dropout(0.25) between layers — **including
the quirk of a ReLU after the final Linear** (my_ray_module.py:106), which
clamps logits ≥ 0 and is visible in the eval flow's logit bar charts. The
quirk is on by default for parity; pass ``final_relu=False`` for the corrected
behavior (documented deviation, SURVEY.md §7 hard-part 4).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class NeuralNetwork(nn.Module):
    """Flatten → Dense(512) → ReLU → Dropout → Dense(512) → ReLU → Dropout
    → Dense(10) [→ ReLU if final_relu]."""

    hidden_dim: int = 512
    num_classes: int = 10
    dropout_rate: float = 0.25
    final_relu: bool = True

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        x = x.reshape((x.shape[0], -1))  # nn.Flatten
        x = nn.Dense(self.hidden_dim, name="dense1")(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.hidden_dim, name="dense2")(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, name="dense3")(x)
        if self.final_relu:
            x = nn.relu(x)
        return x
