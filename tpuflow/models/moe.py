"""Mixture-of-Experts MLP with expert parallelism over the 'expert' mesh axis.

Beyond-parity capability (the reference has no MoE anywhere — its model is an
image MLP, reference my_ray_module.py:94-112): a Switch-style top-1-routed
expert MLP in the GSPMD idiom. Routing is expressed as dense one-hot
einsums — fully static shapes, no gather/scatter — so XLA lays the
token↔expert exchange down as all-to-alls over ICI when the expert weights
are sharded on the 'expert' axis (tpuflow.parallel rules) while tokens stay
sharded on 'data'. This is the classic GShard/Switch formulation, which is
what maps onto the TPU's MXU + ICI rather than a CUDA-style permute kernel.

Pieces:
- router: f32 softmax gate, top-1 expert per token (gradients flow through
  the combine weights);
- capacity: each expert processes at most ``ceil(T/E · capacity_factor)``
  tokens per row group; overflow tokens pass through the residual stream
  (their MoE output is 0);
- load-balance auxiliary loss (Switch: ``E · Σ_e f_e · P_e``), sown into the
  'losses' collection — the train step adds every sown auxiliary to the task
  loss when the model provides one.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEMLP(nn.Module):
    """Drop-in MLP replacement: (B, T, C) → (B, T, C) through E experts."""

    d_model: int
    d_ff: int
    n_experts: int
    capacity_factor: float = 1.25
    aux_weight: float = 1e-2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        B, T, C = x.shape
        E = self.n_experts
        cap = max(1, int(-(-T * self.capacity_factor // E)))

        # Router in f32: gate numerics must not degrade in bf16.
        gate_logits = nn.Dense(E, dtype=jnp.float32, name="gate")(
            x.astype(jnp.float32)
        )
        probs = jax.nn.softmax(gate_logits)  # (B,T,E)
        onehot = jax.nn.one_hot(jnp.argmax(probs, -1), E, dtype=jnp.float32)

        # Switch load-balance loss: E · Σ_e (token fraction · mean gate prob).
        frac = onehot.mean(axis=(0, 1))
        mean_prob = probs.mean(axis=(0, 1))
        self.sow(
            "losses", "moe_aux", self.aux_weight * E * jnp.sum(frac * mean_prob)
        )

        # Position of each token inside its expert's capacity buffer.
        pos = (jnp.cumsum(onehot, axis=1) - 1.0) * onehot  # (B,T,E)
        keep = onehot * (pos < cap)
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), cap) * keep[..., None]
        dispatch = pos_oh.astype(self.dtype)  # (B,T,E,cap) 0/1
        combine = (pos_oh * probs[..., None]).astype(self.dtype)

        w1 = self.param(
            "w1",
            nn.initializers.normal(0.02),
            (E, C, self.d_ff),
            jnp.float32,
        ).astype(self.dtype)
        b1 = self.param(
            "b1", nn.initializers.zeros, (E, self.d_ff), jnp.float32
        ).astype(self.dtype)
        w2 = self.param(
            "w2",
            nn.initializers.normal(0.02),
            (E, self.d_ff, C),
            jnp.float32,
        ).astype(self.dtype)
        b2 = self.param(
            "b2", nn.initializers.zeros, (E, C), jnp.float32
        ).astype(self.dtype)

        # Token→expert exchange (all-to-all under GSPMD), expert FFNs on the
        # MXU, exchange back. All shapes static.
        xin = jnp.einsum("btec,btm->ebcm", dispatch, x)  # (E,B,cap,C)
        h = nn.gelu(
            jnp.einsum("ebcm,emf->ebcf", xin, w1) + b1[:, None, None, :]
        )
        out = jnp.einsum("ebcf,efm->ebcm", h, w2) + b2[:, None, None, :]
        return jnp.einsum("btec,ebcm->btm", combine, out)
