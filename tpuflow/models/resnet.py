"""ResNet-18/50 in Flax — the acceptance-config image models.

The reference repo's only model is the FashionMNIST MLP (my_ray_module.py:
94-112), but the driver acceptance configs name ResNet-18/CIFAR-10 and
ResNet-50/ImageNet behind the same trainer API (BASELINE.md configs 1-2), so
the model zoo provides them as standard Flax modules. TPU notes: convolutions
land on the MXU; NHWC layout (XLA:TPU's native conv layout); BatchNorm
statistics are per-replica like torch DDP's default (no cross-replica sync).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

Conv = partial(nn.Conv, use_bias=False, kernel_init=nn.initializers.he_normal())


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (ResNet-18/34)."""

    filters: int
    strides: int = 1
    norm: Callable = nn.BatchNorm

    @nn.compact
    def __call__(self, x, *, use_running_average: bool):
        norm = partial(self.norm, use_running_average=use_running_average)
        residual = x
        y = Conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = Conv(self.filters, (3, 3))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = Conv(
                self.filters, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck block (ResNet-50/101/152)."""

    filters: int
    strides: int = 1
    norm: Callable = nn.BatchNorm

    @nn.compact
    def __call__(self, x, *, use_running_average: bool):
        norm = partial(self.norm, use_running_average=use_running_average)
        residual = x
        y = Conv(self.filters, (1, 1))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = Conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = norm()(y)
        y = nn.relu(y)
        y = Conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = Conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """NHWC ResNet. ``small_inputs=True`` uses the CIFAR stem (3x3 conv, no
    max-pool) instead of the ImageNet stem (7x7/2 + pool)."""

    stage_sizes: Sequence[int]
    block: type = BasicBlock
    num_classes: int = 10
    width: int = 64
    small_inputs: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        use_ra = not train
        norm = partial(nn.BatchNorm, use_running_average=use_ra)
        if x.ndim == 3:  # (B, H, W) grayscale → add channel dim
            x = x[..., None]
        if self.small_inputs:
            x = Conv(self.width, (3, 3))(x)
        else:
            x = Conv(self.width, (7, 7), strides=(2, 2))(x)
        x = norm()(x)
        x = nn.relu(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, num_blocks in enumerate(self.stage_sizes):
            for j in range(num_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block(self.width * 2**i, strides)(
                    x, use_running_average=use_ra
                )
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes)(x)


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block=BottleneckBlock)
