"""Vision Transformer (ViT) classifier — the attention-stack image family.

Beyond the reference's model layer (a fixed MLP, my_ray_module.py:94-112)
and the convolutional zoo: patches embed with one strided conv (an MXU
matmul), the encoder reuses the same pluggable attention dispatch as the
LM family (``tpuflow.ops.attention`` — xla | Pallas flash | ring |
ulysses), and classification reads a learned CLS token. LayerNorm-only
(no BatchNorm state), so checkpoints are pure params and the model
composes with every trainer/eval path unchanged.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from tpuflow.ops import attention


class EncoderBlock(nn.Module):
    """Pre-LN transformer encoder block (bidirectional attention)."""

    n_embd: int
    n_head: int
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool):
        B, T, C = x.shape
        head_dim = self.n_embd // self.n_head
        h = nn.LayerNorm(dtype=self.dtype, name="ln_1")(x)
        qkv = nn.Dense(3 * self.n_embd, dtype=self.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, self.n_head, head_dim)
        k = k.reshape(B, T, self.n_head, head_dim)
        v = v.reshape(B, T, self.n_head, head_dim)
        a = attention(q, k, v, causal=False, impl=self.attn_impl)
        a = a.reshape(B, T, self.n_embd)
        a = nn.Dense(self.n_embd, dtype=self.dtype, name="proj")(a)
        a = nn.Dropout(self.dropout, deterministic=not train)(a)
        x = x + a
        h = nn.LayerNorm(dtype=self.dtype, name="ln_2")(x)
        h = nn.Dense(
            self.mlp_ratio * self.n_embd, dtype=self.dtype, name="mlp_fc"
        )(h)
        h = nn.gelu(h)
        h = nn.Dense(self.n_embd, dtype=self.dtype, name="mlp_proj")(h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return x + h


class ViT(nn.Module):
    """Images (B, H, W[, C]) → logits (B, num_classes).

    ``patch_size`` must divide H and W. Defaults are a small config that
    trains on the bundled 28/32-pixel datasets; pass ``n_embd``/``n_layer``
    /``n_head``/``patch_size`` for standard sizes (ViT-S/16 = 384/12/6
    at patch 16).
    """

    num_classes: int = 10
    patch_size: int = 4
    n_embd: int = 192
    n_layer: int = 6
    n_head: int = 3
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "xla"

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        if x.ndim == 3:  # (B, H, W) grayscale → add channel dim
            x = x[..., None]
        B, H, W, C = x.shape
        p = self.patch_size
        if H % p or W % p:
            raise ValueError(
                f"patch_size {p} must divide the image size ({H}x{W})"
            )
        # Patch embedding: one strided conv = a (p*p*C -> n_embd) matmul
        # per patch, MXU-shaped.
        x = nn.Conv(
            self.n_embd, (p, p), strides=(p, p), dtype=self.dtype,
            name="patch_embed",
        )(x.astype(self.dtype))
        x = x.reshape(B, -1, self.n_embd)
        n_tok = x.shape[1]
        cls = self.param(
            "cls", nn.initializers.zeros, (1, 1, self.n_embd), jnp.float32
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(self.dtype), (B, 1, self.n_embd)), x],
            axis=1,
        )
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, n_tok + 1, self.n_embd),
            jnp.float32,
        )
        x = x + pos.astype(self.dtype)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        for i in range(self.n_layer):
            x = EncoderBlock(
                self.n_embd,
                self.n_head,
                mlp_ratio=self.mlp_ratio,
                dropout=self.dropout,
                dtype=self.dtype,
                attn_impl=self.attn_impl,
                name=f"block{i}",
            )(x, train)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        # Classify from the CLS token; float32 logits for a stable softmax.
        return nn.Dense(self.num_classes, name="head")(
            x[:, 0].astype(jnp.float32)
        )
