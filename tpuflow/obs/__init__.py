"""tpuflow.obs — dependency-free unified telemetry.

The runtime's evidence trail (ISSUE 1; ROADMAP "as fast as the hardware
allows" is unverifiable without it): spans, counters, gauges, and
histograms recorded as structured JSONL under the run directory, merged
across gang workers into one run timeline, summarized into headline
metrics, and rendered as the flow's timeline card.

Usage (emitters)::

    from tpuflow import obs

    with obs.span("ckpt.save", step=3) as sp:
        ...
        sp.set(bytes=nbytes, gbps=nbytes / dur / 1e9)
    obs.counter("train.tokens", n_tokens)
    obs.histogram("train.step_s", dt)

Every name must be registered in ``tpuflow.obs.catalog`` — enforced by
``tools/obs_lint.py``. Disabled (the default outside a flow run) every
call is a single boolean check; enabled, events buffer in memory and
flush on a background thread (see ``recorder``).
"""

from tpuflow.obs.alerts import (
    RULES as ALERT_RULES,
    AlertEngine,
    burn_gate,
    format_transition,
)
from tpuflow.obs.alerts import engine as alert_engine
from tpuflow.obs.catalog import CATALOG, is_registered, kind_of
from tpuflow.obs.device import (
    ProgramLedger,
    device_summary,
    hbm_snapshot,
    maybe_emit_hbm,
)
from tpuflow.obs.export import (
    MetricsServer,
    maybe_start_from_env as maybe_start_export,
)
from tpuflow.obs.fleet import (
    FleetObservatory,
    MergeableHistogram,
    discover_replicas,
    hist_pctl,
    replica_identity,
)
from tpuflow.obs.flight import dump_flight, flight_path
from tpuflow.obs.goodput import (
    BUCKETS as GOODPUT_BUCKETS,
    ProcessLedger,
    compute_goodput,
)
from tpuflow.obs.goodput import live as goodput_live
from tpuflow.obs.health import (
    Anomaly,
    HealthConfig,
    HealthMonitor,
    ProfileWindow,
    TrainingDiverged,
    health_summary,
)
from tpuflow.obs.profcap import AnomalyCapturer, CaptureConfig
from tpuflow.obs.serve_ledger import (
    GROUPS as SERVE_GROUPS,
    SERVE_BUCKETS,
    AccessLog,
    ServeLedger,
    load_access_log,
    summarize_access,
)
from tpuflow.obs.registry import (
    append_record,
    backfill_bench,
    compare_rows,
    make_record,
    maybe_append_live,
    read_registry,
    registry_path,
    trend_rows,
    verdict_rows,
)
from tpuflow.obs.recorder import (
    Recorder,
    configure,
    counter,
    enabled,
    event,
    flush,
    gauge,
    histogram,
    recorder,
    span,
    timed_iter,
)
from tpuflow.obs.timeline import (
    load_run_events,
    merge_run_events,
    obs_dir,
    read_events,
    summarize,
)

__all__ = [
    "ALERT_RULES",
    "AccessLog",
    "AlertEngine",
    "Anomaly",
    "AnomalyCapturer",
    "CATALOG",
    "CaptureConfig",
    "FleetObservatory",
    "GOODPUT_BUCKETS",
    "HealthConfig",
    "HealthMonitor",
    "MergeableHistogram",
    "MetricsServer",
    "ProcessLedger",
    "ProfileWindow",
    "ProgramLedger",
    "Recorder",
    "SERVE_BUCKETS",
    "SERVE_GROUPS",
    "ServeLedger",
    "TrainingDiverged",
    "alert_engine",
    "append_record",
    "backfill_bench",
    "burn_gate",
    "compare_rows",
    "compute_goodput",
    "configure",
    "counter",
    "device_summary",
    "discover_replicas",
    "dump_flight",
    "enabled",
    "event",
    "flight_path",
    "flush",
    "format_transition",
    "gauge",
    "goodput_live",
    "hbm_snapshot",
    "health_summary",
    "hist_pctl",
    "histogram",
    "is_registered",
    "kind_of",
    "load_access_log",
    "load_run_events",
    "make_record",
    "maybe_append_live",
    "maybe_emit_hbm",
    "maybe_start_export",
    "merge_run_events",
    "obs_dir",
    "read_events",
    "read_registry",
    "recorder",
    "registry_path",
    "replica_identity",
    "span",
    "summarize",
    "summarize_access",
    "timed_iter",
    "trend_rows",
    "verdict_rows",
]
