"""Operator CLI: ``python -m tpuflow.obs summarize <run_dir> [--json]``.

Reads a run directory's merged telemetry (the committed ``events.jsonl``,
or the per-process fragments of a still-running/crashed run) and prints
the headline metrics plus the goodput ledger — no client API, no jax
import, safe to point at a live run from a login shell. ``--json`` dumps
the full ``obs.summarize`` structure for CI and scripts.
"""

from __future__ import annotations

import json
import sys

from tpuflow.obs.goodput import BUCKETS
from tpuflow.obs.timeline import load_run_events, summarize

_USAGE = "usage: python -m tpuflow.obs summarize <run_dir> [--json]"


def main(argv: list[str]) -> int:
    args = [a for a in argv if not a.startswith("-")]
    flags = {a for a in argv if a.startswith("-")}
    if flags - {"--json"} or len(args) != 2 or args[0] != "summarize":
        print(_USAGE, file=sys.stderr)
        return 2
    run_dir = args[1]
    events = load_run_events(run_dir)
    if not events:
        print(f"no telemetry found under {run_dir}", file=sys.stderr)
        return 1
    s = summarize(events)
    if "--json" in flags:
        json.dump(s, sys.stdout, indent=2, sort_keys=True, default=str)
        print()
        return 0
    print(f"events: {len(events)}")
    headline = s.get("headline", {})
    if headline:
        print("headline:")
        for k, v in sorted(headline.items()):
            print(f"  {k}: {v:.6g}" if isinstance(v, float) else f"  {k}: {v}")
    gp = s.get("goodput") or {}
    wall = gp.get("wall_s", 0.0)
    if wall:
        print(
            f"goodput: {100.0 * gp.get('fraction', 0.0):.1f}% of "
            f"{wall:.1f}s wall"
        )
        for b in BUCKETS:
            v = gp.get("buckets", {}).get(b, 0.0)
            if v:
                print(f"  {b}: {v:.3f}s ({100.0 * v / wall:.1f}%)")
        for a in gp.get("attempts", []):
            procs = ",".join(f"p{p}" for p in a.get("procs", []))
            print(
                f"  attempt {a['attempt']}: +{a['start_s']:.1f}s "
                f"for {a['dur_s']:.1f}s [{procs}]"
            )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
