"""Operator CLI: ``python -m tpuflow.obs <command> [target] [--json]``.

Eight commands, all jax-free and safe against a LIVE run from a login
shell:

- ``summarize <run_dir>`` — the run's merged telemetry (the committed
  ``events.jsonl``, or the per-process fragments of a still-running/
  crashed run): headline metrics plus the goodput ledger.
- ``serve-summary <run_dir>`` — the serving observatory (ISSUE 13):
  TTFT/ITL percentiles split by traffic group, finish reasons, and SLO
  violations reproduced from the per-request ACCESS LOG alone (the same
  ``pctl`` math the live /metrics exporter uses), plus the engine-time
  ledger fractions when the event stream carries them.
- ``device-summary <run_dir>`` — the device observatory (ISSUE 15):
  the per-program compile/memory ledger reproduced from the
  ``programs.json`` run artifact alone, the last HBM gauges, the static
  budget verdict, and any anomaly-triggered ``prof.capture`` artifacts
  — all file reads, no jax import.
- ``fleet-summary [target]`` — the fleet observatory (ISSUE 14): poll
  every replica's /status once and print the fleet headline (summed
  load, occupancy-weighted utilization, fleet-exact TTFT/ITL
  percentiles from merged histogram buckets, SLO rates by traffic
  group) plus one line per replica with its health score. ``target``
  is a registration directory or a comma URL list; omitted, the
  ``TPUFLOW_FLEET_REPLICAS`` / ``TPUFLOW_FLEET_REGISTRATION_DIR``
  knobs resolve it.
- ``trend [--metric=M ...] [--window=N]`` — the regression ledger
  (ISSUE 16): the registry's newest record judged against its trailing
  median+MAD window, one verdict row per metric.
- ``compare <runA> <runB>`` — per-metric deltas between two registry
  records (run-id exact or prefix match); a side missing a metric
  reads "absent", never an error.
- ``registry-backfill [<dir>]`` — one-shot idempotent import of the
  driver's ``BENCH_r*.json`` captures into the registry.
- ``trace <request_id> [<dir> ...]`` — end-to-end tracing (ISSUE 18):
  assemble one request's cross-process spans (FrontDoor ingress →
  router forward attempts → replica gateway → engine lifecycle) from
  the given trace directories (a trace dir itself, a run dir holding
  ``trace/`` or ``obs/trace/``, or — no dirs given —
  ``TPUFLOW_TRACE_DIR``) into one merged timeline with the
  critical-path TTFT breakdown; rerouted requests attribute across
  both replicas.

The registry commands resolve the registry file from
``TPUFLOW_REGISTRY_PATH`` (override per-call with
``--registry=PATH``). ``--json`` dumps the full structure for CI and
scripts.
"""

from __future__ import annotations

import json
import os
import sys

from tpuflow.obs.goodput import BUCKETS
from tpuflow.obs.serve_ledger import (
    SERVE_BUCKETS,
    load_access_log,
    summarize_access,
)
from tpuflow.obs.timeline import load_run_events, summarize

_USAGE = (
    "usage: python -m tpuflow.obs "
    "{summarize|serve-summary|device-summary} <run_dir> [--json]\n"
    "       python -m tpuflow.obs fleet-summary "
    "[<registration_dir>|<url,url,...>] [--json]\n"
    "       python -m tpuflow.obs trend [--metric=M ...] [--window=N] "
    "[--registry=PATH] [--json]\n"
    "       python -m tpuflow.obs compare <runA> <runB> "
    "[--registry=PATH] [--json]\n"
    "       python -m tpuflow.obs registry-backfill [<bench_dir>] "
    "[--registry=PATH]\n"
    "       python -m tpuflow.obs trace <request_id> [<dir> ...] "
    "[--json]"
)


def _summarize(run_dir: str, as_json: bool) -> int:
    events = load_run_events(run_dir)
    if not events:
        print(f"no telemetry found under {run_dir}", file=sys.stderr)
        return 1
    s = summarize(events)
    if as_json:
        json.dump(s, sys.stdout, indent=2, sort_keys=True, default=str)
        print()
        return 0
    print(f"events: {len(events)}")
    headline = s.get("headline", {})
    if headline:
        print("headline:")
        for k, v in sorted(headline.items()):
            print(f"  {k}: {v:.6g}" if isinstance(v, float) else f"  {k}: {v}")
    gp = s.get("goodput") or {}
    wall = gp.get("wall_s", 0.0)
    if wall:
        print(
            f"goodput: {100.0 * gp.get('fraction', 0.0):.1f}% of "
            f"{wall:.1f}s wall"
        )
        for b in BUCKETS:
            v = gp.get("buckets", {}).get(b, 0.0)
            if v:
                print(f"  {b}: {v:.3f}s ({100.0 * v / wall:.1f}%)")
        for a in gp.get("attempts", []):
            procs = ",".join(f"p{p}" for p in a.get("procs", []))
            print(
                f"  attempt {a['attempt']}: +{a['start_s']:.1f}s "
                f"for {a['dur_s']:.1f}s [{procs}]"
            )
    return 0


def _fmt_lat(p: dict | None) -> str:
    if not p:
        return "-"
    return (
        f"p50={p['p50']:.4f}s p95={p['p95']:.4f}s p99={p['p99']:.4f}s "
        f"(n={p['count']})"
    )


def _serve_summary(run_dir: str, as_json: bool) -> int:
    records = load_access_log(run_dir)
    if not records:
        print(
            f"no serve access log found under {run_dir} "
            "(obs/access.p*.jsonl — armed by TPUFLOW_SERVE_ACCESS_LOG)",
            file=sys.stderr,
        )
        return 1
    s = summarize_access(records)
    # The engine-time ledger fractions ride the event stream as gauges;
    # best-effort (an access log with no events is still a summary).
    ledger: dict[str, float] = {}
    for ev in load_run_events(run_dir):
        if ev.get("kind") != "gauge":
            continue
        name = ev.get("name", "")
        if name in (
            "serve.idle_fraction",
            "serve.decode_fraction",
            "serve.prefill_fraction",
            "serve.decode_utilization",
            "serve.masked_row_waste",
        ):
            try:
                ledger[name] = float(ev.get("value", 0.0))
            except (TypeError, ValueError):
                pass
    if ledger:
        s["ledger"] = ledger
    if as_json:
        json.dump(s, sys.stdout, indent=2, sort_keys=True, default=str)
        print()
        return 0
    print(
        f"requests: {s['requests']}  tokens: {s['tokens']}  "
        f"slo_violations: {s['slo_violations']}"
    )
    print(
        "finish: "
        + ", ".join(f"{k}={v}" for k, v in s["finish_reasons"].items())
    )
    print(f"ttft: {_fmt_lat(s['ttft'])}")
    print(f"itl:  {_fmt_lat(s['itl'])}")
    for g, rec in s["by_group"].items():
        print(f"  {g}: n={rec['requests']}")
        print(f"    ttft: {_fmt_lat(rec['ttft'])}")
        print(f"    itl:  {_fmt_lat(rec['itl'])}")
    if ledger:
        print("ledger (last gauges):")
        for b in SERVE_BUCKETS:
            v = ledger.get(f"serve.{b}_fraction")
            if v is not None:
                print(f"  {b}: {100.0 * v:.1f}%")
        for extra in ("serve.decode_utilization", "serve.masked_row_waste"):
            if extra in ledger:
                print(f"  {extra.split('.', 1)[1]}: {ledger[extra]:.4f}")
    return 0


def _device_summary(run_dir: str, as_json: bool) -> int:
    from tpuflow.obs.device import device_summary, summarize_entry

    s = device_summary(run_dir)
    if not s:
        print(
            f"no device telemetry found under {run_dir} "
            "(obs/programs.json, device.* gauges, prof.capture events "
            "— armed by the device observatory, see the README "
            "runbook)",
            file=sys.stderr,
        )
        return 1
    if as_json:
        json.dump(s, sys.stdout, indent=2, sort_keys=True, default=str)
        print()
        return 0
    programs = s.get("programs") or []
    if programs:
        print(f"programs: {len(programs)} ({s.get('programs_path')})")
        print(
            "  name             compile_s       flops    arg MiB"
            "    out MiB   temp MiB"
        )
        for e in programs:
            print(summarize_entry(e))
    budget = s.get("budget") or {}
    if budget:
        line = (
            f"budget: resident {budget.get('resident_bytes', 0) / 2**30:.3f}"
            f" GiB over {budget.get('programs', len(programs))} programs"
        )
        if "resident_frac" in budget:
            line += (
                f" = {100.0 * budget['resident_frac']:.1f}% of "
                f"{budget.get('bytes_limit', 0) / 2**30:.2f} GiB limit"
                + (" [OVER]" if budget.get("over") else "")
            )
        print(line)
    hbm = s.get("hbm") or {}
    if hbm:
        def gib(*keys):
            for k in keys:
                v = hbm.get(k)
                if v is not None:
                    return f"{v / 2**30:.3f}"
            return "-"

        print(
            f"hbm: used {gib('hbm_used')} GiB "
            f"(max {gib('hbm_used_max')})"
            f"  peak {gib('hbm_peak_max', 'hbm_peak')}"
            f"  limit {gib('hbm_limit')} GiB"
        )
    for cap in s.get("captures") or []:
        print(
            f"capture[{cap.get('capture', '?')}]: {cap.get('reason')} "
            f"-> {cap.get('dir')}"
            + (
                f" (+{cap.get('memory_profile')})"
                if cap.get("memory_profile")
                else ""
            )
        )
    return 0


def _fleet_summary(target: str | None, as_json: bool) -> int:
    from tpuflow.obs import fleet

    obsy = fleet.FleetObservatory(target)
    if not obsy.discover():
        print(
            "no fleet replicas found — pass a registration dir or a "
            "comma URL list, or set TPUFLOW_FLEET_REPLICAS / "
            "TPUFLOW_FLEET_REGISTRATION_DIR",
            file=sys.stderr,
        )
        return 1
    snap = obsy.poll()
    if as_json:
        json.dump(snap, sys.stdout, indent=2, sort_keys=True, default=str)
        print()
        return 0
    print(fleet.format_fleet_line(snap["fleet"]))
    for row in snap["replicas"]:
        print(fleet.format_replica_line(row))
    fl = snap["fleet"]
    for which in ("ttft", "itl"):
        p = fl.get(which)
        if p:
            print(
                f"{which}: p50={p['p50']:.4g}s p95={p['p95']:.4g}s "
                f"p99={p['p99']:.4g}s (n={p['count']}, fleet-exact from "
                "merged histogram buckets)"
            )
    for g, rate in (fl.get("slo_rate_by_group") or {}).items():
        print(
            f"slo[{g}]: {100.0 * rate:.2f}% "
            f"({fl['slo_by_group'].get(g, 0)} violations / "
            f"{fl['requests_by_group'].get(g, 0)} requests)"
        )
    return 0


def _trace_cmd(
    request_id: str, targets: list[str], as_json: bool
) -> int:
    """Assemble one request's cross-process trace (ISSUE 18). Each
    target may be the trace dir itself or a parent holding ``trace/``
    or ``obs/trace/``; with no targets, ``TPUFLOW_TRACE_DIR`` resolves
    one. Spans from every dir merge into one timeline."""
    from tpuflow.obs import trace as tracemod
    from tpuflow.utils import knobs

    dirs = list(targets)
    if not dirs:
        d = knobs.raw("TPUFLOW_TRACE_DIR")
        if d:
            dirs.append(d)
    if not dirs:
        print(
            "no trace directory — pass one or more dirs (the trace "
            "dir, or a run dir holding trace/ or obs/trace/) or set "
            "TPUFLOW_TRACE_DIR",
            file=sys.stderr,
        )
        return 2
    spans: list[dict] = []
    seen: set[tuple] = set()
    scanned: list[str] = []
    for d in dirs:
        for cand in (
            d,
            os.path.join(d, "trace"),
            os.path.join(d, "obs", "trace"),
        ):
            if not os.path.isdir(cand):
                continue
            scanned.append(cand)
            for s in tracemod.spans_for_request(cand, request_id):
                key = (
                    s.get("trace"), s.get("span"), s.get("name"),
                    s.get("ts"), s.get("writer"),
                )
                if key in seen:
                    continue
                seen.add(key)
                spans.append(s)
    assembled = tracemod.assemble(spans)
    if assembled is None:
        print(
            f"no spans for request {request_id!r} under "
            f"{', '.join(scanned) or ', '.join(dirs)} (unsampled and "
            "never escalated, or the trace dir is wrong)",
            file=sys.stderr,
        )
        return 1
    if as_json:
        json.dump(
            assembled, sys.stdout, indent=2, sort_keys=True, default=str
        )
        print()
        return 0
    for line in tracemod.format_timeline(assembled):
        print(line)
    return 0


def _find_record(records: list[dict], token: str) -> dict | None:
    """The newest record whose run_id matches ``token`` exactly, else
    the newest run-id *prefix* match (so ``bench-17...`` abbreviates)."""
    exact = [r for r in records if r.get("run_id") == token]
    if exact:
        return exact[-1]
    pref = [
        r for r in records if str(r.get("run_id", "")).startswith(token)
    ]
    return pref[-1] if pref else None


def _registry_cli(argv: list[str]) -> int:
    """trend / compare / registry-backfill — the regression ledger
    (ISSUE 16). Jax-free: only the registry module and file reads."""
    from tpuflow.obs import registry as reg

    cmd = argv[0]
    args: list[str] = []
    metrics: list[str] = []
    override = None
    window = None
    as_json = False
    for a in argv[1:]:
        if a == "--json":
            as_json = True
        elif a.startswith("--metric="):
            metrics.append(a.split("=", 1)[1])
        elif a.startswith("--registry="):
            override = a.split("=", 1)[1]
        elif a.startswith("--window="):
            try:
                window = int(a.split("=", 1)[1])
            except ValueError:
                print(_USAGE, file=sys.stderr)
                return 2
        elif a.startswith("-"):
            print(_USAGE, file=sys.stderr)
            return 2
        else:
            args.append(a)
    path = override or reg.registry_path()

    if cmd == "registry-backfill":
        if len(args) > 1:
            print(_USAGE, file=sys.stderr)
            return 2
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
        bench_dir = args[0] if args else repo
        if not path:
            path = os.path.join(bench_dir, reg.DEFAULT_BASENAME)
        n = reg.backfill_bench(bench_dir, path)
        print(f"imported {n} bench record(s) from {bench_dir} -> {path}")
        return 0

    records = reg.read_registry(path) if path else []
    if not records:
        print(
            "empty registry "
            f"({path or 'TPUFLOW_REGISTRY_PATH unset'}) — arm "
            "TPUFLOW_REGISTRY_PATH (or pass --registry=PATH) and run "
            "`python -m tpuflow.obs registry-backfill` to import the "
            "BENCH history",
            file=sys.stderr,
        )
        return 1

    if cmd == "compare":
        if len(args) != 2:
            print(_USAGE, file=sys.stderr)
            return 2
        recs = []
        for token in args:
            rec = _find_record(records, token)
            if rec is None:
                print(
                    f"run {token!r} not found in {path} "
                    f"({len(records)} records)",
                    file=sys.stderr,
                )
                return 1
            recs.append(rec)
        rows = reg.compare_rows(recs[0], recs[1])
        if as_json:
            json.dump(rows, sys.stdout, indent=2, sort_keys=True)
            print()
            return 0
        print(
            f"compare {recs[0].get('run_id')} -> {recs[1].get('run_id')}"
            f" ({path})"
        )
        print(reg.format_rows(
            rows, ("metric", "a", "b", "delta", "delta_pct", "verdict")
        ))
        return 0

    # trend
    if args:
        print(_USAGE, file=sys.stderr)
        return 2
    rows = reg.trend_rows(records, metrics=metrics or None, window=window)
    if as_json:
        json.dump(rows, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0
    newest = records[-1]
    print(
        f"registry: {path} ({len(records)} records) — newest "
        f"{newest.get('run_id')} vs trailing window"
    )
    print(reg.format_rows(
        rows, ("metric", "n", "last", "median", "delta", "z", "verdict")
    ))
    regressed = [r["metric"] for r in rows if r.get("verdict") == "REGRESSED"]
    if regressed:
        print("REGRESSED: " + ", ".join(regressed))
    return 0


def main(argv: list[str]) -> int:
    if argv and argv[0] in ("trend", "compare", "registry-backfill"):
        return _registry_cli(argv)
    if argv and argv[0] == "trace":
        args = [a for a in argv[1:] if not a.startswith("-")]
        flags = {a for a in argv[1:] if a.startswith("-")}
        if flags - {"--json"} or not args:
            print(_USAGE, file=sys.stderr)
            return 2
        return _trace_cmd(args[0], args[1:], "--json" in flags)
    args = [a for a in argv if not a.startswith("-")]
    flags = {a for a in argv if a.startswith("-")}
    commands = (
        "summarize", "serve-summary", "device-summary", "fleet-summary"
    )
    if flags - {"--json"} or not args or args[0] not in commands:
        print(_USAGE, file=sys.stderr)
        return 2
    if args[0] == "fleet-summary":
        # The target is optional: the TPUFLOW_FLEET_* knobs resolve it.
        if len(args) > 2:
            print(_USAGE, file=sys.stderr)
            return 2
        return _fleet_summary(
            args[1] if len(args) == 2 else None, "--json" in flags
        )
    if len(args) != 2:
        print(_USAGE, file=sys.stderr)
        return 2
    if args[0] == "serve-summary":
        return _serve_summary(args[1], "--json" in flags)
    if args[0] == "device-summary":
        return _device_summary(args[1], "--json" in flags)
    return _summarize(args[1], "--json" in flags)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
